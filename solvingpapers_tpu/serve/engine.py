"""Continuous-batching serving engine: the long-lived mixed prefill/decode
step over a slot pool.

`infer.decode.generate` is one static batch to completion — a new request
waits for the whole previous batch. `ServeEngine` instead advances a pool
of S independent slots one iteration at a time (Orca-style iteration-level
scheduling): each `step()` admits waiting requests into free lanes
(chunked prefill, same end-aligned attend_len contract as `generate`),
then advances every active slot by a block of single-token steps, emitting
per-request token streams as they materialize. A slot freed by an
early-EOS sequence is re-acquired by the next queued request immediately
— the batch never drains.

Static shapes throughout (XLA requirement): the batch dimension of every
jitted program is the slot count, inactive slots run masked dummy steps
(their writes land in lane slot 0, overwritten by the next prefill;
masked-softmax zeros annihilate stale finite values exactly — see
`serve/kv_pool.py`). Per-slot positions are made possible by `vmap`ping a
batch-1 single-token apply over the slot axis: the models' cached
attention writes at ``positions[0, 0]`` (one scalar per call), and under
vmap that scalar is per-slot — so every decoder family (gpt, llama3,
gemma, deepseekv3) serves unmodified.

Compiled-program inventory (bounded by construction): ONE decode program
(every block runs the full `decode_block`; a slot that hits EOS or its
budget mid-block keeps stepping and the host discards its overshoot —
the wasted writes stay inside that slot's own lane, which the next
prefill overwrites), one prefill program per prompt bucket (prompts pad
right to a multiple of ``bucket``; the pad region is causally invisible
to real tokens and its cache slots are overwritten by the decode stream
before ever being attended).

Paged KV pool (`ServeConfig.paged`, `serve/kv_pool.py PagedKVPool`):
instead of one contiguous `max_len` lane per slot, the cache is a
physical pool of fixed-size KV pages with per-slot page tables; the
jitted programs gather the logical lane view from the page table (which
rides the existing packed control transfer), run the models unmodified,
and scatter back only written pages. HBM is booked per page, slot count
decouples from max_seq, the scheduler admits on a PAGE budget (free
pages must cover prompt + a decode reservation), and a stream that
outgrows the pool is preempted — pages freed, request requeued at the
head, KV recomputed on resume (token streams unchanged). The lane pool
stays the default and the bench baseline (`serve-bench --paged`).

Cross-request prefix reuse (`serve/prefix_cache.py`, opt-in via
`ServeConfig.prefix_cache` — see its docstring for the cost model):
admission first reuses the longest cached page-aligned prompt prefix —
the lane pool splices it into the freed lane (copy-on-acquire — one
fused dynamic_update_slice program per segment), the paged pool appends
the cached PHYSICAL page ids to the slot's page table (a refcount bump:
zero device copies, no program dispatched) — and prefills only the
uncovered suffix from position `matched`, then hands the prompt's
prefix back to the radix tree (snapshot copy vs page-id reference,
respectively). Cached KV at position p depends only on tokens <= p, so
greedy streams are token-exact with the cache on or off.

Speculative decoding (`serve/spec.py`, opt-in via
`ServeConfig.speculative`): the decode block becomes per-slot
draft-and-verify rounds — a drafter (n-gram prompt-lookup over a
history buffer riding the packed control transfer, or the DeepSeek-V3
MTP heads) proposes up to `spec_k` tokens per slot, one chunked
forward evaluates the whole `1 + spec_k` window, and verification
commits a variable number of tokens per round. Greedy slots verify by
exact argmax match (streams stay byte-identical to spec-off serving
and one-shot `generate`); stochastic slots use lossless rejection
sampling against `fused_sample`'s truncated distributions; grammar
slots ride along draft-free. Draft length is traced per slot — mixed
spec/non-spec batches share one compiled decode program — and a
host-side adaptive controller falls back to the plain block while
drafts keep rejecting.

Per-request sampling (`serve/sampling.py`): every request carries
`SamplingParams` (temperature / top-k / top-p / min-p / seed / stop sets /
logprobs). The knobs live in slot-major struct-of-arrays mirrors packed
into the jitted programs as TRACED control operands — one fused
`fused_sample` serves the whole slot axis, so a greedy request and a
temperature-1.2/top-p-0.9 request coexist in one vmapped decode block
with zero extra compiled programs. Greedy slots (temperature 0) are
token-exact vs per-request one-shot greedy `generate`
(tests/test_serve.py, tests/test_prefix_cache.py,
tests/test_serve_sampling.py); a seeded stochastic slot replays the same
stream run-to-run (its rng chain folds only (seed, sample index) into the
engine's base key — never the slot or step counter).

Request lifecycle: `cancel()` and per-request deadlines free the lane at
the next block boundary (finish reasons eos / length / stop / cancelled /
timeout, counted in `ServeMetrics`); stop strings are matched host-side
on the detokenized stream (matches may span block boundaries); stop
token-id sets extend single-id EOS host-side.

Observability (`metrics/trace.py`, opt-in via `ServeConfig.trace`): a
flight recorder captures per-request lifecycle spans, per-step batch
composition, and scheduler/prefix-cache events into a bounded ring;
export to Perfetto with `engine.trace.export_chrome(path)`, rebuild
timelines with `cli trace-summary`, and arm post-mortem anomaly dumps
with `trace_dump_path` — see the ServeConfig docstring and the README's
Observability section.

Compile & memory observatory (`metrics/xla_obs.py`, opt-in via
`ServeConfig.xla_obs`): every jitted program routes through a compile
registry that records each XLA compilation (signature, wall time,
cost_analysis flops/bytes) and flags recompile storms, while an HBM
ledger accounts per-pool live bytes and projected peak vs device
capacity; `ServeConfig.status_port` serves the live /healthz /metrics
/statusz endpoint (`metrics/http.py`).

Fault tolerance (`serve/faults.py`; always on — real NaN forwards and
device runtime errors need no opt-in): every `step()` runs inside a
supervised fault boundary. A traced per-slot finite-logits guard pins
NaN/Inf forwards to their slot, which is QUARANTINED — block output
discarded, lane/pages scrubbed to zero before release (0 * NaN is NaN;
the stale-lane contract only covers finite values), request finished
``"error"``, every other stream byte-identical. Systemic failures
(XlaRuntimeError / OOM / anything escaping a program call) cost a
bounded pool-rebuild retry — active streams requeue and resume by
recompute, token-exactly — then drain the engine to a 503-reporting
`unhealthy` state until a backed-off recovery. `ServeConfig.fault_plan`
arms the deterministic seeded fault-injection plane (None-pattern off),
`fault_step_deadline_s` the stalled-step watchdog, and
`ServeConfig.degrade` the SLO/ledger-driven degradation ladder (shed
prefix leaves -> hold speculation -> load-shed admissions by class with
jittered Retry-After; hysteresis both ways).

Durable serving (`serve/journal.py`, opt-in via
`ServeConfig.journal_path`): a request write-ahead journal records
submit/commit/finish events (commits once per decode-block boundary,
fsync batched once per step) with atomic live-set compaction; on boot,
`ServeEngine.recover()` replays unfinished entries through the
preemption-resume machinery — greedy and seeded plain-path streams
continue TOKEN-EXACT across a process kill — and the HTTP front door
resumes SSE streams from `Last-Event-ID`. Journal I/O failures degrade
to journal-off with one warning (serving outlives its durability
plane) unless `journal_strict` escalates them.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import time
import uuid
import warnings

import jax
import jax.numpy as jnp
import numpy as np

# imported for the side effect too: buildinfo stamps its process-start
# clock at FIRST import, and /statusz's uptime_s should measure from
# engine-module load (≈ serving-process start), not from whenever the
# first status probe happened to lazily import it
from solvingpapers_tpu import buildinfo
from solvingpapers_tpu.serve import metrics as smetrics
from solvingpapers_tpu.serve.faults import (
    FAULT_INF,
    FAULT_NAN,
    DegradationLadder,
    FaultPlan,
    InjectedFault,
    classify_failure,
)
from solvingpapers_tpu.serve.grammar import encode_allow
from solvingpapers_tpu.serve.journal import Journal, JournalError
from solvingpapers_tpu.serve.kv_pool import (
    TRASH_PAGE,
    KVSlotPool,
    PagedKVPool,
    QuantStore,
    extract_lane,
    gather_lane,
    gather_lanes,
    pad_time,
    quant_gather_lane,
    quant_gather_lanes,
    quant_lane_view,
    quant_lanes_view,
    quant_pool_bytes,
    quant_scatter_lane_pages,
    quant_scatter_window_pages,
    quant_scatter_written_pages,
    quant_store_exact_lanes,
    quant_store_lane,
    quant_store_written,
    scatter_lane_pages,
    scatter_window_pages,
    scatter_written_pages,
    scrub_lane_program,
    scrub_pages_program,
    store_lane,
    strip_time,
)
from solvingpapers_tpu.serve.metrics import ServeMetrics
from solvingpapers_tpu.serve.prefix_cache import PrefixCache
from solvingpapers_tpu.serve.sampling import (
    GREEDY_ROW,
    PackedSampling,
    SamplingParams,
    encode_params,
    fused_sample,
    request_key,
    slot_keys,
)
from solvingpapers_tpu.serve.scheduler import (
    ACTIVE,
    FINISHED,
    REJECTED,
    WAITING,
    FIFOScheduler,
    Request,
)
from solvingpapers_tpu.serve.spec import (
    DRAFTERS,
    SpecController,
    ngram_drafts,
    round_keys,
    spec_verify,
)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape/policy knobs.

    `decode_block` amortizes host dispatch: each decode program advances
    all slots `block` tokens in one `lax.scan` before the host looks at
    the stream again (termination granularity = one block; EOS discovered
    mid-block discards the padded tail, matching `generate`'s
    pad-with-EOS semantics). `bucket` quantizes prefill lengths so the
    number of compiled prefill programs stays bounded — use a multiple of
    128 for `use_flash` models (the Pallas q-block constraint).

    Flight recorder (`metrics/trace.py`, opt-in via `trace`): the engine
    records per-request lifecycle spans (queue / prefill / decode, one
    track per KV slot, one flow per request), per-step composition
    (prefills vs decode slots, control-array transfers, device vs host
    time via `block_until_ready` fencing — the fence only exists when
    tracing is on), and scheduler/prefix-cache events into a bounded
    ring (`trace_capacity` events). Export with
    `engine.trace.export_chrome(path)` and open in Perfetto, or rebuild
    timelines with `cli trace-summary`. `trace_dump_path` arms the
    anomaly dumper: timeout/cancelled finishes, `trace_reject_burst`
    consecutive rejections, or a step exceeding `trace_slow_step_factor`
    x the rolling median step time append the last `trace_dump_events`
    events + a `ServeMetrics.snapshot()` to that JSONL file. With
    `trace` off every hook site is one `is None` branch (< 2% req/s on
    the Poisson bench — BENCH_serve.json `trace_overhead_pct`).

    Profiler (`profile_dir`): opens a `jax.profiler.trace` window around
    engine steps [`profile_steps[0]`, `profile_steps[1]`) with
    `TraceAnnotation` scopes around the prefill/decode/splice programs,
    so engine phases are visible inside the XLA trace (view in
    TensorBoard / Perfetto).

    Prefix cache (`serve/prefix_cache.py`): with `prefix_cache` on, each
    admitted request splices its longest cached page-aligned prompt
    prefix into the lane and prefills only the uncovered suffix (start
    position = matched length; the suffix pads to `bucket` as before, so
    compiled prefill programs stay bounded by (page multiples x
    buckets)). `prefix_cache_bytes` caps the HBM the radix tree may hold
    (LRU leaf eviction; refcounted nodes are never evicted);
    `prefix_page` is the match/segment granularity. `prefix_sched` makes
    the scheduler prefer waiting requests with the shortest uncovered
    suffix (the existing anti-starvation wait budget still overrides).
    Greedy streams are token-exact with the cache on or off. Opt-in:
    every admission pays a match + snapshot copy and the tree holds up
    to `prefix_cache_bytes` of HBM, which is pure overhead on traffic
    with no shared prefixes (~10% req/s on the Poisson bench) — turn it
    on when prompts share stems (system prompts, few-shot, multi-turn).
    """

    n_slots: int = 8
    max_len: int = 512
    decode_block: int = 8
    bucket: int = 64
    # Paged KV pool (serve/kv_pool.py PagedKVPool, vLLM-PagedAttention
    # style): one physical pool of `page_budget` fixed-size KV pages +
    # per-slot page tables instead of contiguous max_len lanes. HBM is
    # booked per PAGE actually needed, so slot count decouples from
    # max_len (more concurrent slots at equal HBM — the bench's
    # --paged arm measures it), and the prefix cache shares pages
    # zero-copy by refcount (a full-page hit dispatches NO device
    # program). Admission moves from slot-count to page-budget
    # accounting: a request is admitted while free pages cover its
    # prompt + a decode-block reservation, and a growing stream that
    # exhausts the pool preempts the youngest request
    # (requeue-and-recompute; greedy/seeded streams are unchanged —
    # resume re-prefills prompt + emitted tokens and the rng chain
    # folds only (seed, sample index)).
    #   page_size   tokens per page; defaults to `prefix_page` so tree
    #               edges align with physical pages (required when both
    #               paged and prefix_cache are on — zero-copy sharing
    #               needs the alignment). max_len must be a multiple.
    #   page_budget allocatable pages; None = n_slots * (max_len /
    #               page_size), the lane-pool-equivalent HBM. Shrink it
    #               (or raise n_slots) to trade worst-case headroom for
    #               concurrency — the whole point of paging.
    paged: bool = False
    page_size: int | None = None
    page_budget: int | None = None
    # Quantized KV storage (ops/quant.py + serve/kv_pool.py QuantStore):
    # the pool holds symmetric int8 payload + per-block f32 absmax
    # scales instead of the compute dtype — roughly HALF the resident KV
    # bytes (vs bf16; a quarter vs f32), i.e. ~2x the servable slots or
    # context at the same HBM budget (the serve-bench --kv-quant
    # capacity arm measures it). The jitted programs dequantize on read
    # (gather/extract sites materialize the familiar compute-dtype lane
    # view — models serve unmodified) and quantize on write (store/
    # scatter sites requantize only the blocks/pages the step wrote).
    # Output quality is gated on MEASUREMENT, not exactness: the bench
    # records a greedy-token agreement rate vs the full-precision pool
    # per BENCH_serve.json entry (>= 0.99 is the CI gate).
    #   kv_quant        None = exact storage (today's pools, untouched
    #                   code paths); "int8" = quantized payload + scale
    #                   sidecar in BOTH pool layouts. The prefix cache
    #                   stores int8 pages/segments + scales (sharing
    #                   stays zero-copy on the paged pool — scales ride
    #                   the page ids). Excludes speculative="mtp" (its
    #                   head-cache lanes are a separate follow-on).
    #   kv_quant_block  lane-pool scale granularity: one f32 absmax
    #                   scale per (slot, kv_quant_block tokens, head)
    #                   — must divide max_len (and prefix_page when the
    #                   lane-pool prefix cache is on). The paged pool
    #                   always scales per (page, head) so scales ride
    #                   the page tables.
    #   kv_exact_lanes  per-request escape hatch capacity: a request
    #                   with SamplingParams.kv_exact serves from one of
    #                   this many full-precision sidecar lanes (plus a
    #                   trash lane), byte-identical to the unquantized
    #                   engine, INSIDE the same compiled programs as
    #                   quantized traffic (the lane index rides the
    #                   packed control rows). 0 (default) books no
    #                   sidecar — pure capacity win — and kv_exact
    #                   submissions are rejected. Exact requests bypass
    #                   the (quantized) prefix cache and never consume
    #                   pages.
    kv_quant: str | None = None
    kv_quant_block: int = 16
    kv_exact_lanes: int = 0
    # Speculative decoding (serve/spec.py): per-slot draft-and-verify
    # inside the decode program. Each decode step runs `spec_rounds`
    # draft-verify rounds: a drafter proposes up to `spec_k` tokens per
    # slot, ONE chunked forward computes the model's distributions over
    # the 1+k-token window, and verification commits 1..k+1 tokens per
    # round — greedy slots by exact argmax match (streams stay
    # byte-identical to spec-off serving and one-shot generate),
    # stochastic slots by rejection sampling against fused_sample's
    # truncated distributions (per-request output distributions provably
    # unchanged), grammar-constrained slots ride along draft-free (one
    # token per step, the stale-mask contract). Draft length is traced
    # per slot, so mixed spec/non-spec batches share ONE compiled decode
    # program.
    #   speculative  None = off; "ngram" = model-free prompt-lookup
    #                self-drafter (device-side lookup over a history
    #                buffer riding the packed control transfer — any
    #                family, either pool); "mtp" = DeepSeek-V3
    #                multi-token-prediction heads (infer/speculative.py
    #                mechanics vmapped over slots; deepseekv3 family,
    #                lane pool, no prefix cache — the head cache has no
    #                hidden states for spliced prefixes)
    #   spec_k       draft tokens per round (chunk width 1 + spec_k);
    #                "mtp" clamps to the model's trained head count
    #   spec_rounds  draft-verify rounds per decode call (None =
    #                decode_block); each call commits between
    #                spec_rounds and spec_rounds * (1 + spec_k) tokens
    #                per slot
    #   spec_ngram   longest tail n-gram the lookup drafter tries
    #                (falls back n, n-1, ..., 1)
    #   spec_min_rate / spec_probe_every  the adaptive controller
    #                (serve/spec.py SpecController): acceptance below
    #                spec_min_rate ACCEPTED DRAFTS PER ROUND drops the
    #                engine to plain blocks for spec_probe_every steps
    #                (doubling on every failed cheap probe, capped), so
    #                zero-acceptance adversarial traffic pays a few
    #                short probes instead of chunked blocks every step.
    #                None scales the threshold with the chunk width
    #                (max(1, spec_k / 4)): each round forwards 1+k
    #                positions, so the acceptance worth paying for
    #                grows with k
    speculative: str | None = None
    spec_k: int = 4
    spec_rounds: int | None = None
    spec_ngram: int = 3
    spec_min_rate: float | None = None
    spec_probe_every: int = 8
    # static support bound for stochastic sampling (clamped to the vocab):
    # fused_sample draws inside the top `sample_cap` logits per step —
    # bounded-support sampling keeps the per-step cost at one top-k
    # selection instead of full-vocab sorts (~100x the forward on
    # XLA:CPU). Requests' top_k must fit under it (submit validates);
    # raise it (up to the vocab size) for exact full-support sampling.
    sample_cap: int = 64
    # SLO accounting (serve/slo.py, opt-in): per-class latency targets,
    # {class: {"ttft_s"/"itl_s"/"e2e_s": seconds, "objective": frac}} —
    # pass `serve.slo.DEFAULT_SLO_TARGETS` for the reference
    # interactive/standard/batch tier set. When set, every finish is
    # accounted under its request's `SamplingParams.slo` class (default
    # "standard", which the dict must define): per-class attainment,
    # error-budget burn rate, and goodput (tokens from SLO-attained
    # requests only) ride the snapshot as slo/* +
    # serve/goodput_tokens[_per_s] gauges and the /statusz `slo`
    # section. None = off: no gauges, and slo-tagged submissions are
    # rejected (the tag would silently account to nothing).
    slo_targets: dict | None = None
    # finishes in the sliding window the burn rate is computed over
    slo_burn_window: int = 256
    # Fault tolerance (serve/faults.py; see that module's docstring for
    # the failure taxonomy). The supervised step loop is ALWAYS on —
    # every step() runs inside a fault boundary that quarantines
    # NaN/Inf-poisoned slots (finish_reason "error", leak-free reclaim,
    # other streams byte-identical) and answers systemic device
    # failures with bounded pool-rebuild retries, then a draining
    # `unhealthy` state /healthz reports as 503 until recovery. The
    # knobs below tune the boundary; `fault_plan` arms the DETERMINISTIC
    # seeded fault-injection plane (None-pattern off, like the tracer):
    #   fault_plan       sequence of serve.faults.FaultSpec (or dicts):
    #                    named sites (prefill/decode/scatter/
    #                    prefix_splice/sse_write) x kinds (nan/inf
    #                    logits poison, synthetic xla_error/oom, stall,
    #                    socket_reset), each firing at an exact visit of
    #                    its site — so every recovery path is testable
    #                    on CPU, bit-reproducibly. None = off: the hot
    #                    path pays one `is not None` branch per site.
    #   fault_max_retries  consecutive pool-rebuild retries a systemic
    #                    failure may consume before the engine drains to
    #                    `unhealthy` (in-flight streams finish "error")
    #   fault_retry_backoff_s  base sleep between rebuild retries
    #                    (doubles per consecutive failure)
    #   fault_recover_backoff_s  how long an unhealthy engine waits
    #                    before accepting work again (doubles across
    #                    repeated unhealthy episodes until a clean step)
    #   fault_step_deadline_s  watchdog: a step exceeding this absolute
    #                    wall deadline is flagged (serve/watchdog_stalls
    #                    counter, trace instant, anomaly dump when the
    #                    dumper is armed). None = off.
    fault_plan: object | None = None
    fault_max_retries: int = 2
    fault_retry_backoff_s: float = 0.05
    fault_recover_backoff_s: float = 0.25
    fault_step_deadline_s: float | None = None
    # Request write-ahead journal (serve/journal.py, opt-in via
    # journal_path — the None-pattern, like the tracer and the fault
    # plane): an fsync'd append-only JSONL journal recording submit
    # (prompt ids + full SamplingParams incl. seed + SLO class +
    # arrival), commit (committed token ids, once per decode-block
    # boundary riding the host-mirror drain — never per token) and
    # finish (reason + usage) events, with atomic tmp+rename live-set
    # compaction so the file stays O(active requests). On boot,
    # `ServeEngine.recover()` replays unfinished entries through the
    # preemption-resume machinery: greedy/seeded recovered streams are
    # TOKEN-EXACT vs an uninterrupted run (seeded chains fold only
    # (seed, sample index)). fsync is batched once per engine step, so
    # a SIGKILL loses at most one step's records.
    #   journal_path     JSONL journal file; an existing file is LOADED
    #                    (the recovery source), then appended. None =
    #                    off: one `is not None` branch per hook.
    #   journal_strict   journal I/O failures (disk full; injected via
    #                    the fault plane's journal_write/io_error site)
    #                    normally degrade to journal-off with a single
    #                    warning and the serve/journal_degraded gauge —
    #                    serving survives, durability is lost and SAYS
    #                    so. strict=True propagates the failure instead
    #                    (a deployment that REQUIRES durability fails
    #                    loudly rather than silently serving without it).
    #   journal_rotate_bytes / journal_rotate_finished   compaction
    #                    triggers: rewrite to the live set once this
    #                    many bytes / finish records accumulate.
    journal_path: str | None = None
    journal_strict: bool = False
    journal_rotate_bytes: int = 4 << 20
    journal_rotate_finished: int = 256
    # Degradation ladder (serve/faults.py DegradationLadder, opt-in):
    # under sustained pressure — paged-pool page exhaustion
    # (pages_free below degrade_free_page_frac of the budget),
    # HBM-projection breach (the xla_obs ledger's projected peak within
    # degrade_headroom_frac of capacity), or SLO error-budget burn
    # (any class's burn rate above degrade_burn_threshold) — the
    # engine climbs one rung at a time: shed prefix-cache leaves ->
    # hold speculation -> load-shed admissions by SLO class (batch
    # first, then standard; shed submissions reject with a jittered
    # Retry-After through the front door). Escalation needs
    # degrade_up_steps consecutive pressured steps, de-escalation
    # degrade_down_steps clear ones (hysteresis — the ladder cannot
    # flap), and recovery re-arms in reverse order. Each rung is the
    # serve/degradation_rung gauge; each transition a trace instant.
    degrade: bool = False
    degrade_up_steps: int = 2
    degrade_down_steps: int = 16
    degrade_free_page_frac: float = 0.125
    degrade_burn_threshold: float = 1.5
    degrade_headroom_frac: float = 0.05
    prefill_chunk: int | None = None
    max_waiting: int = 256
    decode_priority: bool = True
    max_prefills_per_step: int = 1
    max_wait_steps: int = 64
    eos_id: int | None = None  # default per-request EOS (None = run to budget)
    seed: int = 0
    prefix_cache: bool = False
    prefix_page: int = 16
    prefix_cache_bytes: int = 64 << 20
    prefix_sched: bool = False
    # flight recorder (metrics/trace.py); see the class docstring above
    trace: bool = False
    trace_capacity: int = 65536
    trace_dump_path: str | None = None  # anomaly JSONL; requires trace=True
    trace_dump_events: int = 256
    trace_slow_step_factor: float = 10.0
    trace_reject_burst: int = 8
    # rolling in-process time series (metrics/timeseries.py): a fixed-
    # budget ring of periodic metric samples (gauges + per-window
    # counter/histogram deltas), sampled opportunistically from step()
    # — no timer thread. Served as /timeseriesz JSON + /statusz
    # sparklines and attached to every anomaly dump, so a quarantine/
    # degradation/drain artifact carries the preceding N-window of
    # engine state. On by default: capacity x interval bounds memory
    # at O(capacity x n_series) floats (~2 minutes at the defaults).
    timeseries: bool = True
    timeseries_capacity: int = 120
    timeseries_interval_s: float = 1.0
    # jax.profiler window over engine steps [start, stop)
    profile_dir: str | None = None
    profile_steps: tuple[int, int] = (10, 15)
    # compile & memory observatory (metrics/xla_obs.py, opt-in): every
    # jitted program routes through a CompileRegistry (records each XLA
    # compilation's signature, wall time, cost_analysis flops/bytes and
    # memory_analysis temp bytes; flags recompile storms — same program,
    # >= obs_storm_k NEW signatures inside obs_storm_window_s — through
    # the AnomalyMonitor when trace_dump_path is armed) and an HBMLedger
    # tracks per-pool live bytes (params / kv_pool / prefix_cache) plus
    # projected decode-step peak vs device capacity, warning before the
    # projection exceeds it. Gauges ride ServeMetrics.snapshot() as
    # compile/* + mem/* + roofline/* keys. Observability mode: program
    # calls are fenced for device-true run seconds (same contract and
    # paired-bench budget as `trace` — BENCH_serve.json
    # `obs_overhead_pct`); off = None registry, one branch per call site.
    # The registry also parses every compiled program's HLO text into
    # the per-op-category anatomy ledger (metrics/hlo_cost.py —
    # gather/scatter/dot/convert/... flops + output-shape bytes, top-k
    # heaviest ops), surfaced as /statusz `compile.programs.<name>.
    # anatomy`, compile-event args on the flight recorder, and the
    # trace-summary "anatomy" section. obs_hlo_dir optionally dumps
    # each TRUE compile's HLO text (atomic tmp+rename, one file per
    # signature, sanitized program names) so anatomy claims can be
    # diffed offline.
    xla_obs: bool = False
    obs_hlo_dir: str | None = None
    obs_storm_k: int = 8
    obs_storm_window_s: float = 60.0
    # device capacity override for the headroom estimate (bytes); None =
    # ask the backend (memory_stats()["bytes_limit"]; CPU reports none,
    # so headroom gauges are simply absent there)
    obs_capacity_bytes: int | None = None
    # live status endpoint (metrics/http.py, opt-in): /healthz, /metrics
    # (Prometheus text of the current snapshot), /statusz (engine + slot
    # occupancy + compile registry + memory ledger JSON) on a daemon
    # thread bound to status_host. Port 0 = ephemeral (published as
    # engine.status.port); None = no server. Close with engine.close().
    status_port: int | None = None
    status_host: str = "127.0.0.1"
    # OpenAI-compatible HTTP front door (serve/api.py — started by `cli
    # serve` or `serve.api.ApiServer`, NEVER by the engine itself: the
    # API server owns the step-loop thread and the shutdown ordering).
    # Knobs live here so ONE config object describes a serving process:
    #   api_port    port for /v1/completions + /v1/chat/completions
    #               (plus /healthz /metrics /statusz on the same
    #               listener); 0 = ephemeral, published as
    #               ApiServer.port
    #   api_host    bind address (loopback by default — an inspection/
    #               demo surface; front with a real proxy to expose it)
    #   api_max_connections  concurrent streaming connections before
    #               the front door answers 503 (per-connection
    #               backpressure AHEAD of the scheduler's bounded
    #               waiting queue, which 503s the overflow after it)
    #   json_mode   accept `response_format {"type": "json_object"}`:
    #               grammar-constrained decoding via the (S, sample_cap)
    #               allow-mask (serve/grammar.py); constrained slots
    #               share the one compiled decode program but advance
    #               ONE token per decode block (the mask rides the
    #               per-call control transfer and is stale after the
    #               first draw), so JSON-mode throughput is ~1/block of
    #               unconstrained — size decode_block accordingly
    #   stream_queue  per-connection pending stream events before
    #               coalescing (events carry counts, not payloads — a
    #               slow SSE reader never blocks the engine thread)
    #   drain_timeout_s  ApiServer.close(): seconds to wait for active
    #               streams to finish before cancelling them (shutdown
    #               order: drain streams -> engine.close() -> HTTP
    #               threads)
    api_port: int | None = None
    api_host: str = "127.0.0.1"
    api_max_connections: int = 64
    json_mode: bool = True
    stream_queue: int = 256
    drain_timeout_s: float = 10.0


_UNSET = object()


def _inject_fault(logits, fault):
    """Apply the fault-injection plane's logits poison (traced): `fault`
    is the i32 code riding the packed control transfer — 0 clean,
    FAULT_NAN / FAULT_INF poison the slot's whole logits row. An
    all-zero fault operand selects `logits` bitwise unchanged, so the
    disabled plane is a numeric no-op (fault-free streams stay
    token-exact) and costs no extra compiled program — the fault row is
    always part of the signature."""
    f = jnp.asarray(fault)
    mask = (f > 0).reshape(f.shape + (1,) * (logits.ndim - f.ndim))
    bad = jnp.where(f == FAULT_NAN, jnp.nan, jnp.inf).astype(logits.dtype)
    bad = bad.reshape(mask.shape)
    return jnp.where(mask, bad, logits)


def _finite_ok(logits):
    """Per-slot finite-logits guard (traced): True iff every logit the
    sampler would draw from is finite. One cheap reduction riding the
    program's existing outputs — the host pins a NaN/Inf forward to its
    slot with zero extra transfers."""
    axes = tuple(range(1, logits.ndim)) or None
    return jnp.all(jnp.isfinite(logits.astype(jnp.float32)), axis=axes)


def _prefill_lane(model, padded, chunk, start, variables, lane, prompt,
                  length):
    """Shared chunked-prefill core: run `prompt` (right-padded to
    `padded`) through a batch-1 `lane` from position `start`, returning
    the updated lane and the logits row of the LAST REAL token (index
    `length - 1`, gathered from whichever chunk contains it). Both pool
    layouts call this — the lane pool on an extracted lane, the paged
    pool on a gathered page-table view — so the prefill semantics
    (end-aligned attend_len, pad invisibility) cannot drift between
    them."""
    toks = prompt[None, :]
    step = chunk or padded
    last = None
    for cs in range(0, padded, step):
        ce = min(cs + step, padded)
        tok_chunk = jax.lax.slice_in_dim(toks, cs, ce, axis=1)
        positions = jnp.broadcast_to(
            jnp.arange(start + cs, start + ce), (1, ce - cs)
        )
        logits, lane = model.apply(
            variables, tok_chunk, positions=positions, caches=lane,
            deterministic=True, attend_len=start + ce,
        )
        idx = jnp.clip(length - 1 - cs, 0, ce - cs - 1)
        row = jax.lax.dynamic_index_in_dim(logits[0], idx, axis=0,
                                           keepdims=False)
        sel = (length - 1 >= cs) & (length - 1 < ce)
        last = row if last is None else jnp.where(sel, row, last)
    return lane, last


@functools.partial(
    jax.jit,
    static_argnames=("model", "padded", "chunk", "start", "cap"),
    donate_argnames=("caches",),
)
def _prefill_program(model, padded, chunk, start, cap, variables, caches,
                     prompt, ctl, samp, rng):
    """Prefill one request into lane `ctl[0]` and sample its first token.

    `prompt` is (padded,) right-padded; `ctl = [slot, length, step,
    top_k, seed, need_lp, *allow_row]` is the host's packed int control
    word (one transfer instead of many — the host loop's dispatch
    overhead is the serving bottleneck on small models, see
    tools/bench_serve.py), where `length` is the real token count, so
    one compiled program serves every prompt in the bucket.
    `allow_row` is the (cap,) grammar allow-list for the FIRST sampled
    token (-1-padded; all -1 = unconstrained — see serve/grammar.py). `samp = [temperature, top_p, min_p]` is
    the float half of the request's SamplingParams — every sampling knob
    is a traced operand, so the compiled inventory is untouched by the
    param mix (`cap` = ServeConfig.sample_cap is static but fixed per
    engine).
    `rng` is the engine's base key; the first token is sample index 0 of
    the request's chain (see `serve.sampling.request_key`). Chunks mirror
    `generate`'s static-bound python loop; the logits row for the LAST
    REAL token is gathered from whichever chunk contains it (padding
    makes that not-necessarily-the-last chunk).

    `start` (static) is the prefix-cache match length: `prompt` is the
    UNCOVERED SUFFIX, cache slots [0, start) already hold the spliced
    prefix KV, and positions/attend_len shift by `start` — the same
    end-aligned contract, so chunk i attends causally over every written
    slot [0, start + end_i). `start=0` is a full prefill. Static because
    `attend_len` drives a static slice; start values are page multiples,
    keeping the compiled inventory bounded.

    Quantized pools (`caches` a `QuantStore` — a TRACE-TIME branch, so
    the unquantized program graph is untouched): the lane view is
    dequantized out of the slot's int8 + scale rows (or substituted from
    the exact sidecar for a kv_exact slot — ``ctl[-1]`` carries the
    exact-lane index), and the store requantizes exactly the written
    span [start, start + padded) — spliced prefix blocks below `start`
    keep their producer's bytes.
    """
    slot, length = ctl[0], ctl[1]
    quant = isinstance(caches, QuantStore)
    # fault-plane layout contract: the poison code is ALWAYS the last
    # ctl element; the exact-lane index (quant pools) sits before it
    fault = ctl[-1]
    eidx = ctl[-2] if quant else None
    lane = (quant_lane_view(caches, slot, eidx) if quant
            else extract_lane(caches, slot))
    lane, last = _prefill_lane(model, padded, chunk, start, variables,
                               lane, prompt, length)
    last = _inject_fault(last, fault)
    ok = _finite_ok(last)
    packed = PackedSampling(
        temperature=samp[0:1], top_p=samp[1:2], min_p=samp[2:3],
        top_k=ctl[3:4], need_lp=ctl[5:6],
    )
    key = request_key(rng, step_tag=ctl[2], slot=slot, seed=ctl[4],
                      samp_idx=jnp.int32(0))
    first, logprob = fused_sample(last[None], packed, key[None], cap=cap,
                                  allow=ctl[6:6 + cap][None, :])
    if quant:
        caches = quant_store_lane(caches, lane, slot, eidx, start,
                                  start + padded, hi=start + length)
    else:
        caches = store_lane(caches, lane, slot)
    return caches, first[0], logprob[0], ok


@functools.partial(
    jax.jit,
    static_argnames=("model", "padded", "chunk", "start", "cap"),
    donate_argnames=("phys",),
)
def _paged_prefill_program(model, padded, chunk, start, cap, variables,
                           phys, prompt, ctl, samp, rng):
    """Paged-pool prefill: identical contract to `_prefill_program`, but
    the lane is a GATHERED view of the physical page pool and only the
    pages the prefill may have written go back.

    `ctl = [slot, length, step, top_k, seed, need_lp, *allow_row,
    *page_table_row]` — the slot's (pages_per_lane,) page-table row
    rides the same packed int control transfer as the sampling knobs
    and the (cap,) grammar allow-list, so logical->physical
    translation costs zero extra host->device transfers and the
    compiled-program inventory keys on exactly the lane pool's
    `(padded, chunk, start)` triple. On a prefix hit, pages
    [0, start // page) hold SHARED prefix KV the gather materializes
    into the lane view; the scatter starts at `start // page` (static),
    so shared pages are read, never written — the zero-device-copy hit
    the refcount design exists for.

    Quantized pools: the gather dequantizes int8 pages through their
    per-(page, head) scale rows (both ride the same page-table
    translation), a kv_exact slot's view comes whole from the exact
    sidecar (its table rests at trash — exact streams never own pages),
    and the scatter re-quantizes only the written pages."""
    slot, length = ctl[0], ctl[1]
    quant = isinstance(phys, QuantStore)
    fault = ctl[-1]
    if quant:
        eidx = ctl[-2]
        row = ctl[6 + cap:-2]
        lane = quant_gather_lane(phys, row, eidx)
    else:
        row = ctl[6 + cap:-1]
        lane = gather_lane(phys, row)
    lane, last = _prefill_lane(model, padded, chunk, start, variables,
                               lane, prompt, length)
    last = _inject_fault(last, fault)
    ok = _finite_ok(last)
    packed = PackedSampling(
        temperature=samp[0:1], top_p=samp[1:2], min_p=samp[2:3],
        top_k=ctl[3:4], need_lp=ctl[5:6],
    )
    key = request_key(rng, step_tag=ctl[2], slot=slot, seed=ctl[4],
                      samp_idx=jnp.int32(0))
    first, logprob = fused_sample(last[None], packed, key[None], cap=cap,
                                  allow=ctl[6:6 + cap][None, :])
    if quant:
        page = jax.tree_util.tree_leaves(phys.q)[0].shape[1]
        phys = quant_scatter_lane_pages(phys, lane, row, start // page,
                                        eidx, hi=start + length)
    else:
        page = jax.tree_util.tree_leaves(phys)[0].shape[1]
        phys = scatter_lane_pages(phys, lane, row, start // page)
    return phys, first[0], logprob[0], ok


@functools.partial(
    jax.jit,
    static_argnames=("model", "block", "cap"),
    donate_argnames=("caches",),
)
def _decode_program(model, block, cap, variables, caches, state, samp, rng):
    """Advance every slot `block` tokens; inactive slots run masked.

    `state` is the host's packed (9 + cap, n_slots) int32 control block
    — rows [toks, pos, active, eos, step, top_k, seed, samp_idx,
    need_lp] then the transposed (cap, S) grammar allow-lists (all -1 =
    unconstrained; a constrained slot samples only listed ids and the
    HOST accepts one token per block, the mask being stale after the
    first draw — see serve/grammar.py) — and `samp` the packed
    (3, n_slots) float32 half of every slot's SamplingParams (rows
    [temperature, top_p, min_p]), so each call costs two host->device
    transfers regardless of slot count or param mix; the host keeps
    numpy mirrors and only the emitted streams come back. Every sampling knob is traced, so the compiled decode program
    count is identical to the static-greedy engine's (`cap` =
    ServeConfig.sample_cap is static but fixed per engine). `rng` is the
    engine's base key (a constant buffer); per-slot keys fold in the
    request seed and sample index for seeded slots, or the step counter
    riding row 4 for unseeded ones (`serve.sampling.slot_keys`).

    The per-slot apply is a batch-1 single-token forward vmapped over the
    slot axis — per-slot positions and per-slot cache writes fall out of
    the models' ``positions[0, 0]`` write contract under vmap. EOS
    padding is sticky by induction (an emitted EOS forces every later
    emission to EOS), mirroring `generate`'s done-flag semantics.

    Returns ``(caches, (tokens (block, S) i32, logprobs (block, S)
    f32))`` — the logprob row is the chosen token's log-softmax under the
    raw logits (streamed to requests with ``params.logprobs``).
    """
    toks, pos = state[0], state[1]
    active, eos = state[2].astype(bool), state[3]
    step_tag, seeds = state[4, 0], state[6]
    allow = state[9:9 + cap].T  # (S, cap)
    # fault-plane layout contract: the per-slot poison row is ALWAYS the
    # last state row; the exact-lane index row (quant) sits before it
    fault = state[-1]
    packed = PackedSampling(
        temperature=samp[0], top_p=samp[1], min_p=samp[2], top_k=state[5],
        need_lp=state[8],
    )
    # quantized pools (trace-time branch; the plain graph is untouched):
    # the scan carries the DEQUANTIZED (S, max_len, ...) lane view —
    # within-block reads are full precision, quantization happens at the
    # block boundary — and the store requantizes only the blocks each
    # slot's write window [pos0, pos0 + block) touched. state[-2] is the
    # per-slot exact-lane index row.
    quant = isinstance(caches, QuantStore)
    if quant:
        eidx = state[-2]
        pos0 = pos
        lanes = quant_lanes_view(caches, eidx)
    else:
        lanes = caches

    def one(tok, p, slot_caches):
        lane = jax.tree_util.tree_map(lambda a: a[None], slot_caches)
        logits, lane = model.apply(
            variables, tok[None, None], positions=jnp.reshape(p, (1, 1)),
            caches=lane, deterministic=True,
        )
        return logits[0, 0], jax.tree_util.tree_map(
            lambda a: jnp.squeeze(a, axis=0), lane
        )

    def step(carry, _):
        toks, pos, samp_idx, lanes = carry
        logits, lanes = jax.vmap(one)(toks, pos, lanes)
        logits = _inject_fault(logits, fault)
        ok = _finite_ok(logits)
        keys = slot_keys(rng, step_tag, seeds, samp_idx)
        nxt, logprob = fused_sample(logits, packed, keys, cap=cap,
                                    allow=allow)
        nxt = nxt.astype(toks.dtype)
        hit_eos = (eos >= 0) & (toks == eos)
        nxt = jnp.where(hit_eos, eos.astype(toks.dtype), nxt)
        nxt = jnp.where(active, nxt, toks)
        pos = jnp.where(active, pos + 1, pos)
        return (nxt, pos, samp_idx + 1, lanes), (nxt, logprob, ok)

    (toks, pos, _, lanes), (out, lps, oks) = jax.lax.scan(
        step, (toks, pos, state[7], lanes), None, length=block
    )
    if quant:
        caches = quant_store_written(caches, lanes, pos0, block, eidx)
    else:
        caches = lanes
    return caches, (out, lps, jnp.all(oks, axis=0))


@functools.partial(
    jax.jit,
    static_argnames=("model", "block", "cap"),
    donate_argnames=("phys",),
)
def _paged_decode_program(model, block, cap, variables, phys, state, samp,
                          rng):
    """Paged-pool decode block: `_decode_program`'s semantics over a
    physical page pool.

    `state` is the packed int block grown by the page tables: rows
    [0, 9 + cap) are exactly the lane program's control rows (incl. the
    grammar allow-lists), rows [9 + cap, 9 + cap + pages_per_lane)
    carry `table.T` — per-call page tables ride the ONE existing
    control transfer, so a paged decode call still costs two
    host->device transfers total.

    Translation is hoisted OUT of the scan: every slot's logical lane
    view is gathered from its page table once up front (the same
    (S, max_len, ...) layout the vmapped batch-1 apply already serves —
    the models run unmodified), the block's token loop runs on the
    carried lane views exactly like the lane program, and afterwards
    only the WRITE WINDOW goes back to the pool: the block writes
    positions [pos, pos + block), which spans a static number of pages
    per slot — those pages are gathered per slot and scattered to their
    physical ids. Sound because within one block every page outside a
    slot's own write window is read-only (shared prefix pages always
    PRECEDE the write frontier — see kv_pool.py's immutability
    argument), and pages inside the window are exclusively owned.
    Inactive slots' tables rest at the trash page, so their masked
    dummy writes land there instead of in lane 0; an active slot's
    unallocated tail also resolves to trash, which only discarded
    overshoot (post-EOS / post-budget steps inside the block) can reach
    — the host truncates those tokens anyway."""
    toks, pos = state[0], state[1]
    active, eos = state[2].astype(bool), state[3]
    step_tag, seeds = state[4, 0], state[6]
    allow = state[9:9 + cap].T  # (S, cap)
    fault = state[-1]
    quant = isinstance(phys, QuantStore)
    if quant:
        # the exact-lane index row rides after the page tables, the
        # fault row after it
        table = state[9 + cap:-2].T  # (S, pages_per_lane)
        eidx = state[-2]
        lanes = quant_gather_lanes(phys, table, eidx)
    else:
        table = state[9 + cap:-1].T  # (S, pages_per_lane)
        lanes = gather_lanes(phys, table)
    pos0 = pos
    packed = PackedSampling(
        temperature=samp[0], top_p=samp[1], min_p=samp[2], top_k=state[5],
        need_lp=state[8],
    )

    def one(tok, p, slot_caches):
        lane = jax.tree_util.tree_map(lambda a: a[None], slot_caches)
        logits, lane = model.apply(
            variables, tok[None, None], positions=jnp.reshape(p, (1, 1)),
            caches=lane, deterministic=True,
        )
        return logits[0, 0], jax.tree_util.tree_map(
            lambda a: jnp.squeeze(a, axis=0), lane
        )

    def step(carry, _):
        toks, pos, samp_idx, lanes = carry
        logits, lanes = jax.vmap(one)(toks, pos, lanes)
        logits = _inject_fault(logits, fault)
        ok = _finite_ok(logits)
        keys = slot_keys(rng, step_tag, seeds, samp_idx)
        nxt, logprob = fused_sample(logits, packed, keys, cap=cap,
                                    allow=allow)
        nxt = nxt.astype(toks.dtype)
        hit_eos = (eos >= 0) & (toks == eos)
        nxt = jnp.where(hit_eos, eos.astype(toks.dtype), nxt)
        nxt = jnp.where(active, nxt, toks)
        pos = jnp.where(active, pos + 1, pos)
        return (nxt, pos, samp_idx + 1, lanes), (nxt, logprob, ok)

    (toks, pos, _, lanes), (out, lps, oks) = jax.lax.scan(
        step, (toks, pos, state[7], lanes), None, length=block
    )
    out = (out, lps, jnp.all(oks, axis=0))
    page = jax.tree_util.tree_leaves(phys.q if quant else phys)[0].shape[1]
    # static window bound: positions [p, p + block) touch at most this
    # many pages; windows clipped past the lane end rewrite the last
    # page with its own (final) content — idempotent by construction
    for w in range((block - 1) // page + 2):
        pos_w = jnp.clip(pos0 + w * page, 0, table.shape[1] * page - 1)
        if quant:
            # only [pos0, pos0 + block) came from this block's writes;
            # the rest of each touched page re-encodes from its own f32
            # codes (bf16 lane round-trips would drift committed entries)
            phys = quant_scatter_written_pages(phys, lanes, table, pos_w,
                                               lo=pos0, hi=pos0 + block)
        else:
            phys = scatter_written_pages(phys, lanes, table, pos_w)
    if quant:
        phys = quant_store_exact_lanes(phys, lanes, eidx)
    return phys, out


def _spec_rounds_scan(model, k, rounds, cap, max_len, nmax, variables,
                      lanes, state, samp, rng, hist=None, hlen=None,
                      mtp_lanes=None, drafts0=None):
    """Shared draft-verify scan of the speculative decode programs (all
    three call it, so the commit semantics cannot drift between pools or
    drafters). `lanes` is the PADDED (S, max_len + k + 1, ...) lane view
    (`kv_pool.pad_time` — a chunk write can then never clamp-shift onto
    committed KV); `hist`/`hlen` arm the in-program n-gram drafter,
    `mtp_lanes`/`drafts0` the MTP head chain (exactly one pair is set).

    Each round: draft up to `k` tokens per slot, ONE chunked forward over
    the ``1 + k`` window (the models' cached per-query position masking
    makes the chunk causal, and garbage KV written for rejected drafts is
    overwritten by the next round's chunk before anything attends it —
    the `infer/speculative.py` argument, per slot under vmap), verify
    with `spec_verify`, advance the carry by the committed count. The
    per-slot position freezes at ``max_len - 1`` once a stream overshoots
    its lane (overshoot rounds rewrite slack/garbage only; the host has
    already finished such a stream when it truncates the call's output).

    Returns ``(lanes, mtp_lanes, out (rounds, S, k+1) i32,
    commits (rounds, S), proposed (rounds, S), lps (rounds, S, k+1),
    next_drafts (S, k))`` — the host keeps ``out[r, s, :commits[r, s]]``
    round by round.
    """
    toks, pos = state[0], state[1]
    active = state[2].astype(bool)
    step_tag, seeds, samp0 = state[4, 0], state[6], state[7]
    allow = state[9:9 + cap].T
    fault = state[-1]  # fault-plane poison row (always the last row)
    spec_ok = state[9 + cap].astype(bool)
    packed = PackedSampling(
        temperature=samp[0], top_p=samp[1], min_p=samp[2], top_k=state[5],
        need_lp=state[8],
    )
    mtp = mtp_lanes is not None
    arange_k1 = jnp.arange(k + 1)
    if mtp:
        from solvingpapers_tpu.models.deepseekv3 import mtp_head_apply

        mcfg = model.cfg
        params = variables["params"]
        moe_state = variables.get("moe_state", {})

    def fwd(tok, ds, p, slot_caches):
        lane = jax.tree_util.tree_map(lambda a: a[None], slot_caches)
        chunk = jnp.concatenate([tok[None], ds])[None, :].astype(jnp.int32)
        poss = jnp.minimum(p + arange_k1, max_len - 1)[None, :]
        if mtp:
            (logits, h), lane = model.apply(
                variables, chunk, positions=poss, caches=lane,
                deterministic=True, return_hidden=True,
            )
            out = (logits[0], h[0])
        else:
            logits, lane = model.apply(
                variables, chunk, positions=poss, caches=lane,
                deterministic=True,
            )
            out = logits[0]
        return out, jax.tree_util.tree_map(
            lambda a: jnp.squeeze(a, axis=0), lane
        )

    def rnd(carry, _):
        toks, pos, cnt, hist, hlen, drafts, lanes, mlanes = carry
        if hist is not None:
            ds, avail = jax.vmap(
                lambda h, m: ngram_drafts(h, m, k=k, nmax=nmax)
            )(hist, hlen)
        else:
            ds, avail = drafts, jnp.full(toks.shape, k, jnp.int32)
        avail = jnp.where(spec_ok & active, avail, 0)
        if mtp:
            (logits, hs), lanes = jax.vmap(fwd)(toks, ds, pos, lanes)
        else:
            logits, lanes = jax.vmap(fwd)(toks, ds, pos, lanes)
        logits = _inject_fault(logits, fault)
        ok = _finite_ok(logits)
        keys = round_keys(rng, step_tag, seeds, cnt, k + 1)
        out, commits, lps = spec_verify(
            logits, ds, avail, packed, keys, cap=cap, allow=allow
        )
        commits = jnp.where(active, commits, 0)
        nxt = jnp.take_along_axis(
            out, jnp.maximum(commits - 1, 0)[:, None], axis=1
        )[:, 0]
        toks = jnp.where(active, nxt.astype(toks.dtype), toks)
        if mtp:
            a_cut = jnp.maximum(commits - 1, 0)

            def adv(h_s, out_s, p, a_s, *slot_mtp):
                # the head's next-token stream is the COMMITTED matrix
                # row (garbage columns beyond the cut are overwritten by
                # the next round's advance before they are attended) and
                # the fresh draft reads the newest surviving column —
                # infer/speculative.py's loop body, per slot under vmap
                poss = jnp.minimum(p + arange_k1, max_len - 1)[None, :]
                c1 = jax.tree_util.tree_map(lambda a: a[None], slot_mtp[0])
                g1, y1, c1, _ = mtp_head_apply(
                    mcfg, params, moe_state, h_s[None], out_s[None, :],
                    poss, cache=c1,
                )
                d1 = jnp.argmax(jnp.take(g1[0], a_s, axis=0)).astype(
                    jnp.int32)
                new = [jax.tree_util.tree_map(
                    lambda a: jnp.squeeze(a, axis=0), c1)]
                if k == 2:
                    next2 = jnp.concatenate([out_s[1:], out_s[-1:]])
                    next2 = next2.at[a_s].set(d1)
                    c2 = jax.tree_util.tree_map(
                        lambda a: a[None], slot_mtp[1])
                    g2, _, c2, _ = mtp_head_apply(
                        mcfg, params, moe_state, y1, next2[None, :], poss,
                        cache=c2, head=2,
                    )
                    d2 = jnp.argmax(jnp.take(g2[0], a_s, axis=0)).astype(
                        jnp.int32)
                    new.append(jax.tree_util.tree_map(
                        lambda a: jnp.squeeze(a, axis=0), c2))
                    return (jnp.stack([d1, d2]), *new)
                return (d1[None], *new)

            adv_out = jax.vmap(adv)(hs, out, pos, a_cut, *mlanes)
            drafts, mlanes = adv_out[0], tuple(adv_out[1:])
        if hist is not None:
            hist = jax.vmap(
                lambda h, o, m: jax.lax.dynamic_update_slice(h, o, (m,))
            )(hist, out, hlen)
            hlen = jnp.minimum(hlen + commits, max_len)
        pos = jnp.minimum(pos + commits, max_len - 1)
        cnt = cnt + commits
        carry = (toks, pos, cnt, hist, hlen, drafts, lanes, mlanes)
        return carry, (out, commits, avail, lps, ok)

    if hist is not None:
        # pad so the (k+1)-wide write at hlen <= max_len never shifts
        hist = jnp.concatenate(
            [hist, jnp.zeros((hist.shape[0], k + 1), hist.dtype)], axis=1
        )
    carry0 = (toks, pos, samp0, hist, hlen, drafts0, lanes, mtp_lanes)
    carry, (out, commits, proposed, lps, oks) = jax.lax.scan(
        rnd, carry0, None, length=rounds
    )
    next_drafts = (carry[5] if drafts0 is not None
                   else jnp.zeros((toks.shape[0], k), jnp.int32))
    return (carry[6], carry[7], out, commits, proposed, lps, next_drafts,
            jnp.all(oks, axis=0))


@functools.partial(
    jax.jit,
    static_argnames=("model", "k", "rounds", "cap", "max_len", "nmax"),
    donate_argnames=("caches",),
)
def _spec_decode_program(model, k, rounds, cap, max_len, nmax, variables,
                         caches, state, samp, rng):
    """Lane-pool speculative decode block: `rounds` n-gram draft-verify
    rounds per call. `state` extends the plain decode layout: rows
    [0, 9 + cap) are `_decode_program`'s control rows, row ``9 + cap`` is
    the per-slot spec gate (0 = never draft: grammar-constrained slots
    and free lanes), rows [10 + cap, 10 + cap + max_len) carry each
    slot's token HISTORY transposed (prompt + committed tokens — the
    n-gram drafter's corpus) and the final row its live length. The
    history rides the same packed int transfer, so a speculative decode
    call is still two host->device control arrays. Quantized pools add
    the exact-lane index row LAST: the rounds run over the dequantized
    (padded) lane view and the store requantizes each slot's written
    window — rejected-draft garbage past the committed tail lands in
    blocks that are overwritten before they are ever attended, the same
    stale-lane contract as the plain program."""
    quant = isinstance(caches, QuantStore)
    if quant:
        eidx = state[-2]
        pos0 = state[1]
        views = quant_lanes_view(caches, eidx)
    else:
        views = caches
    lanes = pad_time(views, k + 1)
    hist = state[10 + cap:10 + cap + max_len].T
    hlen = state[10 + cap + max_len]
    lanes, _, out, commits, proposed, lps, _, finite = _spec_rounds_scan(
        model, k, rounds, cap, max_len, nmax, variables, lanes, state,
        samp, rng, hist=hist, hlen=hlen,
    )
    views = strip_time(lanes, k + 1)
    if quant:
        # bound the requantized window by the DEVICE-committed count
        # (mirrors the paged path's `last`): draft positions past it
        # hold rejected draws whose outliers would coarsen the whole
        # block's scale for the committed tokens sharing it
        total = commits.sum(axis=0)
        caches = quant_store_written(caches, views, pos0,
                                     rounds * (k + 1), eidx,
                                     hi=pos0 + jnp.maximum(total, 1),
                                     tail_garbage=True)
    else:
        caches = views
    return caches, (out, commits, proposed, lps, finite)


@functools.partial(
    jax.jit,
    static_argnames=("model", "k", "rounds", "cap", "max_len", "nmax"),
    donate_argnames=("phys",),
)
def _paged_spec_decode_program(model, k, rounds, cap, max_len, nmax,
                               variables, phys, state, samp, rng):
    """Paged-pool speculative decode block: `_spec_decode_program`'s
    semantics over the physical page pool. The page tables ride the
    packed transfer after the history rows; the gathered lane view is
    padded (`pad_time`) so chunk writes never clamp-shift, and only the
    DEVICE-committed window scatters back (`scatter_window_pages`):
    rejected-draft garbage past that window never reaches the physical
    pool, so shared prefix pages and the immutability argument are
    untouched by speculation. NOTE the window is bounded by the device
    commit count, which can exceed what the host keeps (grammar slots
    keep round 0 only; EOS/stop truncate): those tail pages hold
    stale-draw KV that is only sound because it lands strictly after the
    slot's attend window and is rewritten before it is ever attended —
    do NOT share or snapshot pages past a slot's host-accepted length."""
    base = 11 + cap + max_len
    quant = isinstance(phys, QuantStore)
    if quant:
        table = state[base:-2].T  # (S, pages_per_lane)
        eidx = state[-2]
        gathered = quant_gather_lanes(phys, table, eidx)
    else:
        table = state[base:-1].T  # (S, pages_per_lane)
        gathered = gather_lanes(phys, table)
    hist = state[10 + cap:10 + cap + max_len].T
    hlen = state[10 + cap + max_len]
    pos0 = state[1]
    lanes = pad_time(gathered, k + 1)
    lanes, _, out, commits, proposed, lps, _, finite = _spec_rounds_scan(
        model, k, rounds, cap, max_len, nmax, variables, lanes, state,
        samp, rng, hist=hist, hlen=hlen,
    )
    lanes = strip_time(lanes, k + 1)
    total = commits.sum(axis=0)
    last = jnp.minimum(pos0 + jnp.maximum(total, 1) - 1, max_len - 1)
    if quant:
        phys = quant_scatter_window_pages(phys, lanes, table, pos0, last,
                                          rounds * (k + 1))
        phys = quant_store_exact_lanes(phys, lanes, eidx)
    else:
        phys = scatter_window_pages(phys, lanes, table, pos0, last,
                                    rounds * (k + 1))
    return phys, (out, commits, proposed, lps, finite)


@functools.partial(
    jax.jit,
    static_argnames=("model", "k", "rounds", "cap", "max_len"),
    donate_argnames=("caches", "mtp"),
)
def _mtp_spec_decode_program(model, k, rounds, cap, max_len, variables,
                             caches, mtp, state, samp, rng):
    """MTP speculative decode block (deepseekv3, lane pool): the chunk
    forward returns hidden states and the trained MTP head(s) — their
    per-slot latent-cache lanes ride in `mtp`, allocated with the same
    ``k + 1`` slack — redraft the next round's tokens in-program
    (`infer/speculative.py` head chaining, vmapped over slots). Rows
    [10 + cap, 10 + cap + k) of `state` carry the FIRST round's drafts
    (the bootstrap from `_mtp_prefill_program`, or the previous call's
    returned `next_drafts`)."""
    lanes = pad_time(caches, k + 1)
    drafts0 = state[10 + cap:10 + cap + k].T.astype(jnp.int32)
    lanes, mtp, out, commits, proposed, lps, nxt, finite = (
        _spec_rounds_scan(
            model, k, rounds, cap, max_len, 0, variables, lanes, state,
            samp, rng, mtp_lanes=mtp, drafts0=drafts0,
        ))
    return (strip_time(lanes, k + 1), mtp,
            (out, commits, proposed, lps, finite), nxt)


@functools.partial(
    jax.jit,
    static_argnames=("model", "padded", "chunk", "cap", "k"),
    donate_argnames=("caches", "mtp"),
)
def _mtp_prefill_program(model, padded, chunk, cap, k, variables, caches,
                         mtp, prompt, ctl, samp, rng):
    """MTP-engine admission: `_prefill_program`'s contract (lane pool,
    full prefill — the MTP engine excludes the prefix cache: a spliced
    prefix has no hidden states for the head cache) plus the MTP head
    prefill and bootstrap drafts, mirroring `infer/speculative.py`'s
    prefill on a padded prompt: the head's cache is filled over columns
    [0, padded - 1) (columns past ``length - 1`` hold pad garbage that
    the decode rounds overwrite before any real query attends them), and
    the bootstrap advances it at column ``length - 1`` with the first
    sampled token to draft the token after it. Returns ``(caches, mtp,
    first, logprob, drafts (k,))``."""
    from solvingpapers_tpu.models.deepseekv3 import mtp_head_apply

    mcfg = model.cfg
    params = variables["params"]
    moe_state = variables.get("moe_state", {})
    slot, length = ctl[0], ctl[1]
    lane = extract_lane(caches, slot)
    toks = prompt[None, :]
    step = chunk or padded
    hs = []
    last = None
    for cs in range(0, padded, step):
        ce = min(cs + step, padded)
        tok_chunk = jax.lax.slice_in_dim(toks, cs, ce, axis=1)
        positions = jnp.broadcast_to(
            jnp.arange(cs, ce), (1, ce - cs)
        )
        (logits, h), lane = model.apply(
            variables, tok_chunk, positions=positions, caches=lane,
            deterministic=True, attend_len=ce, return_hidden=True,
        )
        hs.append(h)
        idx = jnp.clip(length - 1 - cs, 0, ce - cs - 1)
        row = jax.lax.dynamic_index_in_dim(logits[0], idx, axis=0,
                                           keepdims=False)
        sel = (length - 1 >= cs) & (length - 1 < ce)
        last = row if last is None else jnp.where(sel, row, last)
    h_all = jnp.concatenate(hs, axis=1)  # (1, padded, D)
    caches = store_lane(caches, lane, slot)
    last = _inject_fault(last, ctl[-1])
    ok = _finite_ok(last)
    packed = PackedSampling(
        temperature=samp[0:1], top_p=samp[1:2], min_p=samp[2:3],
        top_k=ctl[3:4], need_lp=ctl[5:6],
    )
    key = request_key(rng, step_tag=ctl[2], slot=slot, seed=ctl[4],
                      samp_idx=jnp.int32(0))
    first, logprob = fused_sample(last[None], packed, key[None], cap=cap,
                                  allow=ctl[6:6 + cap][None, :])
    first32 = first[0].astype(jnp.int32)
    # ---- head 1 prefill over columns [0, padded - 1): the next-token
    # stream there is the prompt itself (pad columns hold garbage the
    # decode rounds overwrite before any real attend — same contract as
    # the main lane's pad region)
    m1 = extract_lane(mtp[0], slot)
    y1s = []
    head_end = max(padded - 1, 1)
    for cs in range(0, head_end, step):
        ce = min(cs + step, head_end)
        nxt = jax.lax.slice_in_dim(toks, cs + 1, ce + 1, axis=1)
        g, y1, m1, _ = mtp_head_apply(
            mcfg, params, moe_state, h_all[:, cs:ce], nxt,
            jnp.broadcast_to(jnp.arange(cs, ce), (1, ce - cs)),
            cache=m1, attend_len=ce,
        )
        y1s.append(y1)
    # bootstrap at column length - 1: h of the last real prompt token +
    # the embedding of the just-sampled first token -> drafts position
    # length + 1
    pos_last = jnp.clip(length - 1, 0, padded - 1)
    h_last = jax.lax.dynamic_slice(
        h_all, (0, pos_last, 0), (1, 1, h_all.shape[2])
    )
    g, y1_last, m1, _ = mtp_head_apply(
        mcfg, params, moe_state, h_last, first32[None, None],
        jnp.reshape(pos_last, (1, 1)), cache=m1,
    )
    d1 = jnp.argmax(g[0, -1]).astype(jnp.int32)
    out_mtp = [store_lane(mtp[0], m1, slot)]
    if k == 2:
        y1_all = jnp.concatenate(y1s, axis=1)  # (1, padded - 1, D)
        m2 = extract_lane(mtp[1], slot)
        head2_end = max(padded - 2, 1)
        for cs in range(0, head2_end, step):
            ce = min(cs + step, head2_end)
            nxt = jax.lax.slice_in_dim(toks, cs + 2, ce + 2, axis=1)
            _, _, m2, _ = mtp_head_apply(
                mcfg, params, moe_state, y1_all[:, cs:ce], nxt,
                jnp.broadcast_to(jnp.arange(cs, ce), (1, ce - cs)),
                cache=m2, attend_len=ce, head=2,
            )
        pos_a = jnp.clip(length - 2, 0, padded - 2)
        y_a = jax.lax.dynamic_slice(
            y1_all, (0, pos_a, 0), (1, 1, y1_all.shape[2])
        )
        y_pair = jnp.concatenate([y_a, y1_last], axis=1)
        nxt_pair = jnp.stack([first32, d1])[None, :]
        poss = jnp.stack([pos_a, pos_a + 1])[None, :]
        g2, _, m2, _ = mtp_head_apply(
            mcfg, params, moe_state, y_pair, nxt_pair, poss, cache=m2,
            head=2,
        )
        d2 = jnp.argmax(g2[0, -1]).astype(jnp.int32)
        out_mtp.append(store_lane(mtp[1], m2, slot))
        drafts = jnp.stack([d1, d2])
    else:
        drafts = d1[None]
    return caches, tuple(out_mtp), first[0], logprob[0], drafts, ok


class ServeEngine:
    """Long-lived continuous-batching engine over one decoder model.

    >>> eng = ServeEngine(model, params, ServeConfig(n_slots=4))
    >>> reqs = [eng.submit(p, max_new_tokens=64) for p in prompts]
    >>> eng.run()              # drain: step() until queue + slots empty
    >>> reqs[0].tokens         # per-request generated ids

    `submit` is non-blocking (admission control may mark the request
    ``rejected``); `step()` is one scheduler iteration and may be driven
    by an external loop that interleaves new submissions — that is the
    point of continuous batching.

    Per-request sampling rides `submit(..., params=SamplingParams(...))`
    (default greedy); there is no engine-wide sampler any more — the mix
    of greedy and stochastic requests shares the same compiled programs.
    `detokenize` (token ids -> text) is only needed when requests use
    stop STRINGS; stop token-id sets and everything else work without it.
    """

    def __init__(
        self,
        model,
        params,
        config: ServeConfig | None = None,
        *,
        extra_variables: dict | None = None,
        metrics_window: int = 4096,
        detokenize=None,
    ):
        cfg = config or ServeConfig()
        limit = getattr(model, "max_positions", None)
        if limit is not None and cfg.max_len > limit:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's max positions "
                f"{limit}"
            )
        self.model = model
        self.config = cfg
        self.detokenize = detokenize
        self.variables = {"params": params, **(extra_variables or {})}
        if cfg.prefix_sched and not cfg.prefix_cache:
            raise ValueError(
                "prefix_sched orders admission by cached-prefix match "
                "length, which needs prefix_cache=True — without the radix "
                "tree the knob would silently degrade to plain FIFO"
            )
        self.metrics = ServeMetrics(window=metrics_window)
        # flight recorder + anomaly monitor (both None when tracing is
        # off: every hot-path hook below is a single `is not None` check).
        # The recorder shares the latency metrics' patchable clock so
        # trace-summary phase sums equal measured TTFT + decode wall.
        self.trace = None
        self._mon = None
        # rolling retrospective (metrics/timeseries.py): sampled from
        # step() when the interval elapses — created BEFORE the anomaly
        # monitor so every dump can carry the preceding window
        self.timeseries = None
        if cfg.timeseries:
            from solvingpapers_tpu.metrics.timeseries import TimeSeriesStore

            self.timeseries = TimeSeriesStore(
                capacity=cfg.timeseries_capacity,
                interval_s=cfg.timeseries_interval_s,
                clock=smetrics.now,
            )
        if cfg.trace:
            from solvingpapers_tpu.metrics.trace import (
                AnomalyMonitor,
                FlightRecorder,
            )

            self.trace = FlightRecorder(
                capacity=cfg.trace_capacity, clock=smetrics.now
            )
            if cfg.trace_dump_path:
                self._mon = AnomalyMonitor(
                    self.trace, cfg.trace_dump_path,
                    snapshot_fn=self.metrics.snapshot,
                    last_n=cfg.trace_dump_events,
                    slow_step_factor=cfg.trace_slow_step_factor,
                    reject_burst=cfg.trace_reject_burst,
                    timeseries_fn=(self.timeseries.doc
                                   if self.timeseries is not None
                                   else None),
                )
        elif cfg.trace_dump_path:
            raise ValueError(
                "trace_dump_path dumps the flight recorder's last events "
                "on anomalies, which needs trace=True — without the ring "
                "a dump would hold nothing"
            )
        # TraceAnnotation scopes label the prefill/decode/splice programs
        # inside XLA profiles AND the flight recorder's own timeline
        self._annotate = cfg.trace or cfg.profile_dir is not None
        self._step_idx = 0
        self._profiling = False
        self._profile_done = cfg.profile_dir is None
        self._paged = cfg.paged
        # quantized KV storage (ops/quant.py; see the ServeConfig knob
        # block): the pool payload becomes int8 + per-block scales, the
        # jitted programs dequantize on read / quantize on write, and
        # kv_exact requests ride full-precision sidecar lanes inside the
        # same compiled programs
        self._quant = cfg.kv_quant is not None
        if cfg.kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be 'int8' or None, got {cfg.kv_quant!r}"
            )
        if cfg.kv_quant_block < 1:
            raise ValueError(
                f"kv_quant_block must be >= 1, got {cfg.kv_quant_block}"
            )
        if cfg.kv_exact_lanes < 0:
            raise ValueError(
                f"kv_exact_lanes must be >= 0, got {cfg.kv_exact_lanes}"
            )
        if cfg.kv_exact_lanes and not self._quant:
            raise ValueError(
                "kv_exact_lanes books full-precision sidecar lanes for "
                "kv_exact requests inside a QUANTIZED pool, which needs "
                "kv_quant set — an unquantized pool is exact everywhere "
                "already, so the knob would silently do nothing"
            )
        if self._quant and cfg.speculative == "mtp":
            raise ValueError(
                "kv_quant with speculative='mtp' is unsupported: the MTP "
                "drafter's head-cache lanes are a latent pool of their "
                "own that the quantized store does not cover yet — use "
                "speculative='ngram' (either pool) or drop kv_quant"
            )
        if (self._quant and cfg.prefix_cache and not cfg.paged
                and cfg.prefix_page % cfg.kv_quant_block):
            raise ValueError(
                f"prefix_page {cfg.prefix_page} is not a multiple of "
                f"kv_quant_block {cfg.kv_quant_block}: quantized lane "
                "segments carry whole scale rows, so splice offsets "
                "(page multiples) must be block-aligned"
            )
        # exact-lane sidecar bookkeeping (kv_exact requests): LIFO free
        # list of lane ids [1, kv_exact_lanes]; 0 is the trash lane a
        # quantized slot's exact-side writes fall into
        self._eidx = np.zeros(cfg.n_slots, np.int32)
        self._exact_free = list(range(cfg.kv_exact_lanes, 0, -1))
        if cfg.paged:
            page = cfg.page_size or cfg.prefix_page
            if cfg.prefix_cache and page != cfg.prefix_page:
                raise ValueError(
                    f"page_size {page} != prefix_page {cfg.prefix_page}: "
                    "zero-copy prefix sharing appends PHYSICAL page ids "
                    "to page tables, which needs tree edges and pool "
                    "pages on one granularity — set them equal (or leave "
                    "page_size None to inherit prefix_page)"
                )
            self.pool = PagedKVPool(
                model, cfg.n_slots, cfg.max_len, page,
                page_budget=cfg.page_budget, quant=cfg.kv_quant,
                exact_lanes=cfg.kv_exact_lanes,
            )
        else:
            if cfg.page_size is not None or cfg.page_budget is not None:
                raise ValueError(
                    "page_size/page_budget configure the paged pool and "
                    "need paged=True — on the lane pool they would "
                    "silently do nothing"
                )
            self.pool = KVSlotPool(
                model, cfg.n_slots, cfg.max_len, quant=cfg.kv_quant,
                quant_block=cfg.kv_quant_block,
                exact_lanes=cfg.kv_exact_lanes,
            )
        if self._quant:
            # kv-quant byte gauges ride every snapshot via the provider
            # mechanism — present iff the pool is quantized, the same
            # key-surface discipline as the paged/spec/observatory gauges
            self.metrics.add_gauge_provider(self._kv_quant_gauges)
        # speculative decoding (serve/spec.py; see the ServeConfig knob
        # block): per-slot draft-and-verify rounds inside the decode
        # program, with a host-side adaptive controller that falls back
        # to the plain block while drafts keep rejecting
        self._spec = cfg.speculative
        self._spec_ctl = None
        self._mtp_pool = None
        if cfg.speculative is None:
            if cfg.spec_rounds is not None:
                raise ValueError(
                    "spec_rounds configures the speculative decode block "
                    "and needs speculative set — without a drafter it "
                    "would silently do nothing"
                )
        else:
            if cfg.speculative not in DRAFTERS:
                raise ValueError(
                    f"speculative must be one of {DRAFTERS} (or None), "
                    f"got {cfg.speculative!r}"
                )
            if cfg.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {cfg.spec_k}")
            if cfg.spec_rounds is not None and cfg.spec_rounds < 1:
                raise ValueError(
                    f"spec_rounds must be >= 1, got {cfg.spec_rounds}"
                )
            self._spec_rounds = cfg.spec_rounds or cfg.decode_block
            if cfg.speculative == "mtp":
                heads = getattr(model.cfg, "mtp_heads", 0)
                if heads < 1:
                    raise ValueError(
                        "speculative='mtp' drafts with the model's "
                        "trained multi-token-prediction heads, which "
                        "this model does not have (mtp_heads == 0) — "
                        "use speculative='ngram' for model-free drafting"
                    )
                if cfg.paged:
                    raise ValueError(
                        "speculative='mtp' serves over the lane pool: "
                        "the MTP head cache is a per-slot lane pool of "
                        "its own (paged main-pool support is a "
                        "follow-on) — drop paged or use 'ngram'"
                    )
                if cfg.prefix_cache:
                    raise ValueError(
                        "speculative='mtp' cannot reuse cached prefixes: "
                        "a spliced prefix carries no hidden states for "
                        "the MTP head cache — drop prefix_cache or use "
                        "'ngram'"
                    )
                self._spec_k = min(cfg.spec_k, heads, 2)
                from solvingpapers_tpu.infer.cache import LatentCache

                dim = model.cfg.latent_dim + model.cfg.rope_dim
                # head lanes carry the same k+1 slack the decode
                # programs pad the main lanes with, so chunked head
                # advances never clamp-shift either
                self._mtp_pool = tuple(
                    LatentCache.init(
                        cfg.n_slots, cfg.max_len + self._spec_k + 1, dim,
                        model.cfg.compute_dtype,
                    )
                    for _ in range(self._spec_k)
                )
                self._next_drafts = np.zeros(
                    (cfg.n_slots, self._spec_k), np.int32
                )
            else:
                self._spec_k = cfg.spec_k
                if cfg.spec_ngram < 1:
                    raise ValueError(
                        f"spec_ngram must be >= 1, got {cfg.spec_ngram}"
                    )
            min_rate = cfg.spec_min_rate
            if min_rate is None:
                min_rate = max(1.0, self._spec_k / 4)
            self._spec_ctl = SpecController(
                min_rate=min_rate,
                probe_every=cfg.spec_probe_every,
            )
            self.metrics.add_gauge_provider(self._spec_gauges)
        # SLO accounting (serve/slo.py; see the ServeConfig knob block):
        # host-side per-class attainment/burn/goodput on the finish path,
        # riding the snapshot via the gauge-provider mechanism — present
        # iff slo_targets is configured, None = one branch per finish
        self._slo = None
        if cfg.slo_targets is not None:
            from solvingpapers_tpu.serve.slo import SloTracker

            self._slo = SloTracker(cfg.slo_targets,
                                   burn_window=cfg.slo_burn_window)
            self.metrics.add_gauge_provider(
                lambda: self._slo.gauges(self.metrics.elapsed_s)
            )
        # fault-tolerance layer (serve/faults.py; see the ServeConfig
        # knob block). The supervised step boundary is ALWAYS armed —
        # real NaN forwards and device runtime errors need no opt-in —
        # while the injection plane and the degradation ladder follow
        # the None-pattern.
        if cfg.fault_max_retries < 0:
            raise ValueError(
                f"fault_max_retries must be >= 0, got {cfg.fault_max_retries}"
            )
        if (cfg.fault_step_deadline_s is not None
                and not cfg.fault_step_deadline_s > 0):
            raise ValueError(
                "fault_step_deadline_s must be > 0 (or None to disarm "
                f"the watchdog), got {cfg.fault_step_deadline_s}"
            )
        self._faults = FaultPlan.from_config(cfg.fault_plan)
        # request write-ahead journal (serve/journal.py; see the
        # ServeConfig knob block). None-pattern off; opening an existing
        # path LOADS it — `recover()` is the boot step that replays it.
        if cfg.journal_strict and cfg.journal_path is None:
            raise ValueError(
                "journal_strict escalates journal I/O failures, which "
                "needs journal_path set — without a journal the knob "
                "would silently do nothing"
            )
        self.journal = None
        self._journal_degraded = False
        self._recovered_total = 0
        # trace_id -> live recovered Request: the HTTP front door's
        # Last-Event-ID reconnect surface after a restart (entries drop
        # when the dict is rebuilt on the next recover(); bounded by the
        # live set at recovery time)
        self._recovered: dict[str, Request] = {}
        if cfg.journal_path is not None:
            self.journal = Journal(
                cfg.journal_path,
                rotate_bytes=cfg.journal_rotate_bytes,
                rotate_finished=cfg.journal_rotate_finished,
            )
            self.metrics.add_gauge_provider(self._journal_gauges)
        # per-slot logits-poison row: rides the LAST row/element of every
        # packed control transfer (all-zero = bitwise no-op inside the
        # programs), written by the plan's decode-site pokes and cleared
        # after each dispatch
        self._fault_row = np.zeros(cfg.n_slots, np.int32)
        self._health = "healthy"
        self._consec_failures = 0
        self._failed_since: float | None = None
        self._last_error: str | None = None
        self._recover_at = 0.0
        self._backoff = cfg.fault_recover_backoff_s
        self._ladder = None
        if cfg.degrade:
            for knob in ("degrade_free_page_frac", "degrade_headroom_frac"):
                v = getattr(cfg, knob)
                if not 0.0 < v < 1.0:
                    raise ValueError(f"{knob} must be in (0, 1), got {v}")
            if not cfg.degrade_burn_threshold > 0:
                raise ValueError(
                    "degrade_burn_threshold must be > 0, got "
                    f"{cfg.degrade_burn_threshold}"
                )
            self._ladder = DegradationLadder(
                up_steps=cfg.degrade_up_steps,
                down_steps=cfg.degrade_down_steps,
            )
            self.metrics.add_gauge_provider(
                lambda: {"serve/degradation_rung": float(self._ladder.rung)}
            )
        # delivered-token tick weight for the scheduler's anti-starvation
        # clock: a speculative step can deliver many tokens per slot, so
        # ticking 1 per iteration would make a waiting request's budget
        # worth MORE delivered work under high acceptance — the weight
        # normalizes the wait clock to block-equivalents of delivered
        # tokens (serve/scheduler.py tick)
        self._tick_weight = 1.0
        self.prefix_cache = (
            PrefixCache(page=cfg.prefix_page, max_bytes=cfg.prefix_cache_bytes,
                        trace=self.trace,
                        pool=self.pool if cfg.paged else None)
            if cfg.prefix_cache else None
        )
        if cfg.paged:
            # page-pool occupancy/fragmentation gauges ride every
            # snapshot via the provider mechanism — present iff paged,
            # the same key-surface discipline as the observatory gauges
            self.metrics.add_gauge_provider(self._page_gauges)
        # compile & memory observatory (metrics/xla_obs.py): both None
        # when off, so every program call site is one `is not None`
        # branch — the same discipline as the flight recorder above
        self.registry = None
        self.ledger = None
        if cfg.xla_obs:
            from solvingpapers_tpu.metrics.xla_obs import (
                CompileRegistry,
                HBMLedger,
                pytree_bytes,
            )

            self.registry = CompileRegistry(
                trace=self.trace, monitor=self._mon,
                storm_k=cfg.obs_storm_k,
                storm_window_s=cfg.obs_storm_window_s,
                clock=smetrics.now,
                # the per-op anatomy ledger rides the observatory: the
                # parse is compile-time-only, and the armed steady-state
                # cost is held to the same paired-bench <= 2% budget
                # (BENCH_serve.json anatomy_overhead_pct)
                anatomy=True,
                hlo_dir=cfg.obs_hlo_dir,
            )
            if not cfg.paged:
                # the lane pool owns jitted splice/extract programs and
                # routes them through the registry; the paged pool has
                # NONE (sharing is host-side bookkeeping — the absence
                # of a splice_program in the registry is the zero-copy
                # acceptance check)
                self.pool.registry = self.registry
            self.ledger = HBMLedger(capacity_bytes=cfg.obs_capacity_bytes)
            # params are fixed for the engine's lifetime: account once
            self.ledger.register("params", pytree_bytes(self.variables))
            self.ledger.register("kv_pool", lambda: self.pool.nbytes)
            if self._mtp_pool is not None:
                # the MTP drafter's head-cache lanes are a real pool of
                # their own (latent_dim+rope_dim per position per head)
                self.ledger.register(
                    "mtp_cache", pytree_bytes(self._mtp_pool)
                )
            if self.prefix_cache is not None and not cfg.paged:
                # paged trees hold REFERENCES into the fixed pool — their
                # bytes are already inside kv_pool; a separate ledger
                # entry would double-count the same HBM
                self.ledger.register(
                    "prefix_cache", lambda: self.prefix_cache.bytes_held
                )
            self.ledger.temp_fn = self.registry.max_temp_bytes
            self.metrics.add_gauge_provider(self.registry.gauges)
            self.metrics.add_gauge_provider(self.ledger.gauges)
        self.status = None
        self.scheduler = FIFOScheduler(
            max_waiting=cfg.max_waiting,
            decode_priority=cfg.decode_priority,
            max_prefills_per_step=cfg.max_prefills_per_step,
            max_wait_steps=cfg.max_wait_steps,
            prefer_cached=cfg.prefix_sched,
            prefix_lookup=self._match_len if self.prefix_cache else None,
            can_admit=(self._can_admit
                       if cfg.paged or self._exact_free else None),
            trace=self.trace,
        )
        self._slot_req: list[Request | None] = [None] * cfg.n_slots
        # host-side numpy mirrors of per-slot decode state: shipped to the
        # device as ONE packed array per jitted call — eager .at[].set
        # bookkeeping was half the drain time on small models
        self._toks = np.zeros(cfg.n_slots, np.int32)
        self._pos = np.zeros(cfg.n_slots, np.int32)
        # slot-major SamplingParams mirrors, packed into the jitted calls
        # as traced control arrays (serve/sampling.py). Free lanes rest at
        # the greedy row so an all-greedy batch rides fused_sample's
        # sort-free fast path.
        self._samp_f = np.tile(
            np.asarray(GREEDY_ROW, np.float32)[:, None], (1, cfg.n_slots)
        )
        # grammar allow-lists, slot-major (-1 = unconstrained): refreshed
        # from each constrained request's stepper before every program
        # call, riding the packed int control transfers
        self._allow = np.full((cfg.n_slots, cfg.sample_cap), -1, np.int32)
        self._top_k = np.zeros(cfg.n_slots, np.int32)
        self._seed = np.full(cfg.n_slots, -1, np.int32)
        self._need_lp = np.zeros(cfg.n_slots, np.int32)
        self._rng = jax.random.key(cfg.seed)  # base key; folded per call
        self._rng_step = 0
        self._last_emit = np.zeros(cfg.n_slots)  # per-slot last emit time
        # deadline-bearing requests currently in the waiting queue: step()
        # only scans the queue for expiries when this is nonzero, so
        # deadline-free traffic pays nothing on the dispatch-bound host
        # loop (updated at submit / admit / cancel / purge)
        self._waiting_deadlines = 0
        # live status endpoint LAST: its handler threads read scheduler /
        # slot state, so serving must not start until every piece of
        # engine state above exists (a probe hitting the construction
        # window would 500). Useful with or without the observatory —
        # /statusz simply omits the compile/mem sections when it's off.
        if cfg.status_port is not None:
            from solvingpapers_tpu.metrics.http import StatusServer

            self.status = StatusServer(
                self.statusz,
                # prom_snapshot: the pull path renders the latency
                # histograms as native _bucket/_sum/_count series
                lambda: (self._step_idx, self.metrics.prom_snapshot()),
                host=cfg.status_host, port=cfg.status_port,
                # /healthz answers 503 while the engine is unhealthy
                health_fn=lambda: self.health,
                timeseries_fn=(self.timeseries.doc
                               if self.timeseries is not None else None),
            )

    # ------------------------------------------------------------- submit

    def submit(
        self,
        prompt,
        max_new_tokens: int = 64,
        eos_id=_UNSET,
        params: SamplingParams | None = None,
        deadline_s: float | None = None,
        grammar=None,
        stream_cb=None,
        trace_id: str | None = None,
    ) -> Request:
        """Enqueue one request; returns its live handle immediately.

        `params` attaches per-request SamplingParams (default greedy;
        ``params.max_tokens`` overrides `max_new_tokens` when set).
        `deadline_s` is a relative deadline: a request still waiting or
        decoding `deadline_s` seconds after submit finishes "timeout" at
        the next scheduler iteration / block boundary.

        `grammar` constrains decoding to a formal grammar (one
        `serve.grammar.JsonStepper` per request — it is stateful): every
        draw is restricted to the stepper's allowed-token list via the
        traced allow-mask, and the stream finishes ("stop") when the
        grammar accepts a complete document. EOS is not meaningful
        mid-document, so a grammar request must not also carry an
        `eos_id` (the engine default is ignored; an explicit one
        raises). `stream_cb(request, n_new, finished)` is called on the
        engine thread after every token append and at finish — the
        HTTP front door's streaming hook (see `Request.stream_cb`).

        Bad inputs raise `ValueError` HERE, host-side — never inside a
        traced program: non-integer or non-1-D prompts, empty prompts,
        budgets < 1, prompts beyond the engine capacity, non-positive
        deadlines, stop strings without a `detokenize` callable, a
        grammar alongside an explicit eos_id, and a budget too small
        for the grammar's shortest complete document.

        `trace_id` is the request's durable identity (the HTTP front
        door passes its X-Request-Id): it keys the write-ahead journal
        record and the Last-Event-ID resume surface. With the journal
        on and no id supplied, one is minted — a journaled request must
        always be addressable after a restart.
        """
        arr = np.asarray(prompt)
        # size first: np.asarray([]) defaults to float64, and leading with
        # the dtype check would blame "float" ids on a prompt with no ids
        if arr.size < 1:
            raise ValueError("prompt must have at least one token")
        if arr.dtype.kind not in "iu":
            raise ValueError(
                f"prompt must be integer token ids, got dtype {arr.dtype} "
                "(cast explicitly if the values really are ids)"
            )
        if arr.ndim != 1:
            raise ValueError(
                f"prompt must be 1-D (one request's token ids), got shape "
                f"{arr.shape} — batch by submitting one request per row"
            )
        prompt = arr.astype(np.int32)
        params = params or SamplingParams()
        if params.max_tokens is not None:
            max_new_tokens = params.max_tokens
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if params.stop and self.detokenize is None:
            raise ValueError(
                "params.stop (stop strings) needs the engine constructed "
                "with a `detokenize` callable (token ids -> text); "
                "stop_token_ids work without one"
            )
        if params.top_k > self.config.sample_cap:
            raise ValueError(
                f"top_k {params.top_k} exceeds ServeConfig.sample_cap "
                f"{self.config.sample_cap} — the engine samples inside the "
                "top sample_cap logits; raise the cap (costlier decode "
                "steps) or lower top_k"
            )
        if (params.kv_exact and self._quant
                and not self.config.kv_exact_lanes):
            raise ValueError(
                "kv_exact requests need full-precision sidecar lanes on a "
                "quantized pool — construct the engine with "
                "ServeConfig.kv_exact_lanes >= 1 (on an unquantized "
                "engine kv_exact is a no-op and always accepted)"
            )
        if params.slo is not None:
            if self._slo is None:
                raise ValueError(
                    "params.slo tags the request's SLO class, which needs "
                    "ServeConfig.slo_targets configured — without the "
                    "tracker the tag would silently account to nothing"
                )
            if params.slo not in self._slo.targets:
                raise ValueError(
                    f"unknown SLO class {params.slo!r}: "
                    f"ServeConfig.slo_targets defines "
                    f"{sorted(self._slo.targets)}"
                )
        total = prompt.size + max_new_tokens
        limit = getattr(self.model, "max_positions", None)
        cap = min(self.config.max_len, limit or self.config.max_len)
        if total > cap:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds the engine capacity {cap} "
                "(min of ServeConfig.max_len and the model's max positions)"
            )
        if grammar is not None:
            if eos_id is not _UNSET and eos_id is not None:
                raise ValueError(
                    "a grammar-constrained request cannot carry an eos_id: "
                    "EOS is only legal at a complete document, where the "
                    "grammar finishes the stream itself"
                )
            eos_id = None  # the engine default must not leak in either
            min_close = getattr(grammar, "min_close", 0)
            if max_new_tokens < min_close:
                raise ValueError(
                    f"max_new_tokens {max_new_tokens} cannot complete the "
                    f"grammar's shortest document ({min_close} tokens) — "
                    "the constrained stream would be cut mid-structure"
                )
        req = Request(
            prompt=prompt,
            max_new_tokens=max_new_tokens,
            eos_id=self.config.eos_id if eos_id is _UNSET else eos_id,
            params=params,
            grammar=grammar,
            stream_cb=stream_cb,
        )
        req.trace_id = trace_id
        if deadline_s is not None:
            req.deadline = req.submit_time + deadline_s
        # fault boundary: an unhealthy engine is draining — it must not
        # book slots it cannot serve. Past the recovery backoff the next
        # submission re-arms it (the pool was rebuilt at the unhealthy
        # transition, so recovery is a host-side state flip).
        if self._health == "unhealthy":
            if smetrics.now() >= self._recover_at:
                self._recover()
            else:
                req.state = REJECTED
                req.reject_reason = "unhealthy"
                self.metrics.record_reject()
                if self.trace is not None:
                    self.trace.instant("reject", "request", "queue",
                                       req=req.id, ts=req.submit_time,
                                       reason="unhealthy")
                return req
        # degradation ladder: load-shed admissions by SLO class (batch
        # first) — the front door maps the shed to 503 with a jittered
        # Retry-After and the current rung header
        if self._ladder is not None:
            cls = req.params.slo or "standard"
            if cls in self._ladder.shed_classes():
                req.state = REJECTED
                req.reject_reason = f"shed:{cls}"
                self.metrics.record_reject()
                self.metrics.record_shed(cls)
                if self.trace is not None:
                    self.trace.instant("shed", "engine", "queue",
                                       req=req.id, ts=req.submit_time,
                                       slo=cls, rung=self._ladder.rung)
                return req
        if not self.scheduler.submit(req):
            self.metrics.record_reject()
            if self.trace is not None:
                self.trace.instant("reject", "request", "queue", req=req.id,
                                   ts=req.submit_time, prompt_len=prompt.size)
                if self._mon is not None:
                    self._mon.observe_reject()
        else:
            if req.deadline is not None:
                self._waiting_deadlines += 1
            # journal AFTER acceptance: a rejected request has no
            # durable life to replay (the write-ahead contract is
            # "accepted work survives", not "every knock on the door")
            self._journal_submit(req)
            if self.trace is not None:
                # rid: the client trace id rides the submit instant so
                # the stitched fleet export can join this replica's
                # per-request flow to the router's route/migrate spans
                # (absent on direct submits — no key, not a null)
                rid_arg = ({"rid": req.trace_id}
                           if req.trace_id is not None else {})
                self.trace.instant("submit", "request", "queue", req=req.id,
                                   ts=req.submit_time, prompt_len=prompt.size,
                                   **rid_arg)
                if self._mon is not None:
                    self._mon.observe_accept()
        return req

    def replay_submit(self, prompt, max_new_tokens: int = 64, *,
                      eos_id=_UNSET, params: SamplingParams | None = None,
                      committed=()) -> Request:
        """Shadow-traffic submission for the replay harness
        (serve/replay.py): `submit`'s full validation and admission
        with the side effects a re-serve must not have stripped out —
        no deadline is armed, no WAL records are written (the engine
        must be journal-off: shadow traffic written into a live
        journal would replay itself on the next recovery), the
        recorded `params.max_tokens` never overrides the harness's
        explicit budget (replay budgets to the RECORDED stream length
        so comparisons stay prefix-aligned), and an SLO tag this
        engine does not track is dropped instead of rejected
        (`_entry_request`'s rule: the class is accounting, not
        semantics).

        `committed` pre-loads the request with recorded tokens,
        pinning the recorded seed chain through the preemption-resume
        machinery: admission re-prefills prompt + committed[:-1],
        discards the resampled token, and the next draw lands at
        sample index ``len(committed)``. With ``max_new_tokens =
        len(committed) + 1`` the engine produces exactly ONE token,
        directly comparable to the recorded token at that offset —
        the teacher-forced cut-replay primitive. Host-side only: the
        resume path is the one recover()/adopt() already exercise, so
        a replay-less engine compiles nothing new."""
        if self.journal is not None:
            raise ValueError(
                "replay_submit needs a journal-off engine: shadow "
                "traffic must not write WAL records (build the replay "
                "engine from serve.replay.sanitize_config)"
            )
        params = params or SamplingParams()
        if params.max_tokens is not None:
            params = dataclasses.replace(params, max_tokens=None)
        if params.slo is not None and (
                self._slo is None or params.slo not in self._slo.targets):
            params = dataclasses.replace(params, slo=None)
        committed = [int(t) for t in committed]
        if committed and len(committed) >= max_new_tokens:
            raise ValueError(
                f"committed prefix ({len(committed)} tokens) must leave "
                f"budget to generate (max_new_tokens {max_new_tokens})"
            )
        req = self.submit(prompt, max_new_tokens, eos_id=eos_id,
                          params=params)
        if req.state == REJECTED:
            raise ValueError(
                "replay submission rejected "
                f"({req.reject_reason or 'queue full'}) — size the "
                "replay config's max_waiting to the corpus"
            )
        if committed:
            # pre-step is the safe window: the request is queued but
            # cannot be admitted until the owner's next step()
            req.tokens = committed
        return req

    def cancel(self, req: Request) -> None:
        """Cancel a request: a WAITING one leaves the queue and finishes
        "cancelled" immediately; an ACTIVE one keeps its lane until the
        next block boundary, where the engine discards that block's
        output, finishes it "cancelled", and frees the lane for the next
        queued request. Finished/rejected requests are a no-op."""
        if req.state == WAITING:
            if self.scheduler.remove(req):
                if req.deadline is not None:
                    self._waiting_deadlines -= 1
                self._finish_unadmitted(req, "cancelled", smetrics.now())
        elif req.state == ACTIVE:
            req.cancelled = True

    # --------------------------------------------------------------- step

    def has_work(self) -> bool:
        return bool(len(self.scheduler)) or self.pool.n_active > 0

    def step(self) -> list[Request]:
        """One engine iteration: admit + prefill, then one decode block.

        Returns the requests that FINISHED this iteration.

        Supervised (the fault boundary): any exception escaping the
        iteration — a real `XlaRuntimeError`, a device OOM, or an
        injected fault — is classified (`serve.faults.classify_failure`)
        and answered with a bounded pool-rebuild retry (active streams
        requeue and resume by recompute, token-exactly — the
        preemption argument); after `fault_max_retries` consecutive
        failures the engine drains to `unhealthy` (every in-flight
        stream finishes "error" with its terminal client envelope,
        /healthz flips to 503) and re-arms after a backoff. NaN/Inf
        forwards never raise: the traced finite-logits guard pins them
        to a slot, which `_quarantine` contains below the step
        boundary. A watchdog flags steps exceeding
        `fault_step_deadline_s`; the degradation ladder (if armed)
        re-evaluates its pressure signals after every step.
        """
        if self._health == "unhealthy":
            now = smetrics.now()
            if now < self._recover_at:
                # draining: no device work until the backoff elapses (a
                # tight external drive loop must not busy-spin)
                time.sleep(min(0.005, self._recover_at - now))
                self._step_idx += 1
                self._timeseries_tick()
                return []
            self._recover()
        t0 = smetrics.now()
        try:
            finished = self._step_inner()
        except Exception as exc:  # noqa: BLE001 — the fault boundary
            # no watchdog check on this path: the boundary's own
            # recovery work (pool rebuild + backoff sleep) is not a
            # wedged step — the incident is already accounted as
            # serve/fault_retries, and double-reporting it as a stall
            # would page operators twice for one failure
            finished = self._systemic_failure(exc)
        else:
            ddl = self.config.fault_step_deadline_s
            if ddl is not None:
                dur = smetrics.now() - t0
                if dur > ddl:
                    self._watchdog_fire(dur)
            if self._failed_since is not None:
                # first clean step after a failure episode
                self._note_recovery()
        if self._ladder is not None:
            self._ladder_step()
        self._timeseries_tick()
        return finished

    def _timeseries_tick(self) -> None:
        """Opportunistic rolling-retrospective sample: append one
        window of load gauges + per-window counter/histogram deltas
        when `timeseries_interval_s` has elapsed. Rides step() (no
        timer thread), so an idle engine stops producing windows —
        the gap in the ring IS the record of the idle stretch."""
        ts = self.timeseries
        if ts is None or not ts.due():
            return
        snap = self.metrics.snapshot()
        gauges = {
            "occupancy": round(self.pool.occupancy, 4),
            "queue_depth": float(len(self.scheduler)),
            "n_free": float(self.pool.n_free),
        }
        if getattr(self.pool, "page_budget", 0):
            gauges["pages_free"] = float(self.pool.pages_free)
        cumulative = {
            k: float(snap[k]) for k in (
                "serve/tokens_out", "serve/tokens_prefilled",
                "serve/requests_finished", "serve/requests_rejected",
                "serve/steps",
            ) if k in snap
        }
        # histogram deltas: count/sum increments per window — enough
        # to recover windowed mean latency without O(n) percentiles
        for name, h in self.metrics._latency_hists():
            cumulative[f"serve/{name}_count"] = float(h.count)
            cumulative[f"serve/{name}_sum"] = float(h.sum)
        ts.sample(gauges, cumulative)

    def _step_inner(self) -> list[Request]:
        if not self._profile_done:
            self._profile_tick()
        tr = self.trace
        t_step = smetrics.now() if tr is not None else 0.0
        finished: list[Request] = []
        now = smetrics.now()
        if self._waiting_deadlines > 0:
            expired = [r for r in self.scheduler.queue
                       if r.deadline is not None and now >= r.deadline]
            for req in expired:
                self.scheduler.remove(req)
                self._waiting_deadlines -= 1
                self._finish_unadmitted(req, "timeout", now)
                finished.append(req)
        n_admitted = 0
        if self._paged:
            self._unblock_head()
        picked = self.scheduler.pick(self.pool.n_free, self.pool.n_active)
        at = -1
        try:
            for at, req in enumerate(picked):
                if req.deadline is not None:
                    self._waiting_deadlines -= 1  # left the queue via pick
                n_admitted += 1
                if self._admit(req):
                    finished.append(req)  # prefill-only finish (eos/budget 1)
        except BaseException:
            # failure-safe admission: `pick` already popped this
            # iteration's batch off the queue, so a program failure mid
            # loop would silently LOSE the not-yet-admitted tail (the
            # raising request itself is registered in _slot_req before
            # any dispatch and the fault boundary's rebuild requeues it
            # from there). Put the tail back at the head, order
            # preserved, before the boundary sees the exception. No
            # _waiting_deadlines adjustment: the tail never reached its
            # per-request decrement above, so the counter still counts
            # it — incrementing here would double-count forever.
            for r in reversed(picked[at + 1:]):
                self.scheduler.requeue_front(r)
            raise
        decode_slots = self.pool.n_active
        if decode_slots > 0:
            finished.extend(self._decode_block())
        # anti-starvation clock in DELIVERED-TOKEN units: a speculative
        # step that committed several blocks' worth of tokens ages the
        # waiting queue proportionally (weight = max per-slot delivered /
        # decode_block, floored at 1), so a high-acceptance batch cannot
        # starve the wait budget — plain blocks keep weight 1 exactly
        self.scheduler.tick(self._tick_weight)
        self._tick_weight = 1.0
        self.metrics.record_step(self.pool.occupancy)
        # only steps that did work are traced/monitored: an external
        # serving loop may poll step() while idle, and feeding those
        # ~microsecond no-ops into the ring (spam) and the anomaly
        # monitor's rolling median would make the FIRST real step look
        # like a slow-step anomaly and dump on every step after it
        if tr is not None and (n_admitted or decode_slots or finished):
            now = smetrics.now()
            dur = now - t_step
            tr.complete(
                "step", "engine", "engine", ts=t_step, dur=dur,
                prefills=n_admitted, decode_slots=decode_slots,
                # host->device control transfers: 3 per prefill (prompt +
                # int ctl + float samp), 2 per decode call (packed state +
                # samp block) — the dispatch cost the packed mirrors bound
                transfers=3 * n_admitted + (2 if decode_slots else 0),
                device_s=round(self._dev_s, 6),
            )
            tr.counter("queue_depth", "engine", "engine", ts=now,
                       depth=len(self.scheduler))
            tr.counter("active_slots", "engine", "engine", ts=now,
                       active=self.pool.n_active)
            self._dev_s = 0.0
            # the monitor's rolling median sees only steps that ran a
            # program: purge-only steps (deadline expiries) are traced
            # above but, like idle polls, complete in ~microseconds and
            # would collapse the median until every real step looks slow
            if self._mon is not None and (n_admitted or decode_slots):
                self._mon.observe_step(dur)
        # the journal's batched durability point: ONE fsync per step
        # covering every record the step appended (submit records ride
        # the next step's sync — a kill loses at most one step's worth,
        # the same boundary tokens commit to streams at). Gated on
        # dirty so idle polls never touch the fault-plane visit counter.
        if self.journal is not None and self.journal.dirty:
            self._journal_op(self.journal.sync)
        self._step_idx += 1
        return finished

    # accumulated device time (block_until_ready-fenced program calls)
    # within the current step; only maintained while tracing
    _dev_s = 0.0

    def _profile_tick(self) -> None:
        """Open/close the jax.profiler window around engine steps
        [profile_steps[0], profile_steps[1]) — same stop-before-start
        ordering as the train loop so a window never opens empty."""
        cfg = self.config
        if self._profiling and self._step_idx >= cfg.profile_steps[1]:
            jax.profiler.stop_trace()
            self._profiling = False
            self._profile_done = True
        if (not self._profiling and not self._profile_done
                and self._step_idx >= cfg.profile_steps[0]):
            jax.profiler.start_trace(cfg.profile_dir)
            self._profiling = True

    def _scope(self, name: str):
        """TraceAnnotation around a jitted-program call when tracing or
        profiling is on (labels the program inside XLA traces), a shared
        nullcontext otherwise — ONE call site per program, so operand
        changes cannot silently diverge an annotated copy."""
        if self._annotate:
            return jax.profiler.TraceAnnotation(name)
        return self._null_scope

    _null_scope = contextlib.nullcontext()

    def stop_profile(self) -> None:
        """Close a still-open profiler window (external step() drivers
        that stop before `profile_steps[1]`); run() calls this on drain."""
        if self._profiling:
            jax.profiler.stop_trace()
            self._profiling = False
            self._profile_done = True

    # ------------------------------------------------ fault boundary

    @property
    def health(self) -> str:
        """The /healthz state machine: ``"healthy"`` -> ``"degraded"``
        (the ladder is on a rung > 0 — still serving, a load balancer
        should keep it) -> ``"unhealthy"`` (draining after persistent
        systemic failures; /healthz answers 503 until recovery).
        Reports readiness, not the raw internal flag: once the recovery
        backoff elapses the engine IS ready (the pool was rebuilt at the
        unhealthy transition; the next submission flips the flag), so
        /healthz must return to 200 then — a load balancer that dropped
        the replica on 503 routes no traffic, and a health view gated
        on traffic arriving would keep it out of rotation forever."""
        if (self._health == "unhealthy"
                and smetrics.now() < self._recover_at):
            return "unhealthy"
        if self._ladder is not None and self._ladder.rung > 0:
            return "degraded"
        return "healthy"

    @property
    def degradation_rung(self) -> int:
        """Current ladder rung (0 = normal; 0 when the ladder is off)."""
        return self._ladder.rung if self._ladder is not None else 0

    def _poke_site(self, site: str) -> int:
        """Fault-plane hook at a named hot-path site (one `is None`
        branch when disarmed). Applies host-side effects — ``stall``
        sleeps here, ``xla_error``/``oom`` raise a synthetic
        `InjectedFault` the step boundary classifies like the real
        thing — and routes logits poison: returned as the ctl code for
        prefill sites, written to the per-slot fault row for decode
        sites (cleared after the dispatch it rides)."""
        if self._faults is None:
            return 0
        code = 0
        for spec in self._faults.poke(site):
            self.metrics.record_fault_injected()
            if self.trace is not None:
                self.trace.instant("fault_injected", "engine", "engine",
                                   site=site, kind=spec.kind,
                                   slot=spec.slot)
            if spec.kind == "stall":
                time.sleep(spec.stall_s)
            elif spec.kind in ("xla_error", "oom", "io_error"):
                raise InjectedFault(spec.kind, site)
            elif spec.kind in ("nan", "inf"):
                k = FAULT_NAN if spec.kind == "nan" else FAULT_INF
                if site == "prefill":
                    code = k
                else:
                    self._fault_row[spec.slot % self.config.n_slots] = k
            # socket_reset belongs to the front door's sse_write site
        return code

    def _systemic_failure(self, exc: Exception) -> list[Request]:
        """A step escaped with an exception: the in-flight program's
        donated pool buffers are unusable, so the remedy is rebuild —
        bounded retries first (streams requeue and resume by recompute,
        token-exactly), then the draining `unhealthy` state."""
        kind = classify_failure(exc)
        err = f"{type(exc).__name__}: {exc}"
        now = smetrics.now()
        self._consec_failures += 1
        self._last_error = err
        if self._failed_since is None:
            self._failed_since = now
        if self._consec_failures <= self.config.fault_max_retries:
            # counted only when a rebuild retry is actually granted —
            # the failure that EXHAUSTS the budget is accounted as the
            # unhealthy transition below, not as a retry
            self.metrics.record_engine_retry()
        if self.trace is not None:
            self.trace.instant("engine_fault", "engine", "engine", ts=now,
                               kind=kind, error=err[:200],
                               failures=self._consec_failures)
            if self._mon is not None:
                self._mon.dump("engine_fault", failure_kind=kind,
                               error=err[:500],
                               consecutive=self._consec_failures)
        if self._consec_failures > self.config.fault_max_retries:
            return self._go_unhealthy(err)
        self._rebuild_pool(requeue=True)
        time.sleep(min(
            self.config.fault_retry_backoff_s
            * (2 ** (self._consec_failures - 1)), 2.0,
        ))
        return []

    def _go_unhealthy(self, err: str) -> list[Request]:
        """Retries exhausted: drain — every in-flight and queued request
        finishes "error" host-side (each client gets its terminal
        envelope; slots/pages/exact lanes reclaim leak-free), the pool
        rebuilds so recovery starts from fresh fully-owned buffers, and
        /healthz reports 503 until the recovery backoff elapses."""
        self._health = "unhealthy"
        now = smetrics.now()
        self._recover_at = now + self._backoff
        # doubles across consecutive unhealthy episodes; a clean step
        # (via _note_recovery) resets it
        self._backoff = min(self._backoff * 2, 30.0)
        self.metrics.record_engine_unhealthy()
        if self.trace is not None:
            self.trace.instant("unhealthy", "engine", "engine", ts=now,
                               error=err[:200],
                               recover_after_s=round(
                                   self._recover_at - now, 3))
        finished = self.force_drain("error")
        self._rebuild_pool(requeue=False)
        return finished

    def _recover(self) -> None:
        """Re-arm an unhealthy engine (the pool was rebuilt at the
        unhealthy transition, so this is a host-side state flip)."""
        self._health = "healthy"
        self._consec_failures = 0
        if self.trace is not None:
            self.trace.instant("recovered", "engine", "engine",
                               ts=smetrics.now())

    def _note_recovery(self) -> None:
        """First clean step after a failure episode: stamp the
        wall-clock recovery time (first failure -> first clean step)."""
        now = smetrics.now()
        if self._failed_since is not None:
            self.metrics.record_recovery(now - self._failed_since)
            if self.trace is not None:
                self.trace.instant(
                    "fault_recovered", "engine", "engine", ts=now,
                    recovery_s=round(now - self._failed_since, 4),
                )
        self._failed_since = None
        self._consec_failures = 0
        self._backoff = self.config.fault_recover_backoff_s

    # ----------------------------------------------- write-ahead journal

    def _journal_op(self, fn, *args) -> None:
        """Run one journal operation inside the durability-failure
        boundary: the fault plane's ``journal_write`` site pokes first
        (an ``io_error`` spec raises here, exactly where a real disk
        failure would), and any I/O failure degrades the engine to
        journal-off with ONE warning and the serve/journal_degraded
        gauge — serving must survive losing its journal — unless
        `journal_strict` deliberately lets the failure propagate."""
        if self.journal is None or self._journal_degraded:
            return
        try:
            self._poke_site("journal_write")
            fn(*args)
        except (JournalError, OSError, InjectedFault) as exc:
            if isinstance(exc, InjectedFault) and exc.kind != "io_error":
                raise
            if self.config.journal_strict:
                raise
            self._journal_degraded = True
            warnings.warn(
                f"write-ahead journal failed ({type(exc).__name__}: "
                f"{exc}) — degrading to journal-off: serving continues, "
                "crash recovery and stream resumption are LOST from "
                "here (set ServeConfig.journal_strict to fail loudly "
                "instead)",
                stacklevel=2,
            )
            if self.trace is not None:
                self.trace.instant("journal_degraded", "engine", "engine",
                                   error=str(exc)[:200])

    def _journal_submit(self, req: Request) -> None:
        if self.journal is None:
            return
        if req.trace_id is None or self.journal.is_live(req.trace_id):
            # a journaled request must be addressable after a restart —
            # and a client RE-USING a still-live X-Request-Id must not
            # merge two streams' commits into one journal record (the
            # in-memory registry keeps its documented last-wins
            # behavior; the duplicate gets a fresh durable id)
            req.trace_id = uuid.uuid4().hex
        # grammar steppers are host state the journal cannot replay:
        # such a request is journaled for INSPECTION but flagged, and
        # recovery finishes it "error" instead of resuming it
        self._journal_op(
            self.journal.append_submit, req.trace_id, req.prompt,
            req.max_new_tokens, req.eos_id,
            dataclasses.asdict(req.params), req.submit_time,
            req.grammar is not None,
            None if req.deadline is None
            else max(req.deadline - req.submit_time, 1e-3),
        )

    def _journal_commit(self, req: Request, tokens) -> None:
        if self.journal is not None and len(tokens):
            self._journal_op(self.journal.append_commit, req.trace_id,
                             tokens)

    def _journal_finish(self, req: Request) -> None:
        if self.journal is not None:
            self._journal_op(self.journal.append_finish, req.trace_id,
                             req.finish_reason or "unknown", {
                                 "prompt_tokens": int(req.prompt.size),
                                 "completion_tokens": len(req.tokens),
                             })

    def _journal_gauges(self) -> dict[str, float]:
        """Journal gauges riding every metrics snapshot (registered iff
        `journal_path` — the present-iff-enabled key-surface contract
        of the paged/spec/observatory gauges)."""
        s = self.journal.stats()
        return {
            "serve/journal_records": float(s["records"]),
            "serve/journal_bytes": float(s["bytes_written"]),
            "serve/journal_rotations": float(s["rotations"]),
            "serve/journal_fsync_s": s["fsync_s"],
            "serve/journal_live": float(s["live"]),
            "serve/journal_degraded": float(self._journal_degraded),
            "serve/recovered_requests": float(self._recovered_total),
        }

    def _entry_request(self, e) -> tuple[Request | None, str | None]:
        """Validate + materialize one live journal entry as a resumable
        `Request` carrying its committed tokens — the shared core of
        `recover()` (crash restart) and `adopt()` (fleet migration).

        Returns ``(request, None)`` for an entry this engine can honor:
        the request is WAITING with its deadline re-armed RELATIVE from
        now (absolute deadlines cannot cross a process/replica boundary
        — monotonic clocks differ), or already FINISHED with its stop
        reason when the committed stream satisfies a finish condition
        (the crash/drain landed between the final commit and its finish
        record). Returns ``(None, reason)`` for an entry this engine
        cannot resume token-exactly: grammar requests (host stepper
        state), an unparseable params record, a prompt beyond this
        engine's capacity, stop strings without `detokenize`, or
        kv_exact without sidecar lanes. An SLO class this engine does
        not track is dropped, not fatal — the class is accounting, not
        semantics."""
        limit = getattr(self.model, "max_positions", None)
        cap = min(self.config.max_len, limit or self.config.max_len)
        err = None
        params = None
        if e.grammar:
            err = "grammar stepper state is not journaled"
        else:
            try:
                p = dict(e.params)
                p["stop_token_ids"] = tuple(
                    p.get("stop_token_ids") or ())
                p["stop"] = tuple(p.get("stop") or ())
                params = SamplingParams(**p)
            except (TypeError, ValueError) as exc:
                err = f"unreplayable params: {exc}"
        if err is None:
            if len(e.prompt) < 1 or \
                    len(e.prompt) + e.max_new_tokens > cap:
                err = f"beyond this engine's capacity {cap}"
            elif params.stop and self.detokenize is None:
                err = "stop strings need a detokenize callable"
            elif (params.kv_exact and self._quant
                  and not self.config.kv_exact_lanes):
                err = "kv_exact needs exact sidecar lanes"
            elif params.slo is not None and (
                self._slo is None or params.slo not in self._slo.targets
            ):
                # the SLO class is accounting, not semantics: keep
                # the stream, drop the untracked tag
                params = dataclasses.replace(params, slo=None)
        if err is not None:
            return None, err
        req = Request(
            prompt=np.asarray(e.prompt, np.int32),
            max_new_tokens=e.max_new_tokens,
            eos_id=e.eos_id, params=params,
        )
        req.trace_id = e.rid
        req.tokens = [int(t) for t in e.tokens]
        if e.deadline_s is not None:
            # absolute deadlines cannot cross a restart (monotonic
            # clocks reset), so the recovered request re-arms its
            # ORIGINAL relative budget from now — bounded again,
            # not unbounded
            req.deadline = req.submit_time + e.deadline_s
        reason = (self._stop_reason(req, req.tokens[-1])
                  if req.tokens else None)
        if (reason is None and req.tokens and params.stop
                and self._stop_string_at(req, 0) is not None):
            # commits are written AFTER stop-string truncation, so
            # a committed stream never extends past a match — any
            # match here means the stream was complete at the crash
            reason = "stop"
        if reason is not None:
            req.state = FINISHED
            req.finish_reason = reason
            req.finish_time = smetrics.now()
        return req, None

    def adopt(self, entry) -> Request:
        """Adopt a live journal entry from ANOTHER replica's journal —
        the fleet router's stream-migration primitive (serve/fleet.py
        `FleetRouter.drain`). The drained replica force-finishes the
        stream ``"migrated"``; this engine continues it through the same
        preemption-resume machinery `recover()` uses (re-prefill prompt
        + committed tokens, discard the resampled token — TOKEN-EXACT
        for greedy and seeded plain-decode streams, the journal
        contract). Call with this engine's step lock held (the
        EngineLoop lock): adoption touches the scheduler queue and the
        journal the engine thread also owns.

        The adopted request is journaled into THIS engine's journal
        when it has one (submit + committed prefix — a crash after the
        migration recovers the stream HERE), registered in the
        recovered set so Last-Event-ID reconnects resolve through the
        same path as a crash restart, and requeued at the FRONT of the
        queue (it predates everything waiting — the same FIFO-survives
        rule as `recover()`; when migrating several entries, adopt them
        newest-first so the oldest ends at the head). Note the journal
        submit re-keys `trace_id` if this engine already has a live
        journal entry under the same id — read the id back from the
        returned request. An entry whose committed stream already
        satisfies a finish condition comes back FINISHED (journaled
        through to its finish record) instead of requeued.

        Raises ValueError for an entry this engine cannot resume
        token-exactly (see `_entry_request`) — the caller decides how
        to surface the failed migration; nothing is enqueued."""
        req, err = self._entry_request(entry)
        if err is not None:
            raise ValueError(
                f"journal entry {entry.rid} cannot be adopted ({err})")
        self._journal_submit(req)
        self._journal_commit(req, req.tokens)
        if req.done:
            self._journal_finish(req)
        else:
            # bypasses max_waiting like requeue_front's preemption case:
            # the stream was already admitted once, on the drained peer
            self.scheduler.requeue_front(req)
            if req.deadline is not None:
                self._waiting_deadlines += 1
        self._recovered[req.trace_id] = req
        self._recovered_total += 1
        if self.trace is not None:
            self.trace.instant(
                "journal_adopt", "engine", "engine", rid=req.trace_id,
                committed=len(req.tokens), done=req.done,
            )
        return req

    def recover(self) -> list[Request]:
        """Replay the journal's unfinished entries through the
        preemption-resume machinery: each live entry becomes a WAITING
        request carrying its committed tokens; admission re-prefills
        prompt + committed[:-1], discards the resampled token and
        continues decoding — TOKEN-EXACT vs an uninterrupted run for
        greedy streams (any configuration) and seeded stochastic
        streams on the plain decode path (seeded chains fold only
        (seed, sample index); tests/test_journal.py pins it across
        pools and kv_quant; under speculation stochastic streams are
        distribution-exact, the live-preemption contract). Call ONCE
        at boot, before the first step.

        Entries the new engine cannot honor resume-exactly — grammar
        requests (host stepper state), stop-string requests on an
        engine without `detokenize`, kv_exact without sidecar lanes, a
        prompt beyond this engine's capacity, or an unparseable params
        record — finish ``"error"`` in the journal instead of being
        silently dropped. An entry whose committed stream already
        satisfies a stop condition (the crash landed between its final
        commit and its finish record) is finished with that reason.
        Returns the requests actually requeued (oldest first); the
        journal is compacted to exactly that live set."""
        if self.journal is None:
            raise ValueError(
                "recover() replays the write-ahead journal, which needs "
                "ServeConfig.journal_path set"
            )
        resumed: list[Request] = []
        for e in self.journal.live_entries():
            usage = {"prompt_tokens": len(e.prompt),
                     "completion_tokens": len(e.tokens)}
            req, err = self._entry_request(e)
            if err is not None:
                warnings.warn(
                    f"journal entry {e.rid} cannot be recovered ({err}) "
                    "— finishing it \"error\"", stacklevel=2,
                )
                self._journal_op(self.journal.append_finish, e.rid,
                                 "error", usage)
                continue
            if req.done:
                # the crash landed between the final commit and its
                # finish record: the stream is already complete
                self._journal_op(self.journal.append_finish, e.rid,
                                 req.finish_reason, usage)
                continue
            resumed.append(req)
        # oldest ends at the queue head: FIFO order survives the crash
        for req in reversed(resumed):
            self.scheduler.requeue_front(req)
            if req.deadline is not None:
                self._waiting_deadlines += 1
        self._recovered = {r.trace_id: r for r in resumed}
        self._recovered_total = len(resumed)
        # compact to exactly the live set (and make it durable): a
        # recovered journal starts O(active), not O(crash history)
        self._journal_op(self.journal.compact)
        self._journal_op(self.journal.sync)
        if self.trace is not None:
            self.trace.instant("journal_recover", "engine", "engine",
                               resumed=len(resumed))
        return resumed

    def _rebuild_pool(self, requeue: bool) -> None:
        """Replace the device pool with fresh buffers after a systemic
        failure (a raising jitted call may have consumed its donated
        inputs — the old pytree cannot be trusted). With `requeue`,
        every active stream returns to the queue head ordered oldest-
        first and resumes by recompute: cached KV depends only on token
        ids and seeded chains fold only (seed, sample index), so
        resumed streams are token-exact (the preemption argument). The
        prefix cache is dropped wholesale — lane segments may alias
        rebuilt state and paged trees hold page ids into the dead pool."""
        cfg = self.config
        if requeue:
            active = [r for r in self._slot_req if r is not None]
            # youngest requeued first so the OLDEST ends at the head
            active.sort(key=lambda r: r.admit_time or 0.0, reverse=True)
            for req in active:
                if self._paged and req.slot is not None:
                    req.pages_held = max(
                        req.pages_held, int(self.pool.n_alloc[req.slot])
                    )
                req.slot = None
                self.scheduler.requeue_front(req)
                if req.deadline is not None:
                    self._waiting_deadlines += 1
        self._slot_req = [None] * cfg.n_slots
        self._toks[:] = 0
        self._pos[:] = 0
        self._samp_f[:] = np.asarray(GREEDY_ROW, np.float32)[:, None]
        self._allow[:] = -1
        self._top_k[:] = 0
        self._seed[:] = -1
        self._need_lp[:] = 0
        self._fault_row[:] = 0
        self._eidx[:] = 0
        self._exact_free = list(range(cfg.kv_exact_lanes, 0, -1))
        if self._paged:
            page = cfg.page_size or cfg.prefix_page
            self.pool = PagedKVPool(
                self.model, cfg.n_slots, cfg.max_len, page,
                page_budget=cfg.page_budget, quant=cfg.kv_quant,
                exact_lanes=cfg.kv_exact_lanes,
            )
        else:
            self.pool = KVSlotPool(
                self.model, cfg.n_slots, cfg.max_len, quant=cfg.kv_quant,
                quant_block=cfg.kv_quant_block,
                exact_lanes=cfg.kv_exact_lanes,
            )
            if self.registry is not None:
                self.pool.registry = self.registry
        if self._mtp_pool is not None:
            from solvingpapers_tpu.infer.cache import LatentCache

            dim = self.model.cfg.latent_dim + self.model.cfg.rope_dim
            self._mtp_pool = tuple(
                LatentCache.init(
                    cfg.n_slots, cfg.max_len + self._spec_k + 1, dim,
                    self.model.cfg.compute_dtype,
                )
                for _ in range(self._spec_k)
            )
            self._next_drafts[:] = 0
        if self.prefix_cache is not None:
            self.prefix_cache = PrefixCache(
                page=cfg.prefix_page, max_bytes=cfg.prefix_cache_bytes,
                trace=self.trace,
                pool=self.pool if cfg.paged else None,
            )
            self.metrics.record_prefix_state(0, self.prefix_cache.evictions)

    def _watchdog_fire(self, dur_s: float) -> None:
        """A step exceeded the absolute deadline: count it, stamp a
        trace instant, and (when the anomaly dumper is armed) dump the
        flight-recorder tail for the post-mortem."""
        self.metrics.record_watchdog_stall(dur_s)
        if self.trace is not None:
            self.trace.instant(
                "watchdog_stall", "engine", "engine",
                step_s=round(dur_s, 4),
                deadline_s=self.config.fault_step_deadline_s,
            )
            if self._mon is not None:
                self._mon.dump(
                    "watchdog_stall", step_s=round(dur_s, 4),
                    deadline_s=self.config.fault_step_deadline_s,
                )

    def _quarantine(self, req: Request, now: float) -> Request:
        """Blast-radius containment for a NaN/Inf-poisoned slot: the
        block's tokens are discarded (drawn from non-finite logits), the
        slot's lane/pages are SCRUBBED to zero before release (masked
        attention annihilates finite stale values exactly, but
        ``0 * NaN`` is NaN — an unscrubbed poisoned lane would leak into
        its next occupant), and the request finishes "error". Every
        other stream — computed in the same program call from its own
        per-slot lane — continues byte-identically."""
        slot = req.slot
        self.metrics.record_quarantine()
        # a prefill-poisoned request has no first token: _finish closes
        # its lifecycle spans with a zero-width prefill phase
        if self.trace is not None:
            self.trace.instant("quarantine", "engine", f"slot{slot}",
                               req=req.id, ts=now, tokens=len(req.tokens))
            if self._mon is not None:
                self._mon.dump("quarantine", req=req.id, slot=slot)
        self._scrub_slot(slot)
        self._finish(req, "error", now)
        self._notify(req, 0)
        return req

    def _scrub_slot(self, slot: int) -> None:
        """Zero a poisoned slot's device state before its storage is
        reused (see `_quarantine`). Paged pools scrub only the slot's
        exclusively-owned pages — shared prefix pages hold KV written
        strictly before the poisoned step and other holders still read
        them — plus the trash page, where the poisoned slot's masked
        overshoot writes land."""
        eidx = jnp.int32(int(self._eidx[slot]) if self._quant else 0)
        if self._paged:
            n = int(self.pool.n_alloc[slot])
            own = [int(p) for p in self.pool.table[slot, :n]
                   if self.pool.refcount[p] == 1]
            row = np.full(self.pool.pages_per_lane + 1, TRASH_PAGE,
                          np.int32)
            row[:len(own)] = own
            self.pool.phys = scrub_pages_program(
                self.pool.phys, jnp.asarray(row), eidx
            )
        else:
            self.pool.caches = scrub_lane_program(
                self.pool.caches, jnp.int32(slot), eidx
            )
            if self._mtp_pool is not None:
                self._mtp_pool = tuple(
                    scrub_lane_program(c, jnp.int32(slot), jnp.int32(0))
                    for c in self._mtp_pool
                )

    def force_drain(self, reason: str = "cancelled") -> list[Request]:
        """Finish every in-flight and queued request host-side — no
        device work, so it cannot hang on a wedged program. The
        bounded-shutdown backstop (`close`) and the unhealthy drain
        (`reason="error"`); slots, pages and exact lanes reclaim through
        the ordinary finish paths, so the pool drains leak-free."""
        now = smetrics.now()
        finished: list[Request] = []
        for req in [r for r in self._slot_req if r is not None]:
            self._finish(req, reason, now)
            self._notify(req, 0)
            finished.append(req)
        for req in list(self.scheduler.queue):
            self.scheduler.remove(req)
            self._finish_unadmitted(req, reason, now)
            finished.append(req)
        self._waiting_deadlines = 0
        return finished

    def _ladder_step(self) -> None:
        """One degradation-ladder evaluation (per engine step): gather
        the pressure signals, move at most one rung (hysteresis lives in
        the ladder), and apply the current rung's effects. Rung 1 sheds
        a few prefix-cache leaves per step (gradual — a short spike must
        not destroy the whole cache); rung 2 additionally holds
        speculation; rungs 3/4 shed admissions in `submit`."""
        cfg = self.config
        reasons = []
        if self._paged and (self.pool.pages_free
                            < cfg.degrade_free_page_frac
                            * self.pool.page_budget):
            reasons.append("pages")
        if self.ledger is not None and self.ledger.capacity_bytes:
            peak = self.ledger.projected_peak_bytes()
            if peak > (1.0 - cfg.degrade_headroom_frac) \
                    * self.ledger.capacity_bytes:
                reasons.append("hbm")
        if self._slo is not None:
            for cls in self._slo.targets:
                if self._slo.burn_rate(cls) > cfg.degrade_burn_threshold:
                    reasons.append(f"burn:{cls}")
                    break
        new = self._ladder.observe(bool(reasons), reasons)
        if new is not None:
            self.metrics.record_degrade_transition()
            if self.trace is not None:
                self.trace.instant(
                    "degrade", "engine", "engine", rung=new,
                    name=self._ladder.name,
                    reasons=",".join(reasons) or "clear",
                )
        rung = self._ladder.rung
        if rung >= 1 and self.prefix_cache is not None:
            shed = 0
            while shed < 4 and self.prefix_cache.evict_one():
                shed += 1
            if shed:
                self.metrics.record_prefix_state(
                    self.prefix_cache.bytes_held,
                    self.prefix_cache.evictions,
                )
        if rung >= 2 and self._spec_ctl is not None:
            self._spec_ctl.hold(2)

    def statusz(self) -> dict:
        """The /statusz document: live engine state assembled from
        host-side mirrors only (safe to call from the status server's
        request threads while the engine steps)."""
        d = {
            # build identity FIRST: a scraped replica must be
            # identifiable (which build, which jax, how long up) before
            # any of its numbers are aggregated — ROADMAP item 2's
            # per-replica prerequisite
            "build": buildinfo.build_info(),
            "engine": {
                "n_slots": self.config.n_slots,
                "n_free": self.pool.n_free,
                "occupancy": self.pool.occupancy,
                "queue_depth": len(self.scheduler),
                "step": self._step_idx,
                "max_len": self.config.max_len,
                "decode_block": self.config.decode_block,
            },
            "slots": [
                {
                    "slot": i,
                    "req": None if r is None else r.id,
                    "position": int(self.pool.positions[i]),
                }
                for i, r in enumerate(self._slot_req)
            ],
            "metrics": self.metrics.snapshot(),
        }
        m = self.metrics
        d["health"] = {
            "state": self.health,
            "consecutive_failures": self._consec_failures,
            "last_error": self._last_error,
            "quarantines": m.quarantines,
            "retries": m.engine_retries,
            "unhealthy_episodes": m.engine_unhealthy,
            "watchdog_stalls": m.watchdog_stalls,
        }
        if self._faults is not None:
            d["health"]["fault_plan"] = self._faults.stats()
        if self._ladder is not None:
            d["health"]["ladder"] = self._ladder.stats()
        if self.journal is not None:
            d["journal"] = {
                **self.journal.stats(),
                "strict": self.config.journal_strict,
                "degraded": self._journal_degraded,
                "recovered_requests": self._recovered_total,
            }
        if self._paged:
            d["kv_pages"] = {
                "page_size": self.pool.page_size,
                "page_budget": self.pool.page_budget,
                "pages_free": self.pool.pages_free,
                "pages_active": self.pool.pages_active,
                "fragmentation": self.pool.fragmentation,
                "per_slot_pages": self.pool.n_alloc.tolist(),
            }
        if self._quant:
            pool = self.pool
            store = pool.phys if self._paged else pool.caches
            pool_bytes, scale_bytes, exact_bytes, base_bytes = \
                quant_pool_bytes(store)
            d["kv_quant"] = {
                "mode": self.config.kv_quant,
                "quant_block": pool.quant_block,
                "kv_pool_bytes": pool_bytes + exact_bytes,
                "quant_bytes": pool_bytes,
                "scale_bytes": scale_bytes,
                "exact_bytes": exact_bytes,
                "baseline_bytes": base_bytes,
                "bytes_ratio": round(pool_bytes / base_bytes, 4),
                "exact_lanes": pool.exact_lanes,
                "exact_lanes_free": len(self._exact_free),
                "exact_slots": [
                    i for i, e in enumerate(self._eidx) if e
                ],
            }
        if self._spec is not None:
            m = self.metrics
            d["spec"] = {
                "drafter": self._spec,
                "k": self._spec_k,
                "rounds": self._spec_rounds,
                "steps": m.spec_steps,
                "drafts_proposed": m.spec_proposed,
                "drafts_accepted": m.spec_accepted,
                "acceptance_rate": round(
                    m.spec_accepted / m.spec_proposed, 4
                ) if m.spec_proposed else 0.0,
                "tokens_per_step": round(
                    m.spec_tokens / m.spec_steps, 2
                ) if m.spec_steps else 0.0,
                **self._spec_ctl.stats(),
            }
        if self._slo is not None:
            d["slo"] = self._slo.statusz()
        if self.prefix_cache is not None:
            d["prefix_cache"] = self.prefix_cache.stats()
        if self.registry is not None:
            d["compile"] = self.registry.snapshot()
        if self.ledger is not None:
            d["mem"] = self.ledger.snapshot()
        if self.timeseries is not None and len(self.timeseries):
            # the human rendering of the rolling retrospective: one
            # sparkline per series (right edge = now); the raw rows
            # live on /timeseriesz
            d["timeseries"] = {
                "interval_s": self.timeseries.interval_s,
                "windows": len(self.timeseries),
                "sparklines": self.timeseries.sparklines(),
            }
        return d

    def close(self, drain_s: float = 0.0) -> None:
        """Bounded shutdown: drive step() for up to `drain_s` seconds of
        graceful drain, then FORCE-CANCEL whatever is still in flight
        host-side (`force_drain`) — so SIGTERM can never hang on a
        wedged request (the deadline is checked before every step; a
        single stalled step can overrun it by at most its own duration,
        after which no further device work is dispatched). Releases
        external resources (status endpoint, profiler window).
        Idempotent; the engine itself stays usable."""
        deadline = smetrics.now() + drain_s
        while (self.has_work() and self._health != "unhealthy"
               and smetrics.now() < deadline):
            self.step()
        if self.has_work():
            self.force_drain("cancelled")
        if self.journal is not None and self.journal.dirty:
            # make the drain's finish records durable before the
            # process goes away (the journal stays open — the engine
            # itself stays usable after close())
            self._journal_op(self.journal.sync)
        self.stop_profile()
        if self.status is not None:
            self.status.close()
            self.status = None

    def run(self, max_steps: int | None = None) -> None:
        """Drive step() until queue and slots drain (or `max_steps`)."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return
        self.stop_profile()

    # ------------------------------------------------------------ private

    def _bucketed(self, length: int, start: int = 0) -> int:
        b = self.config.bucket
        padded = -(-length // b) * b
        limit = getattr(self.model, "max_positions", None)
        cap = min(self.config.max_len, limit or self.config.max_len) - start
        return max(length, min(padded, cap))

    def _notify(self, req: Request, n_new: int) -> None:
        """Fire the request's streaming hook (engine thread): `n_new`
        tokens were appended (0 for a tokenless finish boundary —
        cancel/timeout), `finished` mirrors the lifecycle state."""
        cb = req.stream_cb
        if cb is not None:
            cb(req, n_new, req.state == FINISHED)

    def _grammar_allow(self, req: Request) -> np.ndarray:
        """The request's current allowed-token list packed into a
        (sample_cap,) allow row. The grammar contract says the list is
        never empty before the document completes (and a completed
        document finishes the request immediately), so emptiness here
        is a stepper bug — failing loudly beats silently decoding
        unconstrained."""
        ids = req.grammar.allowed(req.remaining)
        if not ids:
            raise RuntimeError(
                f"grammar for request {req.id} returned an empty "
                f"allow-list mid-generation (budget {req.remaining}) — "
                "the mask-never-empty contract is broken"
            )
        return encode_allow(ids, self.config.sample_cap)

    def _match_len(self, prompt: np.ndarray) -> int:
        """Cached page-aligned prefix length for `prompt` (read-only; the
        scheduler's admission lookup). Capped at len-1: the suffix prefill
        must produce at least one logits row to sample from."""
        if self.prefix_cache is None or prompt.size < 2:
            return 0
        return self.prefix_cache.peek(prompt[: prompt.size - 1])

    # -------------------------------------------------- paged-pool policy

    def _page_gauges(self) -> dict[str, float]:
        """Page-pool occupancy gauges riding every metrics snapshot
        (registered iff `paged` — the present-iff-enabled key-surface
        contract the observatory gauges set)."""
        pool = self.pool
        return {
            "serve/pages_free": float(pool.pages_free),
            "serve/pages_active": float(pool.pages_active),
            "serve/page_fragmentation": float(pool.fragmentation),
        }

    def _kv_quant_gauges(self) -> dict[str, float]:
        """Quantized-pool byte gauges riding every metrics snapshot
        (registered iff `kv_quant` — the present-iff-enabled key-surface
        contract of the paged/spec/observatory gauges). Byte math is
        analytic (host-side shape sums), never a device read."""
        pool = self.pool
        store = pool.caches if not self._paged else pool.phys
        pool_bytes, scale_bytes, exact_bytes, base_bytes = \
            quant_pool_bytes(store)
        out = {
            # resident KV bytes per bookable token slot: the capacity
            # price of one context token under this pool (int8 payload
            # + scale sidecar; exact lanes are a fixed surcharge the
            # *_exact_* gauges expose separately)
            "serve/kv_bytes_per_token": pool_bytes / pool.token_capacity,
            "serve/kv_quant_scale_bytes": float(scale_bytes),
            # what the same payload would hold at the compute dtype,
            # minus int8 + scales — the ledger-visible capacity win (the
            # exact-lane sidecar is a separately-disclosed surcharge)
            "serve/kv_quant_bytes_saved": float(base_bytes - pool_bytes),
        }
        if pool.exact_lanes:
            out["serve/kv_quant_exact_lanes_free"] = float(
                len(self._exact_free)
            )
            out["serve/kv_quant_exact_active"] = float(
                pool.exact_lanes - len(self._exact_free)
            )
        return out

    def _page_need(self, req: Request) -> int:
        """Pages a waiting request needs to start: prefill coverage of
        its (resume-aware) sequence net of the cached-prefix hint, plus
        one decode block's reservation. Deliberately an ESTIMATE — the
        hint can go stale between gate and admit, and several admissions
        in one iteration share the same free count; `_ensure_pages`'
        reclaim path absorbs any over-admission."""
        pool = self.pool
        if req.tokens:
            seq = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
            )
        else:
            seq = req.prompt
        matched = 0
        if self.prefix_cache is not None and seq.size > 1:
            matched = self.prefix_cache.peek(seq[: seq.size - 1])
        suffix = int(seq.size) - matched
        padded = self._bucketed(suffix, start=matched)
        need = min(matched + padded + self.config.decode_block,
                   self.config.max_len)
        return pool.pages_for(need) - matched // pool.page_size

    def _can_admit(self, req: Request) -> bool:
        """The scheduler's capacity gate beyond free slots: paged pools
        admit while free pages cover the request's prompt + a decode
        reservation (free SLOTS alone no longer imply capacity — that is
        what decouples slot count from max_seq); kv_exact requests on a
        quantized pool instead need a free full-precision sidecar lane
        (they never consume pages). Estimates can go stale across one
        iteration's picks — `_admit`'s bail paths absorb over-admission."""
        if self._quant and req.params.kv_exact:
            return bool(self._exact_free)
        if self._paged:
            return self.pool.pages_free >= self._page_need(req)
        return True

    def _unblock_head(self) -> None:
        """Shed prefix-tree page references for a page-starved queue
        head BEFORE the scheduler picks. Without this the engine can
        livelock: the tree's references persist after every stream
        drains (that is the cache working as designed), but reclaim
        otherwise only runs inside `_admit`/`_cover_decode` — which a
        blocked `can_admit` gate prevents from ever running again.
        Runs only with the pool fully IDLE: while streams are active,
        their ordinary finish-and-release is what unblocks the head
        (transient backpressure — shedding the tree then would destroy
        the cache for nothing), and active streams are never preempted
        for a WAITING request. Once they all drain, either the head
        fits or only the tree still holds pages — and with the tree
        spent, `page_budget >= pages_per_lane` guarantees any single
        request fits."""
        if (not self.scheduler.queue or self.pool.n_active > 0
                or self.prefix_cache is None):
            return
        head = self.scheduler.queue[0]
        if self._quant and head.params.kv_exact:
            return  # blocked on exact lanes, not pages: the tree can't help
        shed = False
        while (not self._can_admit(head)
               and self.prefix_cache.evict_one()):
            shed = True
        if shed:
            self.metrics.record_prefix_state(
                self.prefix_cache.bytes_held, self.prefix_cache.evictions
            )

    def _ensure_pages(self, slot: int, n_tokens: int) -> bool:
        """Grow `slot`'s page table to cover `n_tokens`, reclaiming
        under pressure: shed prefix-tree references first (cheap — the
        cache is advisory), then preempt the youngest other stream
        (requeue-and-recompute). False only when the pool cannot cover
        this slot even with everything else evicted."""
        while not self.pool.ensure(slot, n_tokens):
            if not self._reclaim_one(protect={slot}):
                return False
        return True

    def _reclaim_one(self, protect: set) -> bool:
        """Free page capacity by one unit: evict one prefix-tree leaf
        (preferred — dropping cache never hurts correctness) or, with
        the tree spent, preempt the YOUNGEST active request not in
        `protect` (latest-admitted loses: it has the least sunk prefill
        work and the oldest streams keep their latency contract). False
        when nothing reclaimable remains. A tree eviction may free zero
        pages (a slot still shares them) — callers loop, and each call
        removes a node or a stream, so the loop terminates."""
        pc = self.prefix_cache
        if pc is not None and pc.evict_one():
            self.metrics.record_prefix_state(pc.bytes_held, pc.evictions)
            return True
        victim = None
        for r in self._slot_req:
            if r is None or r.slot in protect:
                continue
            if self._quant and r.params.kv_exact:
                continue  # exact streams hold no pages: nothing to free
            if victim is None or r.admit_time > victim.admit_time:
                victim = r
        if victim is None:
            return False
        self._preempt(victim)
        return True

    def _preempt(self, req: Request) -> None:
        """Evict an ACTIVE stream on page exhaustion: its pages free
        immediately (shared ones survive under the tree's references —
        often making its own resume a prefix HIT), the request returns
        to the HEAD of the queue, and `_admit`'s resume path recomputes
        its KV when pages free up. Runs only at block boundaries, so no
        in-flight program output is lost."""
        slot = req.slot
        self.metrics.record_preemption()
        req.pages_held = max(req.pages_held, int(self.pool.n_alloc[slot]))
        if self.trace is not None:
            self.trace.instant("preempt", "engine", f"slot{slot}",
                               req=req.id, tokens=len(req.tokens))
        self._slot_req[slot] = None
        self._toks[slot] = 0
        self._pos[slot] = 0
        self._samp_f[:, slot] = GREEDY_ROW
        self._allow[slot] = -1
        self._top_k[slot] = 0
        self._seed[slot] = -1
        self._need_lp[slot] = 0
        if self._eidx[slot]:
            self._exact_free.append(int(self._eidx[slot]))
            self._eidx[slot] = 0
        self.pool.release(slot)
        req.slot = None
        self.scheduler.requeue_front(req)
        if req.deadline is not None:
            self._waiting_deadlines += 1

    def _cover_decode(self, block: int) -> None:
        """Page-budget guard before a decode block: every surviving slot
        must own pages for its next `block` writes (a slot that hits
        EOS/budget mid-block keeps stepping — overshoot beyond coverage
        lands in the trash page and is discarded host-side, but REAL
        tokens' writes must be owned). Oldest streams are covered first;
        reclaim preempts youngest-first, so under exhaustion the pool
        degrades to fewer, older streams instead of corrupting any."""
        active = [r for r in self._slot_req if r is not None]
        active.sort(key=lambda r: r.admit_time)
        covered: set[int] = set()
        for req in active:
            if req.slot is None:
                continue  # preempted by an earlier slot's reclaim
            slot = req.slot
            covered.add(slot)
            if self._quant and req.params.kv_exact:
                continue  # exact streams write sidecar lanes, not pages
            target = min(int(self._pos[slot]) + block, self.config.max_len)
            ok = self.pool.ensure(slot, target)
            while not ok:
                if not self._reclaim_one(protect=covered):
                    break
                ok = self.pool.ensure(slot, target)
            if not ok:
                # nothing reclaimable left: this stream yields too
                self._preempt(req)
                covered.discard(slot)

    def _admit(self, req: Request) -> bool:
        """Prefill `req` into a free lane; True if it finished already.

        With the prefix cache on: reuse the longest cached page-aligned
        prompt prefix — the lane pool SPLICES it into the lane
        (copy-on-acquire, one fused device program), the paged pool
        APPENDS the cached physical page ids to the slot's page table
        (refcount bump, zero device copies) — prefill only the uncovered
        suffix from position `matched`, then hand the prompt's
        page-aligned prefix back to the tree (snapshot copy vs page-id
        reference, same split).

        A request with tokens already emitted is a PREEMPTED one being
        resumed (paged pool only): the prefill recomputes KV for prompt
        + emitted-so-far (minus the newest token, whose KV is written
        when it is fed back), the program's sampled token is discarded
        (the stream already holds it), and decode continues where it
        stopped — token streams are unchanged because cached KV depends
        only on the token ids, and seeded sampling chains fold only
        (seed, sample index).
        """
        slot = self.pool.acquire()
        assert slot is not None, "scheduler admitted beyond free slots"
        tr = self.trace
        now = smetrics.now()
        resumed = bool(req.tokens)
        req.state = ACTIVE
        req.slot = slot
        req.admit_time = now
        # registered BEFORE any device dispatch: if a program call below
        # raises, the fault boundary's rebuild scans _slot_req to
        # requeue in-flight work — a mid-admission request must not slip
        # through the scan and get lost (the bail paths clear it)
        self._slot_req[slot] = req

        if resumed:
            seq = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
            )
        else:
            seq = req.prompt
        length = int(seq.size)
        matched = 0
        # kv_exact streams bypass the (quantized) prefix cache entirely:
        # a spliced int8 prefix would break their byte-exactness, and
        # their sidecar lanes own no pages/segments the tree could share
        exact = self._quant and req.params.kv_exact
        use_pc = self.prefix_cache is not None and not exact
        if use_pc and length > 1:
            match = self.prefix_cache.match(seq[: length - 1])
            matched = match.length
            if matched:
                # fault-plane site: the prefix-cache reuse path (splice
                # program / zero-copy page append)
                self._poke_site("prefix_splice")
                # pin across the reuse. In today's single-threaded engine
                # nothing can evict between match and splice (eviction only
                # runs inside insert, below) — the pin is the invariant a
                # future async/threaded admission path must keep, kept live
                # here so the refcount machinery stays exercised.
                self.prefix_cache.pin(match)
                if self._paged:
                    # zero-copy hit: the matched nodes' PHYSICAL page ids
                    # go straight into the slot's page table (host-side
                    # incref) — no device program is dispatched at all,
                    # which the compile registry can prove (no
                    # splice_program entry ever appears)
                    for node in match.nodes:
                        self.pool.append_shared(slot, node.pages)
                    self.prefix_cache.unpin(match)
                    if tr is not None:
                        tr.instant(
                            "share", "prefix", f"slot{slot}", req=req.id,
                            matched=matched,
                            pages=matched // self.prefix_cache.page,
                        )
                else:
                    t_sp = smetrics.now() if tr is not None else 0.0
                    offset = 0
                    for node in match.nodes:
                        self.pool.splice_prefix(slot, node.segment, offset)
                        offset += node.length
                    self.prefix_cache.unpin(match)
                    if tr is not None:
                        # fence: the splice programs run async; without
                        # the wait the span would record dispatch, not
                        # the copy
                        jax.block_until_ready(self.pool.caches)
                        t_sp1 = smetrics.now()
                        self._dev_s += t_sp1 - t_sp
                        tr.complete("splice", "prefix", f"slot{slot}",
                                    ts=t_sp, dur=t_sp1 - t_sp, req=req.id,
                                    matched=matched,
                                    pages=matched // self.prefix_cache.page)

        suffix = length - matched
        padded = self._bucketed(suffix, start=matched)
        if (self._paged and not exact
                and not self._ensure_pages(slot, matched + padded)):
            # pathological: even after shedding the whole tree and every
            # other stream the pool cannot cover this prefill. Hand the
            # pages and slot back and retry next iteration.
            self._slot_req[slot] = None
            self.pool.release(slot)
            req.slot = None
            self.scheduler.requeue_front(req)
            if req.deadline is not None:
                self._waiting_deadlines += 1
            return False
        eidx = 0
        if exact:
            if not self._exact_free:
                # the admission gate's estimate went stale (several exact
                # picks in one iteration): requeue and retry when a
                # sidecar lane frees — the paged bail path's discipline
                self._slot_req[slot] = None
                self.pool.release(slot)
                req.slot = None
                self.scheduler.requeue_front(req)
                if req.deadline is not None:
                    self._waiting_deadlines += 1
                return False
            eidx = self._exact_free.pop()
            self._eidx[slot] = eidx
        # admission metrics AFTER the bail points above: a requeued-and-
        # retried admission must not add a second queue-wait sample or
        # count its prefix lookup twice
        if not resumed:
            self.metrics.record_admit(req, now)
        if use_pc and length > 1:
            self.metrics.record_prefix_lookup(matched)
        chunk = self.config.prefill_chunk
        if chunk is None and padded > 4096:
            chunk = 2048  # same auto-chunk threshold as infer.decode.generate
        if chunk is not None and chunk >= padded:
            chunk = None
        prompt_padded = np.zeros(padded, np.int32)
        prompt_padded[:suffix] = seq[matched:]
        samp_row, top_k, seed = encode_params(req.params)
        need_lp = int(req.params.logprobs)
        self._samp_f[:, slot] = samp_row
        self._top_k[slot] = top_k
        self._seed[slot] = seed
        self._need_lp[slot] = need_lp
        head = np.asarray(
            [slot, suffix, self._rng_step, top_k, seed, need_lp], np.int32
        )
        # grammar allow-list for the FIRST sampled token (resumed
        # requests discard that sample, but the mask must still be
        # well-formed); free/unconstrained lanes rest at -1
        self._allow[slot] = (self._grammar_allow(req)
                             if req.grammar is not None else -1)
        # fault-plane site: the prefill dispatch (stall/synthetic-error
        # effects apply here; a nan/inf spec poisons THIS prefill's
        # sampled-token logits through the ctl code below)
        pf_fault = self._poke_site("prefill")
        # the paged program reads the slot's page-table row off the SAME
        # packed int transfer as the allow-list (logical->physical
        # translation with zero extra host->device traffic); the
        # fault-plane poison code is ALWAYS the last element, the
        # exact-lane index (quant pools) second-to-last
        ctl = np.concatenate(
            [head, self._allow[slot]]
            + ([self.pool.table[slot]] if self._paged else [])
            + ([np.asarray([eidx], np.int32)] if self._quant else [])
            + [np.asarray([pf_fault], np.int32)]
        )
        self._rng_step += 1
        t_pf = smetrics.now() if tr is not None else 0.0
        if self._spec == "mtp":
            # admission doubles as the MTP bootstrap: the head cache is
            # prefilled alongside the main lane and the first round's
            # drafts come back with the first token (matched is always 0
            # — the MTP engine excludes the prefix cache)
            pf_args = (
                self.model, padded, chunk, self.config.sample_cap,
                self._spec_k, self.variables, self.pool.caches,
                self._mtp_pool, jnp.asarray(prompt_padded),
                jnp.asarray(ctl), jnp.asarray(samp_row, np.float32),
                self._rng,
            )
            with self._scope("serve/prefill"):
                if self.registry is not None:
                    (pool_tree, self._mtp_pool, first, logprob, drafts,
                     ok) = self.registry.call(
                        "mtp_prefill_program", (padded, chunk),
                        _mtp_prefill_program, pf_args,
                        static_argnums=(0, 1, 2, 3, 4),
                    )
                else:
                    (pool_tree, self._mtp_pool, first, logprob, drafts,
                     ok) = _mtp_prefill_program(*pf_args)
            self.pool.caches = pool_tree
            self._next_drafts[slot] = np.asarray(drafts)
        else:
            prog = (_paged_prefill_program if self._paged
                    else _prefill_program)
            pool_tree = self.pool.phys if self._paged else self.pool.caches
            pf_args = (
                self.model, padded, chunk, matched, self.config.sample_cap,
                self.variables, pool_tree, jnp.asarray(prompt_padded),
                jnp.asarray(ctl), jnp.asarray(samp_row, np.float32),
                self._rng,
            )
            with self._scope("serve/prefill"):
                if self.registry is not None:
                    # signature = the static shape triple; everything else
                    # (params, caches, control arrays) is fixed per engine
                    pool_tree, first, logprob, ok = self.registry.call(
                        "prefill_program", (padded, chunk, matched),
                        prog, pf_args, static_argnums=(0, 1, 2, 3, 4),
                    )
                else:
                    pool_tree, first, logprob, ok = prog(*pf_args)
            if self._paged:
                self.pool.phys = pool_tree
            else:
                self.pool.caches = pool_tree
        first = int(first)  # blocks on the program — t_pf1 is device-true
        if tr is not None:
            t_pf1 = smetrics.now()
            self._dev_s += t_pf1 - t_pf
            tr.complete("prefill_program", "engine", f"slot{slot}", ts=t_pf,
                        dur=t_pf1 - t_pf, req=req.id, padded=padded,
                        suffix=suffix, chunk=chunk or 0)
        if not bool(np.asarray(ok)):
            # poisoned prefill: quarantine BEFORE the prefix-cache
            # insert below — a non-finite lane must never be snapshotted
            # or page-shared into the radix tree
            self._quarantine(req, smetrics.now())
            return True
        if use_pc:
            # hand the prefilled span to the tree while [0, length) is
            # pristine (an active lane's decode writes land at positions
            # >= length, and dummy writes only hit FREED lanes' slot 0 /
            # the trash page)
            page = self.prefix_cache.page
            aligned = (length - 1) // page * page
            # aligned == matched on a full hit: nothing new to cache, and
            # insert's internal re-match would re-walk the whole prefix on
            # the dispatch-bound host hot path for nothing
            if aligned > matched:
                if self._paged:
                    # reference, not copy: the tree increfs the slot's own
                    # fully-filled pages (only a trailing PARTIAL page
                    # would need a snapshot, and insert never takes one —
                    # aligned is a page multiple)
                    self.prefix_cache.insert(
                        seq[:aligned],
                        lambda off, n: self.pool.share_range(slot, off, n),
                    )
                else:
                    self.prefix_cache.insert(
                        seq[:aligned],
                        lambda off, n: self.pool.extract_prefix(slot, off, n),
                    )
            self.metrics.record_prefix_state(
                self.prefix_cache.bytes_held, self.prefix_cache.evictions
            )
        if self.ledger is not None:
            # live bytes only grow at admission (prefix snapshots) and
            # program temp only at new compiles (just above) — one
            # projected-peak check per admitted request, never per token
            self.ledger.check()
        now = smetrics.now()
        if resumed:
            # recompute complete: the sampled token is discarded (the
            # stream already holds every emitted id) and decode resumes
            # at the preempted position
            self.metrics.record_recompute_tokens(suffix)
            self._last_emit[slot] = now
            self.pool.positions[slot] = length
            self._toks[slot] = req.tokens[-1]
            self._pos[slot] = length
            # _slot_req[slot] was registered before the dispatch (the
            # fault boundary's rebuild scans it) — nothing to set here
            if tr is not None:
                tr.instant("resume", "request", f"slot{slot}", req=req.id,
                           ts=now, recomputed=suffix,
                           tokens=len(req.tokens))
            return False
        req.first_token_time = now
        req.tokens.append(first)
        if req.grammar is not None:
            req.grammar.advance(first)
        if req.params.logprobs:
            req.logprobs.append(float(logprob))
        # the first token is a one-token commit at the admission
        # boundary (decode blocks commit the rest block-by-block)
        self._journal_commit(req, (first,))
        self.metrics.record_first_token(req, now, prefilled=suffix)
        if tr is not None:
            # lifecycle spans stamped from the request's OWN timestamps:
            # queue + prefill partition TTFT exactly (submit -> admit ->
            # first token), which is what lets trace-summary's phase sums
            # reproduce the measured latencies instead of approximating
            # them from instrumentation spans
            tr.complete("queue", "request", "queue", ts=req.submit_time,
                        dur=req.admit_time - req.submit_time, req=req.id)
            tr.complete("prefill", "request", f"slot{slot}",
                        ts=req.admit_time, dur=now - req.admit_time,
                        req=req.id, prefilled=suffix, matched=matched)
        self._last_emit[slot] = now
        self.pool.positions[slot] = length
        self._toks[slot] = first
        self._pos[slot] = length
        reason = self._stop_reason(req, first)
        if req.grammar is not None and req.grammar.done:
            reason = "stop"  # complete document beats a length finish
        if reason != "eos" and self._stop_string_at(req, 0) is not None:
            reason = "stop"  # the first token alone completed a match
        if reason is None:
            self._notify(req, 1)
            return False
        self._finish(req, reason, now)
        self._notify(req, 1)
        return True

    def _stop_reason(self, req: Request, tok: int) -> str | None:
        """Why the just-appended token `tok` ends `req`'s stream — "eos",
        "stop" (stop token-id set), "length", or None (keep decoding).
        Token-level checks only; stop STRINGS are matched once per block
        by `_stop_string_at` (a per-token full-stream decode would make
        the dispatch-bound host loop O(n^2) in stream length)."""
        if req.eos_id is not None and tok == req.eos_id:
            return "eos"
        if req.params.stop_token_ids and tok in req.params.stop_token_ids:
            return "stop"
        if req.remaining == 0:
            return "length"
        return None

    def _stop_string_at(self, req: Request, start: int) -> int | None:
        """Earliest token index >= `start` whose appended text completes a
        stop-string match over the decoded stream, or None. ONE full
        decode per block (matches may span block boundaries because the
        whole generated stream is searched); the per-prefix walk to
        locate the completing token runs only on a hit — at most once in
        a request's lifetime, since a hit finishes it.

        Deliberately NOT a bounded tail-window re-decode (the vLLM
        trick): `detokenize` is caller-supplied and need not be
        prefix-stable — merge-y tokenizers can rewrite text at token
        boundaries and tokens may decode to empty strings, so a
        fixed-token window can miss or misplace a cross-boundary match.
        The full re-decode is exact for ANY detokenizer at one O(stream)
        host call per block, bounded by max_len."""
        if not req.params.stop:
            return None
        text = self.detokenize(req.tokens)
        if not any(s in text for s in req.params.stop):
            return None
        for k in range(start, len(req.tokens)):
            prefix = self.detokenize(req.tokens[: k + 1])
            if any(s in prefix for s in req.params.stop):
                return k
        return len(req.tokens) - 1  # decode-boundary quirk: match only
        # materializes with the full stream; attribute it to the last token

    def _spec_gauges(self) -> dict[str, float]:
        """Speculation gauges riding every metrics snapshot (registered
        iff `speculative` — the present-iff-enabled key-surface contract
        of the paged/observatory gauges)."""
        m = self.metrics
        rate = (m.spec_accepted / m.spec_proposed) if m.spec_proposed else 0.0
        per_step = (m.spec_tokens / m.spec_steps) if m.spec_steps else 0.0
        return {
            "serve/spec_acceptance_rate": rate,
            "serve/spec_tokens_per_step": per_step,
            "serve/spec_drafts_rejected": float(
                m.spec_proposed - m.spec_accepted
            ),
        }

    def _spec_block(self, probe: bool = False) -> list[Request]:
        """One speculative decode step: `spec_rounds` draft-verify rounds
        in ONE program call, committing a variable number of tokens per
        slot. The host walk mirrors `_decode_block`'s exactly — per
        committed token: append, grammar advance, logprobs, stop checks —
        so every lifecycle behavior (EOS/budget/stop-string/cancel/
        timeout, overshoot discard) is identical; only the token source
        changed. Grammar-constrained slots keep ONE token per step (their
        allow-mask is stale after the first draw): they ride the same
        program draft-free and the host takes round 0's first commit.

        `probe` runs the controller's short measurement block (a couple
        of rounds) instead of the full one — cheap acceptance evidence
        after a hold, so adversarial traffic pays a fraction of a block,
        not a full chunked block, per probe."""
        cfg = self.config
        k = self._spec_k
        rounds = min(2, self._spec_rounds) if probe else self._spec_rounds
        mtp = self._spec == "mtp"
        if self._paged:
            # cover the worst-case committed window (every round sweeps);
            # reclaim preempts youngest-first under pressure as usual
            self._cover_decode(min(rounds * (k + 1), cfg.max_len))
            if self.pool.n_active == 0:
                return []
        # fault-plane site: the speculative block IS the decode dispatch
        self._poke_site("decode")
        acap = cfg.sample_cap
        if mtp:
            rows = 10 + acap + k + 1
        else:
            rows = (11 + acap + cfg.max_len
                    + (self.pool.pages_per_lane if self._paged else 0)
                    + (1 if self._quant else 0) + 1)
        state = np.zeros((rows, cfg.n_slots), np.int32)
        state[0] = self._toks
        state[1] = self._pos
        state[3] = -1
        for slot, r in enumerate(self._slot_req):
            if r is None:
                continue
            state[2, slot] = 1
            if r.eos_id is not None:
                state[3, slot] = r.eos_id
            state[7, slot] = len(r.tokens)
            if r.grammar is not None:
                # constrained slots never draft (spec gate stays 0) and
                # refresh their allow row exactly like the plain block
                self._allow[slot] = self._grammar_allow(r)
            else:
                state[9 + acap, slot] = 1
            if not mtp:
                # the slot's token history — the n-gram drafter's corpus
                # — rides the packed transfer, one column per slot
                seq = np.concatenate(
                    [r.prompt, np.asarray(r.tokens, np.int32)]
                )
                m = min(int(seq.size), cfg.max_len)
                state[10 + acap:10 + acap + m, slot] = seq[:m]
                state[10 + acap + cfg.max_len, slot] = m
        state[4] = self._rng_step
        state[5] = self._top_k
        state[6] = self._seed
        state[8] = self._need_lp
        state[9:9 + acap] = self._allow.T
        if mtp:
            state[10 + acap:10 + acap + k] = self._next_drafts.T
        elif self._paged:
            base = 11 + acap + cfg.max_len
            state[base:base + self.pool.pages_per_lane] = self.pool.table.T
        if self._quant:
            state[-2] = self._eidx
        # fault-plane poison row, always last; one-shot per dispatch
        state[-1] = self._fault_row
        self._fault_row[:] = 0
        self._rng_step += 1
        tr = self.trace
        t_dec = smetrics.now() if tr is not None else 0.0
        if mtp:
            prog = _mtp_spec_decode_program
            args = (self.model, k, rounds, acap, cfg.max_len,
                    self.variables, self.pool.caches, self._mtp_pool,
                    jnp.asarray(state), jnp.asarray(self._samp_f),
                    self._rng)
            statics = (0, 1, 2, 3, 4)
        else:
            prog = (_paged_spec_decode_program if self._paged
                    else _spec_decode_program)
            args = (self.model, k, rounds, acap, cfg.max_len,
                    cfg.spec_ngram, self.variables,
                    self.pool.phys if self._paged else self.pool.caches,
                    jnp.asarray(state), jnp.asarray(self._samp_f),
                    self._rng)
            statics = (0, 1, 2, 3, 4, 5)
        with self._scope("serve/spec_block"):
            if self.registry is not None:
                # one speculative decode shape per engine, exactly like
                # decode_block — a second signature IS the anomaly
                res = self.registry.call(
                    "spec_block", (rounds, k), prog, args,
                    static_argnums=statics,
                )
            else:
                res = prog(*args)
        if mtp:
            self.pool.caches, self._mtp_pool, outs, nxt = res
            # np.array, not asarray: the device view is read-only and
            # the next admission writes its bootstrap drafts in place
            self._next_drafts = np.array(nxt)
        elif self._paged:
            self.pool.phys, outs = res
        else:
            self.pool.caches, outs = res
        out, commits, proposed, lps, finite = outs
        # fault-plane site: post-block output fetch / paged scatter
        self._poke_site("scatter")
        t_dev = 0.0
        if tr is not None:
            jax.block_until_ready(out)
            t_dev = smetrics.now()
            self._dev_s += t_dev - t_dec
        out = np.asarray(out)          # (rounds, S, k+1)
        commits = np.asarray(commits)  # (rounds, S)
        proposed = np.asarray(proposed)
        lps = np.asarray(lps)
        finite = np.asarray(finite)    # (S,) — the per-slot guard
        now = smetrics.now()
        finished: list[Request] = []
        tot_prop = tot_acc = tot_rounds = 0
        delivered = 0
        max_appended = 0
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if tr is not None:
                tr.complete("spec_block", "engine", f"slot{slot}",
                            ts=t_dec, dur=t_dev - t_dec, req=req.id,
                            rounds=rounds, k=k)
            if not finite[slot]:
                finished.append(self._quarantine(req, now))
                continue
            if req.cancelled:
                self._finish(req, "cancelled", now)
                finished.append(req)
                self._notify(req, 0)
                continue
            if req.deadline is not None and now >= req.deadline:
                self._finish(req, "timeout", now)
                finished.append(req)
                self._notify(req, 0)
                continue
            appended = 0
            reason = None
            base = len(req.tokens)
            grammar1 = req.grammar is not None
            for r in range(rounds):
                n = int(commits[r, slot])
                if not grammar1:
                    tot_prop += int(proposed[r, slot])
                    tot_acc += max(n - 1, 0)
                    tot_rounds += 1
                    # request-scoped acceptance fact (debug timeline):
                    # engine-wide rates hide a single adversarial stream
                    req.spec_proposed += int(proposed[r, slot])
                    req.spec_accepted += max(n - 1, 0)
                # a grammar slot accepts only round 0's first commit —
                # later rounds drew through a stale mask (overshoot,
                # discarded exactly like the plain block's tail)
                take = n if not grammar1 else (1 if r == 0 else 0)
                for j in range(take):
                    t = int(out[r, slot, j])
                    req.tokens.append(t)
                    if grammar1:
                        req.grammar.advance(t)
                    if req.params.logprobs:
                        req.logprobs.append(float(lps[r, slot, j]))
                    appended += 1
                    reason = self._stop_reason(req, t)
                    if grammar1 and req.grammar.done:
                        reason = "stop"
                    if reason is not None:
                        break
                if reason is not None:
                    break
            kk = self._stop_string_at(req, base)
            if kk is not None:
                last = len(req.tokens) - 1
                if reason is None or kk < last or reason == "length":
                    del req.tokens[kk + 1:]
                    if req.params.logprobs:
                        del req.logprobs[kk + 1:]
                    appended -= last - kk
                    reason = "stop"
            # one commit per request per speculative block (same
            # boundary as the plain block's — the drafts' variable
            # commit counts are invisible to the journal)
            self._journal_commit(req, req.tokens[base:])
            self.metrics.record_tokens(
                req, appended, now - self._last_emit[slot], now
            )
            self._last_emit[slot] = now
            self.pool.positions[slot] += appended
            delivered += appended
            max_appended = max(max_appended, appended)
            if reason is not None:
                self._finish(req, reason, now)
                finished.append(req)
            else:
                # an unfinished slot kept every commit, so the host
                # mirrors track the device carry exactly (the device's
                # internal position is rebuilt from these next call)
                self._toks[slot] = req.tokens[-1]
                self._pos[slot] += appended
            self._notify(req, appended)
        self.metrics.record_spec_step(tot_prop, tot_acc, delivered)
        if tot_rounds:
            self._spec_ctl.observe(tot_acc, tot_rounds)
        self._tick_weight = max(1.0, max_appended / cfg.decode_block)
        return finished

    def _decode_block(self) -> list[Request]:
        if self._spec is not None:
            decision = self._spec_ctl.decide()
            if decision != "off":
                return self._spec_block(probe=decision == "probe")
        cfg = self.config
        block = cfg.decode_block
        if self._paged:
            self._cover_decode(block)
            if self.pool.n_active == 0:
                return []  # exhaustion preempted every stream this block
        # fault-plane site: the decode-block dispatch (stall/synthetic
        # errors apply here; nan/inf pokes write the per-slot fault row
        # packed into THIS call's control transfer)
        self._poke_site("decode")
        acap = cfg.sample_cap
        rows = (9 + acap + (self.pool.pages_per_lane if self._paged else 0)
                + (1 if self._quant else 0) + 1)
        state = np.zeros((rows, cfg.n_slots), np.int32)
        state[0] = self._toks
        state[1] = self._pos
        state[3] = -1
        for slot, r in enumerate(self._slot_req):
            if r is not None:
                state[2, slot] = 1
                if r.eos_id is not None:
                    state[3, slot] = r.eos_id
                # sample index of this block's first draw: the request
                # has emitted len(tokens) so far (index 0 was prefill's)
                state[7, slot] = len(r.tokens)
                if r.grammar is not None:
                    # the stepper advanced with last block's accepted
                    # token: refresh this slot's allow-list (only the
                    # FIRST draw of the block is accepted — see below)
                    self._allow[slot] = self._grammar_allow(r)
        state[4] = self._rng_step
        state[5] = self._top_k
        state[6] = self._seed
        state[8] = self._need_lp
        state[9:9 + acap] = self._allow.T
        if self._paged:
            # the page tables ride the SAME packed transfer: still two
            # host->device control arrays per decode call
            state[9 + acap:9 + acap + self.pool.pages_per_lane] = \
                self.pool.table.T
        if self._quant:
            # exact-lane indices ride second-to-last (0 = quantized/trash)
            state[-2] = self._eidx
        # the fault-plane poison row is ALWAYS the last row (all-zero =
        # bitwise no-op in the program); one-shot per dispatch
        state[-1] = self._fault_row
        self._fault_row[:] = 0
        self._rng_step += 1
        tr = self.trace
        t_dec = smetrics.now() if tr is not None else 0.0
        prog = _paged_decode_program if self._paged else _decode_program
        dec_args = (
            self.model, block, self.config.sample_cap, self.variables,
            self.pool.phys if self._paged else self.pool.caches,
            jnp.asarray(state), jnp.asarray(self._samp_f), self._rng,
        )
        with self._scope("serve/decode_block"):
            if self.registry is not None:
                # one decode shape per engine — a second signature here
                # IS the anomaly the registry exists to catch. Named
                # after the trace span ("decode_block") so the offline
                # roofline join in summarize_trace matches.
                pool_tree, (out, lps, finite) = self.registry.call(
                    "decode_block", (block,), prog, dec_args,
                    static_argnums=(0, 1, 2),
                )
            else:
                pool_tree, (out, lps, finite) = prog(*dec_args)
        if self._paged:
            self.pool.phys = pool_tree
        else:
            self.pool.caches = pool_tree
        # fault-plane site: the post-block output fetch / paged scatter
        # boundary (where async XLA runtime errors actually surface)
        self._poke_site("scatter")
        t_dev = 0.0
        if tr is not None:
            # fence so the span is device wall time, not dispatch time;
            # the np.asarray below would block anyway, so the fence costs
            # nothing extra — it just moves the wait to a measured point
            jax.block_until_ready(out)
            t_dev = smetrics.now()
            self._dev_s += t_dev - t_dec
        out = np.asarray(out)  # (block, n_slots); overshoot truncated below
        lps = np.asarray(lps)
        finite = np.asarray(finite)  # (n_slots,) — the per-slot guard
        now = smetrics.now()
        finished: list[Request] = []
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if tr is not None:
                # one fused program advances every lane together: each
                # active slot's block span shares the program's wall time
                tr.complete("decode_block", "engine", f"slot{slot}",
                            ts=t_dec, dur=t_dev - t_dec, req=req.id,
                            block=block)
            if not finite[slot]:
                # the guard pinned a NaN/Inf forward to this slot: its
                # block output is garbage — contain it; every other
                # slot's walk below proceeds untouched
                finished.append(self._quarantine(req, now))
                continue
            if req.cancelled:
                # lifecycle kill at the block boundary: this block's
                # output is discarded, the lane frees for the next pick
                self._finish(req, "cancelled", now)
                finished.append(req)
                self._notify(req, 0)
                continue
            if req.deadline is not None and now >= req.deadline:
                self._finish(req, "timeout", now)
                finished.append(req)
                self._notify(req, 0)
                continue
            appended = 0
            reason = None
            base = len(req.tokens)
            # a grammar-constrained slot accepts only the block's FIRST
            # draw: the allow-mask rode this call's control transfer and
            # is stale after one advance — the tail is discarded exactly
            # like post-EOS overshoot (stale writes in the slot's own
            # lane are overwritten before they are ever attended)
            span = 1 if req.grammar is not None else block
            for t, lp in zip(out[:span, slot], lps[:span, slot]):
                req.tokens.append(int(t))
                if req.grammar is not None:
                    req.grammar.advance(int(t))
                if req.params.logprobs:
                    req.logprobs.append(float(lp))
                appended += 1
                reason = self._stop_reason(req, int(t))
                if req.grammar is not None and req.grammar.done:
                    reason = "stop"  # complete document ends the stream
                if reason is not None:
                    break  # the tail of the block is discarded overshoot
            k = self._stop_string_at(req, base)
            if k is not None:
                # a stop string completed at token k; it wins over a
                # token-level reason that fired LATER in the block (and
                # over "length" at the same token — the old per-token
                # check order), truncating the overshoot
                last = len(req.tokens) - 1
                if reason is None or k < last or reason == "length":
                    del req.tokens[k + 1:]
                    if req.params.logprobs:
                        del req.logprobs[k + 1:]
                    appended -= last - k
                    reason = "stop"
            # ONE commit record per request per block, riding the same
            # host-mirror drain that appended the tokens (the journal's
            # granularity is the engine's — never per token)
            self._journal_commit(req, req.tokens[base:])
            self.metrics.record_tokens(
                req, appended, now - self._last_emit[slot], now
            )
            self._last_emit[slot] = now
            self.pool.positions[slot] += appended
            if reason is not None:
                self._finish(req, reason, now)
                finished.append(req)
            elif req.grammar is not None:
                # the mirror advances by the ONE accepted token; the
                # device's remaining writes land beyond the mirror
                # position and are overwritten by the next block
                self._toks[slot] = out[0, slot]
                self._pos[slot] += 1
            else:
                # mirror the device carry: the slot ran the full block
                self._toks[slot] = out[-1, slot]
                self._pos[slot] += block
            self._notify(req, appended)
        return finished

    def _finish(self, req: Request, reason: str, now: float) -> None:
        req.state = FINISHED
        req.finish_reason = reason
        req.finish_time = now
        if req.first_token_time is None:
            # finished before its first token ever landed (a quarantined
            # or force-drained mid-admission request): close the
            # lifecycle with a zero-width prefill phase so the traced
            # three-span partition below never subtracts None
            req.first_token_time = now
            if self.trace is not None:
                self.trace.complete("queue", "request", "queue",
                                    ts=req.submit_time,
                                    dur=(req.admit_time or now)
                                    - req.submit_time, req=req.id)
                self.trace.complete("prefill", "request",
                                    f"slot{req.slot}",
                                    ts=req.admit_time or now,
                                    dur=now - (req.admit_time or now),
                                    req=req.id)
        if self._paged and req.slot is not None:
            # page-usage fact for the request's debug timeline, stamped
            # before release frees the table (streams only grow, so the
            # finish-boundary count IS the peak)
            req.pages_held = max(req.pages_held,
                                 int(self.pool.n_alloc[req.slot]))
        self._journal_finish(req)
        self.metrics.record_finish(req, now)
        if self._slo is not None:
            req.slo_result = self._slo.observe(req, now)
        if self.trace is not None:
            # lifecycle decode phase: first token -> finish (0 for
            # prefill-only finishes) — with queue + prefill above, the
            # three spans partition finish_time - submit_time exactly
            self.trace.complete(
                "decode", "request", f"slot{req.slot}",
                ts=req.first_token_time, dur=now - req.first_token_time,
                req=req.id, tokens=len(req.tokens),
            )
            self.trace.instant("finish", "request", f"slot{req.slot}",
                               req=req.id, ts=now, reason=reason)
            if self._mon is not None:
                self._mon.observe_finish(reason)
        slot = req.slot
        self._slot_req[slot] = None
        # park the idle lane at position 0 with greedy sampling rows: the
        # masked dummy writes land in slot 0 (overwritten by the next
        # prefill), and an all-greedy, unconstrained resting state keeps
        # idle batches on fused_sample's sort-free fast path
        self._toks[slot] = 0
        self._pos[slot] = 0
        self._samp_f[:, slot] = GREEDY_ROW
        self._allow[slot] = -1
        self._top_k[slot] = 0
        self._seed[slot] = -1
        self._need_lp[slot] = 0
        if self._eidx[slot]:
            # hand the exact sidecar lane back (stale data contract as
            # the pools': the next exact prefill overwrites before read)
            self._exact_free.append(int(self._eidx[slot]))
            self._eidx[slot] = 0
        self.pool.release(slot)

    def _finish_unadmitted(self, req: Request, reason: str,
                           now: float) -> None:
        """Finish a request cancelled or timed out while in the waiting
        queue — either never admitted, or a PREEMPTED stream waiting to
        resume (paged pool; it already has tokens and stamped queue +
        prefill spans at its original admission)."""
        req.state = FINISHED
        req.finish_reason = reason
        req.finish_time = now
        self._journal_finish(req)
        self.metrics.record_finish(req, now)
        if self._slo is not None:
            req.slo_result = self._slo.observe(req, now)
        if self.trace is not None:
            if req.first_token_time is None:
                # its whole life was queue time; no prefill/decode phases
                self.trace.complete("queue", "request", "queue",
                                    ts=req.submit_time,
                                    dur=now - req.submit_time, req=req.id)
            else:
                # preempted mid-stream: queue/prefill spans exist from
                # the original admission — close the lifecycle with the
                # decode phase (first token -> finish) instead of a
                # second full-life queue span, keeping the three-phase
                # partition of finish - submit intact
                self.trace.complete(
                    "decode", "request", "queue",
                    ts=req.first_token_time,
                    dur=now - req.first_token_time,
                    req=req.id, tokens=len(req.tokens),
                )
            self.trace.instant("finish", "request", "queue", req=req.id,
                               ts=now, reason=reason)
            if self._mon is not None:
                self._mon.observe_finish(reason)
        self._notify(req, 0)
