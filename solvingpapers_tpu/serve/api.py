"""OpenAI-compatible HTTP front door over a live `ServeEngine`.

The engine can batch, page, sample and observe, but it is an in-process
object: this module is the network boundary — the vLLM-shaped serving
surface ROADMAP item 5 calls for. One stdlib `ThreadingHTTPServer` (the
`metrics/http.py` daemon-thread pattern — zero dependencies) exposes:

    POST /v1/completions        OpenAI completions, string or token-id
                                prompts, SSE streaming (`stream: true`)
    POST /v1/chat/completions   chat messages through a minimal template
    GET  /v1/models             the one hosted model
    GET  /healthz /metrics /statusz   the PR-5 inspection surface, on
                                the SAME port family (one listener to
                                probe, scrape and debug)

Concurrency model: the engine stays single-threaded. `EngineLoop` owns
the only thread that calls `engine.step()`, and serializes `submit` /
`cancel` from HTTP handler threads behind one lock (a submit waits at
most one decode block). Token flow back out is lock-free: the engine's
per-request `stream_cb` fires on the engine thread and pushes a COUNT
into the connection's bounded queue; the handler thread wakes, reads
the request's token list (append-only — a count-prefix read is safe
under the GIL), detokenizes the delta and writes the SSE event. A slow
reader fills its queue and events coalesce (counts, not payloads), so
no client can block the engine.

Cancellation is disconnect-driven: the SSE writer maps a broken pipe —
or a half-closed socket, probed between events — to `engine.cancel`,
freeing the slot at the next block boundary; `timeout_s` maps to
`submit(deadline_s=)`.

Stream resumption (serve/journal.py): every SSE chunk carries an
``id: <request id>:<token offset>`` field; a client that lost its
connection POSTs again with ``Last-Event-ID`` set to the last id it
saw, and the server replays the committed tokens past that offset and
re-attaches the connection to the live tail — from the in-process
registry, from the engine's recovered set after a crash-restart
(`ServeEngine.recover`), or from the write-ahead journal's record of a
finished stream. `GET /v1/requests/<id>` likewise falls back to the
journal (marked ``source: "journal"``) for requests evicted from the
bounded registry or served by a previous process incarnation. Admission pressure maps to HTTP: a full waiting
queue (or the paged pool's page-budget gate rejecting) answers 503 +
Retry-After, invalid requests answer 400 with the OpenAI error
envelope (serve/openai.py) — never a traceback over a socket.

Fleet mode (serve/fleet.py): constructed with a `FleetRouter`, the same
surface fronts N replicas — admissions route by prefix affinity /
SLO burn / load with ranked retry on a full replica (`X-Replica-Id`
says where a request landed), the 503 capacity probe and Retry-After
rung reflect the FLEET view, `/metrics` serves the merged + per-replica
labeled exposition, `/statusz` grows a ``fleet`` section, and a drained
replica's SSE streams close WITHOUT a terminal chunk — the reconnect-
with-Last-Event-ID signal; the cursor resolves on the adopting peer
(blocking responses ride the migration transparently instead).

Request tracing rides every completion: the front door honors an
`X-Request-Id` header (minting one when absent or malformed), echoes it
on the response, stamps it on the engine `Request`, and — when the
engine's flight recorder is on — records HTTP-layer spans (`accept` =
headers->body read, `parse` = body->validated, `queue_handoff` =
validated->engine submit, `sse_drain` = engine finish->last byte
written, `disconnect` instants) on an "http" trace track joined to the
engine's lifecycle spans by the request id. The boundaries are
CONTIGUOUS stamps on the engine's own clock, so accept + parse +
queue_handoff + queue + prefill + decode + sse_drain partitions the
server-observed wall exactly — `GET /v1/requests/<id>` assembles that
end-to-end timeline (plus the request's speculative-acceptance,
kv-quant and page-usage facts) from a bounded in-memory registry, with
or without the recorder.

Shutdown ordering (`ApiServer.close`, idempotent): stop accepting new
work (503), drain active streams up to `drain_timeout_s` then cancel
the stragglers, stop the engine loop, `engine.close()`, then tear down
the HTTP threads — so no handler ever touches a closed engine.
"""

from __future__ import annotations

import json
import queue
import random
import re
import select
import socket
import threading
import time
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from solvingpapers_tpu.metrics.http import healthz_response
from solvingpapers_tpu.metrics.writer import PrometheusTextWriter
from solvingpapers_tpu.serve import metrics as smetrics
from solvingpapers_tpu.serve import openai as oai
from solvingpapers_tpu.serve.grammar import JsonStepper
from solvingpapers_tpu.serve.openai import ApiError
from solvingpapers_tpu.serve.scheduler import ACTIVE

# client-supplied X-Request-Id values we honor: short, printable, safe
# to echo into headers/JSON/trace args verbatim. Anything else gets a
# minted id (the request still traces — a hostile header must not be
# able to opt out of observability or smuggle bytes into the trace).
_RID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")


class EngineLoop:
    """The engine's single driver thread + the submit/cancel gateway.

    Every engine interaction from a handler thread goes through
    `self.lock`; the loop holds it across each `step()`, so the engine
    never sees concurrent mutation. Idle (no work) it parks on an event
    that `submit` sets — no busy-spin, sub-ms wake."""

    def __init__(self, engine, start: bool = True):
        self.engine = engine
        self.lock = threading.RLock()
        self._waiters = 0
        self._waiter_lock = threading.Lock()  # += is not atomic
        self._wake = threading.Event()
        self._stop = threading.Event()
        self.error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, name="engine-loop", daemon=True
        )
        if start:
            self._thread.start()

    def _locked(self, fn):
        """Run an engine call under the step lock, counted as a waiter
        so the loop hands the lock over instead of convoying."""
        with self._waiter_lock:
            self._waiters += 1
        try:
            with self.lock:
                return fn()
        finally:
            with self._waiter_lock:
                self._waiters -= 1

    def submit(self, *args, **kwargs):
        if self.error is not None:
            raise RuntimeError(
                f"engine loop died: {type(self.error).__name__}: "
                f"{self.error}"
            )
        req = self._locked(lambda: self.engine.submit(*args, **kwargs))
        self._wake.set()
        return req

    def cancel(self, req) -> None:
        # lock-free fast path for a live stream: cancelling an ACTIVE
        # request is ONE flag write the engine reads at the next block
        # boundary — taking the step lock here would make disconnect
        # cancel wait out the whole remaining stream (the loop re-wins
        # its own lock back-to-back; a handler thread parked on it can
        # starve for seconds — the classic convoy). The flag is written
        # directly, NOT via engine.cancel: its state re-check could race
        # a paged-pool preemption (ACTIVE -> WAITING) and run unlocked
        # queue surgery on this thread; the bare flag is safe in every
        # state (a preempted-then-resumed stream cancels at its next
        # block boundary, a finished one ignores it). A request we see
        # WAITING does need the lock for the queue removal; if it races
        # the other way (WAITING -> ACTIVE), the locked engine.cancel
        # re-checks and degrades to the same flag write.
        if req.state == ACTIVE:
            req.cancelled = True
        else:
            self._locked(lambda: self.engine.cancel(req))
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                with self.lock:
                    busy = self.engine.has_work()
                    if busy:
                        self.engine.step()
            except BaseException as e:  # noqa: BLE001 — must not die mute
                self._fail(e)
                return
            if self._waiters:
                # hand the lock over: without an explicit yield this
                # thread re-acquires it before a parked submitter ever
                # gets scheduled (lock convoy), and submissions stall
                # until the engine drains
                time.sleep(0.001)
            elif not busy:
                self._wake.wait(0.05)
                self._wake.clear()

    def _fail(self, exc: BaseException) -> None:
        """A step() raised: the engine may be inconsistent, so the loop
        stops driving it — but silently wedging every open stream would
        be worse (heartbeats forever, /healthz green). Record the error
        (new submissions fail fast), then force-finish every in-flight
        request host-side with reason "error" so each connection gets
        its terminal event and closes."""
        import traceback

        self.error = exc
        traceback.print_exception(type(exc), exc, exc.__traceback__)
        with self.lock:
            inflight = [r for r in self.engine._slot_req if r is not None]
            inflight += list(self.engine.scheduler.queue)
            now = time.monotonic()
            for r in inflight:
                r.state = "finished"
                r.finish_reason = "error"
                r.finish_time = now
                cb = r.stream_cb
                if cb is not None:
                    try:
                        cb(r, 0, True)
                    except Exception:  # noqa: BLE001
                        pass

    def close(self, drain_timeout_s: float = 0.0) -> None:
        """Stop the loop; with a drain timeout, let in-flight work
        finish first, then cancel whatever remains so the loop can exit
        having returned every lane. BOUNDED end to end: the
        cancel-resolution drain is also wall-capped (a wedged or
        fault-stalled program must not turn SIGTERM into a hang), and
        anything still in flight past the cap is force-finished
        host-side via `engine.force_drain` — no further device work."""
        if not self._thread.is_alive():
            return
        deadline = time.monotonic() + drain_timeout_s
        while time.monotonic() < deadline:
            with self.lock:
                if not self.engine.has_work():
                    break
            time.sleep(0.01)
        with self.lock:
            for r in list(self.engine._slot_req):
                if r is not None:
                    self.engine.cancel(r)
            for r in list(self.engine.scheduler.queue):
                self.engine.cancel(r)
            # one bounded drain pass finishes the cancelled streams
            # (cancels resolve at the next block boundary); capped on
            # BOTH steps and wall clock — a step stalled past the cap
            # falls through to the host-side force drain below
            steps = 0
            cancel_deadline = time.monotonic() + min(
                5.0, max(1.0, drain_timeout_s)
            )
            while (self.engine.has_work() and steps < 64
                   and time.monotonic() < cancel_deadline):
                self.engine.step()
                steps += 1
            if self.engine.has_work():
                self.engine.force_drain("cancelled")
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)


class _Stream:
    """Per-connection bridge from the engine's stream_cb to a handler
    thread: a bounded queue of (n_new, finished) counts. Full queue =
    coalesce (the reader catches up from the request's token list);
    the terminal event always lands (a slot is drained to make room)."""

    def __init__(self, maxsize: int):
        self.q: queue.Queue = queue.Queue(maxsize=max(2, maxsize))

    def __call__(self, req, n_new: int, finished: bool) -> None:
        try:
            self.q.put_nowait((n_new, finished))
        except queue.Full:
            if finished:
                try:
                    self.q.get_nowait()
                except queue.Empty:
                    pass
                self.q.put_nowait((n_new, finished))


class ApiServer:
    """The front door: binds `engine.config.api_host:api_port` and
    serves the OpenAI surface + the status endpoints over one listener.

    `decode` (ids -> text) renders streamed text and backs json_object
    mode's token table; `encode` (text -> ids) admits string prompts —
    without it only token-id prompts are accepted. `token_table`
    (id -> string list) skips the per-id decode probe when the caller
    already built one (`cli serve` does — one source of truth). `loop`
    lets tests inject an unstarted `EngineLoop`; by default the server
    owns one.
    """

    # request timelines kept for GET /v1/requests/<id>: a debug surface,
    # so bounded and evict-oldest (a long-lived server must not grow a
    # dict per request served). A client re-using an id overwrites the
    # older entry — last-wins, like the header contract implies.
    timeline_cap = 1024
    # replay runs kept for GET /v1/replay/<id> — same bounded evict-
    # oldest discipline (each record holds a full divergence report)
    replay_cap = 16

    def __init__(self, engine=None, *, encode=None, decode=None,
                 token_table=None, model_name: str = "solvingpapers",
                 loop=None, router=None):
        # fleet mode (serve/fleet.py FleetRouter): the front door keeps
        # its single submit/SSE surface and routes through the router —
        # replica 0 stays `self.engine`/`self.loop` as the config /
        # vocab / grammar / fault-plane source (every replica serves
        # the same model), while admissions, capacity, health, metrics
        # and statusz consult the fleet views
        self.router = router
        if router is not None:
            if engine is None:
                engine = router.replicas[0].engine
            if loop is None:
                loop = router.replicas[0].loop
        if engine is None:
            raise ValueError("ApiServer needs an engine or a router")
        cfg = engine.config
        self.engine = engine
        self.encode = encode
        self.decode = decode
        self.model_name = model_name
        self.loop = loop if loop is not None else EngineLoop(engine)
        self.closing = threading.Event()
        self._closed = False
        self._active = 0          # streams currently open
        self._counts = {
            "requests": 0, "streams": 0, "disconnects": 0,
            "rejected": 0, "client_errors": 0,
        }
        self._count_lock = threading.Lock()
        # jittered Retry-After source: a fixed hint synchronizes every
        # rejected client into a retry herd that lands back as one
        # burst — each 503 draws its own delay instead (seeded for
        # reproducible tests; the draw ORDER across racing handler
        # threads is inherently nondeterministic, which is fine — the
        # point is that the hints differ, not which client gets which)
        self._retry_rng = random.Random(0xFA17)
        self._retry_lock = threading.Lock()
        self._timelines: OrderedDict[str, dict] = OrderedDict()
        self._timeline_lock = threading.Lock()
        # replay observatory (serve/replay.py): bounded run registry,
        # one run in flight at a time (each run builds its own engine —
        # a second concurrent build would thrash the host), and the
        # replay/* gauge payload of the LAST finished run (empty until
        # one exists — the present-iff-enabled key-surface contract)
        self._replays: OrderedDict[str, dict] = OrderedDict()
        self._replay_lock = threading.Lock()
        self._replay_active = False
        self._replay_gauge_vals: dict[str, float] = {}
        vocab = getattr(getattr(engine.model, "cfg", None), "vocab_size",
                        None) or (1 << 31)
        self.vocab_size = vocab
        # token table for grammar mode: caller-supplied, or derived by
        # decoding each id once (None = id outside the detokenizer's
        # range / unprintable)
        self.token_table = list(token_table) if token_table else None
        if self.token_table is None and decode is not None \
                and vocab < (1 << 20):
            table = []
            for i in range(vocab):
                try:
                    table.append(decode([i]))
                except Exception:
                    table.append(None)
            self.token_table = table
        # allowed-set memo shared by every request's stepper: all
        # steppers run over the one token table, so state-keyed entries
        # are valid across requests (serve/grammar.py)
        self._grammar_cache: dict = {}
        self._grammar_err = None
        if cfg.json_mode and self.token_table is not None:
            try:
                JsonStepper(self.token_table)  # vocabulary viability
            except ValueError as e:
                self._grammar_err = str(e)
        elif cfg.json_mode:
            self._grammar_err = (
                "json_object mode needs the server constructed with a "
                "`decode` callable (token table)"
            )
        self.engine.metrics.add_gauge_provider(self._gauges)
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.0 close-delimited framing: SSE bodies end when the
            # connection does, no chunked encoding needed
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def do_GET(self):  # noqa: N802
                server._get(self)

            def do_POST(self):  # noqa: N802
                server._post(self)

        self._httpd = ThreadingHTTPServer(
            (cfg.api_host, cfg.api_port or 0), Handler
        )
        self._httpd.daemon_threads = True
        self.host = cfg.api_host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="api-http", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ plumbing

    def url(self, path: str = "") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def _gauges(self) -> dict:
        c = self._counts
        return {
            "serve/http_connections": float(self._active),
            "serve/http_requests": float(c["requests"]),
            "serve/http_streams": float(c["streams"]),
            "serve/http_disconnects": float(c["disconnects"]),
            "serve/http_rejected": float(c["rejected"]),
            "serve/http_client_errors": float(c["client_errors"]),
            # replay/* from the last finished replay run — {} until one
            # has run, so a replay-less server's key surface is unchanged
            **self._replay_gauge_vals,
        }

    def _bump(self, key: str, delta: int = 1) -> None:
        with self._count_lock:
            self._counts[key] += delta

    def _bump_active(self, delta: int) -> None:
        with self._count_lock:
            self._active += delta

    @staticmethod
    def _send(h, code: int, body: str, ctype: str,
              headers: dict | None = None) -> None:
        data = body.encode()
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        h.wfile.write(data)

    def _send_json(self, h, code: int, obj: dict,
                   headers: dict | None = None) -> None:
        self._send(h, code, json.dumps(obj) + "\n", "application/json",
                   headers)

    def _retry_headers(self) -> dict:
        """Backpressure headers for every 503: a JITTERED Retry-After
        (integer seconds; the base grows with the degradation rung, so
        a deeper squeeze pushes retries further out) plus the current
        rung itself — client observability into WHY it was shed."""
        src = self.router if self.router is not None else self.engine
        rung = getattr(src, "degradation_rung", 0)
        with self._retry_lock:
            retry = self._retry_rng.randint(1 + rung, 4 + rung)
        return {"Retry-After": str(retry),
                "X-Degradation-Rung": str(rung)}

    def _engines(self) -> list:
        """Every engine this front door fronts (fleet or single) — the
        scan set for recovered-request and journal lookups: after a
        drain migration the stream's record lives on a PEER replica."""
        if self.router is not None:
            return [r.engine for r in self.router.replicas]
        return [self.engine]

    def _find_recovered(self, rid: str):
        """The recovered/adopted Request for `rid` on ANY replica, or
        None — the Last-Event-ID resolution step between the live
        registry and the journal fallback. When both a drained
        replica's "migrated" husk and a peer's adopted request carry
        the id, the adopted one wins: its token list is the stream."""
        best = None
        for eng in self._engines():
            req = getattr(eng, "_recovered", {}).get(rid)
            if req is None:
                continue
            if req.finish_reason != "migrated":
                return req
            best = best or req
        return best

    def _journal_lookup(self, rid: str):
        """The best journal record for `rid` across the fleet: a LIVE
        entry anywhere wins outright (the stream is still running —
        e.g. adopted by a peer but not yet recovered into a Request);
        among finished entries, a real outcome beats the drained
        replica's ``"migrated"`` tombstone (the adopting replica's
        record is the one whose tokens are the stream's truth)."""
        best = None
        for eng in self._engines():
            entry = (eng.journal.lookup(rid)
                     if eng.journal is not None else None)
            if entry is None:
                continue
            if not entry.finished:
                return entry
            if best is None or (best.finish_reason == "migrated"
                                and entry.finish_reason != "migrated"):
                best = entry
        return best

    def _loop_for(self, req):
        """The EngineLoop that owns `req` — the router's owner map in
        fleet mode (a migrated stream's cancel must land on the replica
        actually decoding it), `self.loop` otherwise."""
        if self.router is not None:
            return self.router.owner_loop(req)
        return self.loop

    def _send_error(self, h, err: ApiError,
                    headers: dict | None = None) -> None:
        self._bump("rejected" if err.status == 503 else "client_errors")
        headers = dict(headers or {})
        if err.status == 503:
            headers.update(self._retry_headers())
        try:
            self._send_json(h, err.status, err.body(), headers)
        except (BrokenPipeError, ConnectionResetError):
            pass

    # ------------------------------------------------------------- routes

    def _get(self, h) -> None:
        path = h.path.split("?", 1)[0]
        try:
            if path == "/healthz":
                # the engine's health state machine through the shared
                # wire mapping (metrics/http.py healthz_response — the
                # status-port endpoint uses the same one, so the two
                # /healthz surfaces can never diverge); a dead engine
                # loop is unhealthy regardless of what the engine says.
                # Fleet mode serves the ROUTER's view: healthy while any
                # admitting replica is (the router steers around the
                # rest — one sick replica must not fail the fleet out
                # of an external balancer's rotation)
                if self.router is not None:
                    state = self.router.health
                else:
                    state = getattr(self.engine, "health", "healthy")
                    if self.loop.error is not None:
                        state = "unhealthy"
                code, body = healthz_response(state)
                self._send(h, code, body, "text/plain")
            elif path == "/metrics":
                # prom_snapshot: latency histograms render as native
                # _bucket/_sum/_count series on this pull path. Fleet
                # mode: ONE exposition with the unlabeled merged series
                # (exact LogHistogram merge) + replica="rN"-labeled
                # per-replica series (render_sets keeps one # TYPE per
                # name across the label sets)
                if self.router is not None:
                    text = PrometheusTextWriter.render_sets(
                        self.router.prom_sets())
                else:
                    with self.loop.lock:
                        step, snap = (self.engine._step_idx,
                                      self.engine.metrics.prom_snapshot())
                    text = PrometheusTextWriter.render(step, snap)
                self._send(h, 200, text, "text/plain; version=0.0.4")
            elif path == "/statusz":
                with self.loop.lock:
                    doc = self.engine.statusz()
                if self.router is not None:
                    # replica 0's engine doc stays the backbone (same
                    # keys as single-engine serving — dashboards keep
                    # working); the fleet section adds the per-replica
                    # occupancy/health/rung table + routing counters
                    doc["fleet"] = self.router.statusz()
                self._send_json(h, 200, doc)
            elif path == "/timeseriesz":
                # the rolling retrospective: per-replica docs in fleet
                # mode, the single engine's doc otherwise; 404 when the
                # owner runs without a store (timeseries=False)
                if self.router is not None:
                    self._send_json(h, 200, self.router.timeseriesz())
                elif getattr(self.engine, "timeseries", None) is not None:
                    self._send_json(h, 200, self.engine.timeseries.doc())
                else:
                    self._send(h, 404, "no time-series store (run with "
                               "timeseries enabled)\n", "text/plain")
            elif path == "/v1/models":
                self._send_json(h, 200, {
                    "object": "list",
                    "data": [{"id": self.model_name, "object": "model",
                              "owned_by": "local"}],
                })
            elif path.startswith("/v1/requests/"):
                self._request_status(h, path[len("/v1/requests/"):])
            elif path.startswith("/v1/replay/"):
                self._replay_status(h, path[len("/v1/replay/"):])
            else:
                self._send(h, 404, "not found\n", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001 — a handler must not die
            try:
                self._send(h, 500, f"{type(e).__name__}: {e}\n",
                           "text/plain")
            except (BrokenPipeError, ConnectionResetError):
                pass

    def _request_status(self, h, rid: str) -> None:
        """GET /v1/requests/<id>: the request's end-to-end timeline —
        HTTP phases + engine lifecycle phases (they partition the
        server-observed wall exactly: contiguous stamps on one clock)
        plus its speculative/kv-quant/page facts and SLO verdict."""
        with self._timeline_lock:
            rec = self._timelines.get(rid)
        if rec is None:
            # journal fallback: a request evicted from the bounded
            # registry (or served by a PREVIOUS process incarnation)
            # still has its full record in the write-ahead journal —
            # reconstruct what it holds, marked source "journal"
            doc = self._journal_timeline(rid)
            if doc is not None:
                self._send_json(h, 200, doc, {"X-Request-Id": rid})
                return
            self._send_json(h, 404, {"error": {
                "message": f"no timeline for request id {rid!r} (unknown, "
                           f"evicted past the last "
                           f"{self.timeline_cap} requests with no journal "
                           "record, or aged out of the journal's finished "
                           "window)",
                "type": "invalid_request_error", "param": None,
                "code": "request_not_found",
            }})
            return
        self._send_json(h, 200, self._assemble_timeline(rec),
                        {"X-Request-Id": rid})

    def _journal_timeline(self, rid: str) -> dict | None:
        """`GET /v1/requests/<id>` from the journal alone: no HTTP
        phases (the connection that carried the request may predate
        this process), but the durable facts — prompt/completion
        sizes, the committed token ids themselves, outcome, usage —
        are all reconstructible. `source: "journal"` marks the
        provenance; a live recovered request reports its current
        committed state."""
        entry = self._journal_lookup(rid)
        if entry is None:
            return None
        recovered = self._find_recovered(rid) is not None
        if entry.finished:
            state = "finished"
        elif recovered:
            state = "active"
        else:
            state = "journaled"
        return {
            "request_id": rid,
            "source": "journal",
            "state": state,
            "recovered": recovered,
            "finish_reason": entry.finish_reason,
            "tokens": list(entry.tokens),
            "usage": entry.usage,
            "facts": {
                "prompt_tokens": len(entry.prompt),
                "completion_tokens": len(entry.tokens),
                "grammar": entry.grammar,
            },
        }

    # ---------------------------------------------------------- replay

    def _post_replay(self, h) -> None:
        """POST /v1/replay: launch a bounded background replay of a
        journal against a candidate config (serve/replay.py) — the
        live engine's weights on a FRESH engine, the live engine never
        touched. Body: ``journal`` (default: this engine's own journal
        path), ``config_overrides`` (ServeConfig field -> value),
        ``max_requests`` (corpus cap, default 256), ``cut_stride``,
        ``pace``. One run in flight at a time (409 otherwise); poll
        GET /v1/replay/<id> for progress + the report. 202 on
        accept."""
        from solvingpapers_tpu.serve import replay as replay_mod

        try:
            body = self._read_body(h)
            journal = body.get("journal") or self.engine.config.journal_path
            if not journal:
                raise ApiError(
                    "no journal to replay: pass 'journal' (a path this "
                    "server can read) or serve with --journal",
                    param="journal")
            overrides = body.get("config_overrides") or {}
            if not isinstance(overrides, dict):
                raise ApiError("config_overrides must be an object",
                               param="config_overrides")
            try:
                candidate = replay_mod.apply_overrides(
                    self.engine.config, dict(overrides))
            except (ValueError, TypeError) as e:
                raise ApiError(str(e), param="config_overrides") from None
            max_requests = int(body.get("max_requests", 256))
            cut_stride = int(body.get("cut_stride", 8))
            pace = bool(body.get("pace", False))
        except ApiError as e:
            self._send_error(h, e)
            return
        with self._replay_lock:
            if self._replay_active:
                self._send_json(h, 409, {"error": {
                    "message": "a replay run is already in flight — "
                               "poll it to completion first",
                    "type": "invalid_request_error", "param": None,
                    "code": "replay_in_flight",
                }})
                return
            self._replay_active = True
            run_id = uuid.uuid4().hex[:12]
            rec = {
                "id": run_id, "state": "running",
                "progress": {"done": 0, "total": 1},
                "journal": journal, "config_overrides": overrides,
                "report": None, "error": None,
            }
            self._replays[run_id] = rec
            while len(self._replays) > self.replay_cap:
                self._replays.popitem(last=False)

        def work():
            try:
                harness = replay_mod.ReplayHarness.from_engine(
                    self.engine)
                entries = harness.load(journal)

                def prog(done, total):
                    rec["progress"] = {"done": done, "total": total}

                rec["report"] = harness.run(
                    entries, candidate, cut_stride=cut_stride,
                    max_requests=max_requests, pace=pace,
                    journal_path=journal, progress=prog)
                rec["state"] = "finished"
                # the replay/* gauges ride the LIVE engine's /metrics
                # and /statusz through the registered provider
                self._replay_gauge_vals = replay_mod.report_gauges(
                    rec["report"])
            except Exception as e:  # noqa: BLE001 — surfaced via GET
                rec["error"] = f"{type(e).__name__}: {e}"
                rec["state"] = "error"
            finally:
                with self._replay_lock:
                    self._replay_active = False

        threading.Thread(target=work, name="replay", daemon=True).start()
        self._send_json(h, 202, {"id": run_id, "state": "running"},
                        {"Location": f"/v1/replay/{run_id}"})

    def _replay_status(self, h, run_id: str) -> None:
        """GET /v1/replay/<id>: state + progress while running, the
        full divergence report once finished, the error string on
        failure. Bounded registry — evicted runs 404."""
        with self._replay_lock:
            rec = self._replays.get(run_id)
            doc = dict(rec) if rec is not None else None
        if doc is None:
            self._send_json(h, 404, {"error": {
                "message": f"no replay run {run_id!r} (unknown or "
                           f"evicted past the last {self.replay_cap} "
                           "runs)",
                "type": "invalid_request_error", "param": None,
                "code": "replay_not_found",
            }})
            return
        self._send_json(h, 200, doc)

    @staticmethod
    def _hop_phases(req) -> dict[str, float]:
        """One migration hop's engine phases from its Request stamps:
        queue / prefill / decode up to ITS finish (a migrated husk
        finishes "migrated" at the drain, so its intervals are closed
        — the trail's partition stays exact across the hop). A hop
        admitted but frozen before its first token spent its whole
        admitted life in prefill."""
        ph: dict[str, float] = {}
        if req.admit_time is not None:
            ph["queue"] = req.admit_time - req.submit_time
            if req.first_token_time is not None:
                ph["prefill"] = req.first_token_time - req.admit_time
                if req.finish_time is not None:
                    ph["decode"] = req.finish_time - req.first_token_time
            elif req.finish_time is not None:
                ph["prefill"] = req.finish_time - req.admit_time
        elif req.finish_time is not None:
            ph["queue"] = req.finish_time - req.submit_time
        return ph

    def _assemble_timeline(self, rec: dict) -> dict:
        """One JSON timeline from the HTTP record + the engine Request's
        own lifecycle timestamps. Phases are adjacent intervals —
        accept -> parse -> [route] -> queue_handoff -> queue -> prefill
        -> decode -> [migrate -> peer_queue -> peer_prefill ->
        peer_decode ...] -> sse_drain — so their sum equals t_done -
        t_accept (the server-observed e2e wall) to the clock's
        resolution; in-flight requests report the phases they have
        reached so far.

        Fleet: `route` is the router's ranking+retry wall
        (`Request.fleet_route_s`), carved out of the handoff window it
        happens inside so the partition is preserved; after a drain
        migration the trail keeps EVERY hop — the original replica's
        phases up to its "migrated" finish (the husks `rec["hops"]`
        preserved before the front door swapped in each successor),
        the `migrate` gap (freeze -> adoption on the peer), then the
        adopting replica's phases as peer_*."""
        req = rec["req"]
        hops = rec.get("hops") or []
        chain = [hp["req"] for hp in hops] + [req]
        req0 = chain[0]
        cfg = self.engine.config
        phases: dict[str, float] = {
            "accept": rec["t_body"] - rec["t_accept"],
            "parse": rec["t_parsed"] - rec["t_body"],
        }
        handoff = max(req0.submit_time - rec["t_parsed"], 0.0)
        route_s = min(max(getattr(req0, "fleet_route_s", 0.0), 0.0),
                      handoff)
        if route_s > 0:
            phases["route"] = route_s
        phases["queue_handoff"] = handoff - route_s
        if not hops:
            if req.admit_time is not None:
                phases["queue"] = req.admit_time - req.submit_time
                if req.first_token_time is not None:
                    phases["prefill"] = (req.first_token_time
                                         - req.admit_time)
                    if req.finish_time is not None:
                        phases["decode"] = (req.finish_time
                                            - req.first_token_time)
            elif req.finish_time is not None:
                # never admitted (cancel/timeout in the queue, or
                # rejected): its whole engine life was queue time
                phases["queue"] = req.finish_time - req.submit_time
        else:
            phases.update(self._hop_phases(req0))
            for prev, nxt in zip(chain, chain[1:]):
                if prev.finish_time is not None:
                    phases["migrate"] = (
                        phases.get("migrate", 0.0)
                        + max(nxt.submit_time - prev.finish_time, 0.0))
                for k, v in self._hop_phases(nxt).items():
                    key = f"peer_{k}"
                    phases[key] = phases.get(key, 0.0) + v
        if rec["t_done"] is not None and req.finish_time is not None:
            phases["sse_drain"] = max(rec["t_done"] - req.finish_time, 0.0)
        phases = {k: round(v, 6) for k, v in phases.items()}
        facts: dict = {
            "prompt_tokens": int(req.prompt.size),
            "completion_tokens": len(req.tokens),
            "kv_quant": cfg.kv_quant,
            "kv_exact": bool(req.params.kv_exact),
        }
        if cfg.speculative is not None:
            facts["spec"] = {
                "drafter": cfg.speculative,
                "proposed": req.spec_proposed,
                "accepted": req.spec_accepted,
                "acceptance_rate": round(
                    req.spec_accepted / req.spec_proposed, 4
                ) if req.spec_proposed else None,
            }
        if cfg.paged:
            facts["pages_held"] = req.pages_held
            facts["page_size"] = self.engine.pool.page_size
        doc = {
            "request_id": rec["trace_id"],
            "engine_req": req.id,
            "kind": "chat" if rec["chat"] else "completion",
            "stream": rec["stream"],
            "state": req.state,
            "finish_reason": req.finish_reason,
            "disconnected": rec["disconnected"],
            "phases": phases,
            "phase_sum_s": round(sum(phases.values()), 6),
            "e2e_s": round(rec["t_done"] - rec["t_accept"], 6)
            if rec["t_done"] is not None else None,
            "facts": facts,
        }
        if req.slo_result is not None:
            doc["slo"] = req.slo_result
        elif cfg.slo_targets is not None:
            # in flight (or excluded finish): class known, verdict not
            doc["slo"] = {"class": self.engine._slo.classify(req),
                          "attained": None}
        if self.router is not None:
            # the fleet trail facts: which replica served (or is
            # serving) the request, how many peers refused before one
            # took it, and — after a drain migration — every hop the
            # stream took (the husks' engine ids + finish reasons plus
            # the live successor), matching the phases' migrate/peer_*
            # entries above
            doc["fleet"] = {
                "replica": rec.get("replica"),
                "reroutes": int(rec.get("reroutes") or 0),
                "migrated": bool(hops),
                "hops": [
                    {"replica": hp.get("replica"),
                     "engine_req": hp["req"].id,
                     "finish_reason": hp["req"].finish_reason}
                    for hp in hops
                ] + [{"replica": rec.get("replica"),
                      "engine_req": req.id,
                      "finish_reason": req.finish_reason}],
            }
        return doc

    def _post(self, h) -> None:
        # accept boundary: first stamp after the server parsed the
        # request line + headers — everything from here to the last
        # response byte is carved into contiguous spans on this clock
        t_accept = smetrics.now()
        path = h.path.split("?", 1)[0]
        if path == "/v1/replay":
            self._post_replay(h)
            return
        chat = path == "/v1/chat/completions"
        if not chat and path != "/v1/completions":
            self._send(h, 404, "not found\n", "text/plain")
            return
        self._bump("requests")
        # stream resumption: a reconnect presents the last SSE event id
        # it saw ("<request id>:<token offset>") instead of a new job —
        # replay the already-committed tokens (live request, a recovered
        # one after a restart, or the journal's record of a finished
        # stream) and re-attach to the live tail
        lei = (h.headers.get("Last-Event-ID") or "").strip()
        if lei:
            try:
                self._drain_body(h)
                self._resume_stream(h, lei, chat)
            except ApiError as e:
                self._send_error(h, e)
            except (BrokenPipeError, ConnectionResetError):
                self._bump("disconnects")
            except Exception as e:  # noqa: BLE001
                try:
                    self._send_json(h, 500, {"error": {
                        "message": f"{type(e).__name__}: {e}",
                        "type": "internal_error", "param": None,
                        "code": None,
                    }})
                except (BrokenPipeError, ConnectionResetError):
                    pass
            return
        # honor the client's X-Request-Id (sane values only), else mint:
        # the id rides the engine Request, the trace, the response
        # header, and GET /v1/requests/<id> — one identity end to end
        rid_in = (h.headers.get("X-Request-Id") or "").strip()
        trace_id = rid_in if _RID_RE.match(rid_in) else uuid.uuid4().hex
        rid_headers = {"X-Request-Id": trace_id}
        try:
            body = self._read_body(h)
            t_body = smetrics.now()
            self._serve_completion(h, body, chat=chat, trace_id=trace_id,
                                   t_accept=t_accept, t_body=t_body)
        except ApiError as e:
            self._send_error(h, e, headers=rid_headers)
        except (BrokenPipeError, ConnectionResetError):
            self._bump("disconnects")
        except Exception as e:  # noqa: BLE001
            try:
                self._send_json(h, 500, {"error": {
                    "message": f"{type(e).__name__}: {e}",
                    "type": "internal_error", "param": None, "code": None,
                }}, rid_headers)
            except (BrokenPipeError, ConnectionResetError):
                pass

    @staticmethod
    def _check_resume_offset(offset: int, committed: int, rid: str) -> None:
        """Reject a resume offset past the committed prefix instead of
        silently clamping: fsync batches per step, so after a hard
        crash a client can hold tokens the journal never made durable —
        replaying from the clamp would hand it that span a SECOND time
        with no signal. 409 tells it to restart (or re-request inside
        the committed prefix) explicitly."""
        if offset > committed:
            raise ApiError(
                f"Last-Event-ID offset {offset} exceeds the {committed} "
                f"committed token(s) recoverable for request {rid!r} — "
                "the tail past the last durable commit was lost with "
                "the crash; resume from within the committed prefix or "
                "restart the stream",
                status=409, code="resume_offset_beyond_committed",
            )

    def _sse_open(self, h, trace_id: str, replica: str | None = None,
                  reroutes: int = 0):
        """Send the SSE response headers and return THE event writer
        (one framing implementation for live streams, re-attached
        resumes and journal-only replays): each chunk is an optional
        ``id: <trace_id>:<eid>`` resume cursor + a ``data:`` line, and
        the fault plane's ``sse_write`` site pokes per event
        (socket_reset/stall specs apply to replayed streams exactly
        like live ones). FaultPlan.poke serializes internally —
        handler threads and the engine loop share one plan across
        lock domains."""
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("X-Request-Id", trace_id)
        if replica is not None:
            h.send_header("X-Replica-Id", replica)
        if reroutes:
            # submit was retried on a peer after ranked replicas
            # refused — reroute visibility alongside X-Replica-Id
            h.send_header("X-Fleet-Reroutes", str(reroutes))
        h.end_headers()

        def event(obj, eid: int | None = None) -> None:
            faults = getattr(self.engine, "_faults", None)
            if faults is not None:
                for spec in faults.poke("sse_write"):
                    self.engine.metrics.record_fault_injected()
                    tr = self.engine.trace
                    if tr is not None:
                        # same instant the engine's _poke_site stamps,
                        # so counters and timeline agree on injections
                        tr.instant("fault_injected", "engine", "http",
                                   site="sse_write", kind=spec.kind,
                                   slot=spec.slot)
                    if spec.kind == "socket_reset":
                        raise ConnectionResetError(
                            "injected socket reset at sse_write"
                        )
                    if spec.kind == "stall":
                        time.sleep(spec.stall_s)
            payload = b""
            if eid is not None:
                payload += f"id: {trace_id}:{eid}\n".encode()
            payload += b"data: " + json.dumps(obj).encode() + b"\n\n"
            h.wfile.write(payload)
            h.wfile.flush()

        return event

    @staticmethod
    def _drain_body(h) -> None:
        """Consume (and discard) any request body: a resume reconnect
        needs only the Last-Event-ID header, but the bytes must still
        be read off the socket before the SSE response streams back."""
        try:
            n = int(h.headers.get("Content-Length", 0))
        except ValueError:
            n = 0
        if 0 < n <= (8 << 20):
            h.rfile.read(n)

    def _resume_stream(self, h, lei: str, chat: bool) -> None:
        """Resume a stream from its last delivered SSE event id.

        The id is ``<request id>:<token offset>`` (exactly what the
        server stamped on the `id:` field of every chunk). Sources, in
        order: the live request registry (same process), the engine's
        recovered set (`ServeEngine.recover` after a restart), then the
        write-ahead journal's record of a finished stream. Committed
        tokens past the offset replay immediately; a still-live request
        re-attaches this connection to its tail (the previous
        connection's bridge is abandoned — last reconnect wins, like
        the X-Request-Id contract)."""
        rid, _, off_s = lei.rpartition(":")
        # ASCII digits only: str.isdigit() accepts exotic Unicode
        # digits that int() then rejects, which would turn a malformed
        # header into a 500 instead of this 400
        if not rid or not (off_s.isascii() and off_s.isdigit()):
            raise ApiError(
                f"malformed Last-Event-ID {lei!r} — expected "
                "\"<request id>:<token offset>\" as stamped on the "
                "stream's id: fields", param="Last-Event-ID",
            )
        offset = int(off_s)
        with self._timeline_lock:
            rec = self._timelines.get(rid)
        req = rec["req"] if rec is not None else None
        if req is None:
            req = self._find_recovered(rid)
        if req is not None and req.finish_reason == "migrated" \
                and self.router is not None:
            # the registry's object is the DRAINED replica's husk; the
            # peer's adopted request (same id, same committed prefix,
            # still decoding) is the stream the cursor belongs to
            adopted = self._find_recovered(rid)
            if adopted is not None and adopted is not req:
                if rec is not None:
                    # keep the husk: its phases are the original
                    # replica's leg of the request trail
                    rec.setdefault("hops", []).append(
                        {"req": req, "replica": rec.get("replica")})
                    rec["req"] = adopted
                req = adopted
        if req is not None:
            self._check_resume_offset(offset, len(req.tokens), rid)
            owner = (self.router.owner(rid)
                     if self.router is not None else None)
            if rec is not None and owner is not None:
                rec["replica"] = owner.rid
            new_rec = {
                "trace_id": rid, "req": req, "chat": chat, "stream": True,
                "t_accept": smetrics.now(), "t_body": smetrics.now(),
                "t_parsed": smetrics.now(), "t_done": None,
                "disconnected": False,
                "replica": owner.rid if owner is not None else None,
            }
            bridge = _Stream(self.engine.config.stream_queue)
            if not req.done:
                # re-attach: the engine reads stream_cb at each notify,
                # so the flip is one reference write; a notification
                # racing the flip is absorbed by the drain loop's
                # req.done / token-count polling
                req.stream_cb = bridge
            # prime one event so the replay of already-committed tokens
            # does not wait out the loop's 0.5s poll
            bridge(req, 0, req.done)
            self._bump("streams")
            rid_out = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
            self._stream_response(h, req, bridge, rid_out, chat, new_rec,
                                  start=offset)
            return
        entry = self._journal_lookup(rid)
        if entry is None:
            raise ApiError(
                f"no resumable stream for request id {rid!r} (unknown, "
                "or aged out of the journal's finished window)",
                status=404, code="request_not_found",
            )
        # journal-only replay: the stream has no live engine object
        # (finished, or a restart that never ran recover()) — replay the
        # committed record and close it out honestly
        self._check_resume_offset(offset, len(entry.tokens), rid)
        self._bump("streams")
        rid_out = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        event = self._sse_open(h, rid)

        # ONE delta implementation (_delta): render the already-seen
        # prefix, then diff — a non-prefix-stable detokenizer resends
        # the full text instead of slicing garbage
        rendered = ""
        if offset:
            _, rendered = self._delta(entry.tokens, offset, "")
        delta, _ = self._delta(entry.tokens, len(entry.tokens), rendered)
        upto = len(entry.tokens)
        if chat:
            event(oai.chat_chunk(rid_out, self.model_name, None,
                                 role=True), eid=offset)
            if delta:
                event(oai.chat_chunk(rid_out, self.model_name, delta),
                      eid=upto)
        elif delta:
            event(oai.completion_chunk(rid_out, self.model_name, delta),
                  eid=upto)
        reason = entry.finish_reason if entry.finished else "error"
        if not entry.finished:
            event(oai.error_event(
                "stream is not live on this server (it was journaled "
                "but not recovered) — committed tokens above are "
                "complete as delivered"))
        usage = entry.usage or {
            "prompt_tokens": len(entry.prompt),
            "completion_tokens": len(entry.tokens),
        }
        usage = {**usage, "total_tokens":
                 usage.get("prompt_tokens", 0)
                 + usage.get("completion_tokens", 0)}
        if chat:
            event(oai.chat_chunk(rid_out, self.model_name, None,
                                 reason=reason, usage=usage), eid=upto)
        else:
            event(oai.completion_chunk(rid_out, self.model_name, "",
                                       reason=reason, usage=usage),
                  eid=upto)
        h.wfile.write(b"data: [DONE]\n\n")
        h.wfile.flush()

    @staticmethod
    def _read_body(h) -> dict:
        try:
            n = int(h.headers.get("Content-Length", 0))
        except ValueError:
            n = 0
        if n <= 0 or n > (8 << 20):
            raise ApiError("request body required (JSON)", param=None)
        raw = h.rfile.read(n)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ApiError(f"request body is not valid JSON: {e.msg}",
                           param=None) from None
        if not isinstance(body, dict):
            raise ApiError("request body must be a JSON object")
        return body

    # -------------------------------------------------------- completion

    def _serve_completion(self, h, body: dict, chat: bool, trace_id: str,
                          t_accept: float, t_body: float) -> None:
        cfg = self.engine.config
        if self.closing.is_set():
            raise ApiError("server is shutting down", status=503,
                           err_type="server_error", code="shutting_down")
        if self.router is None and self.loop.error is not None:
            # fleet mode has no single fatal loop: a dead replica just
            # stops admitting and the router routes around it (only an
            # empty candidate set 503s, below)
            raise ApiError(
                "engine loop failed — the server needs a restart "
                f"({type(self.loop.error).__name__})", status=503,
                err_type="server_error", code="engine_failed",
            )
        params, max_tokens, timeout_s = oai.parse_sampling(
            body,
            slo_classes=set(cfg.slo_targets) if cfg.slo_targets else None,
        )
        stream = bool(body.get("stream", False))
        json_mode = oai.wants_json(body, cfg.json_mode)
        if json_mode and self._grammar_err:
            raise ApiError(self._grammar_err, param="response_format")
        if chat:
            prompt_ids = oai.parse_prompt(
                {"prompt": oai.chat_prompt(body)}, self.encode,
                self.vocab_size,
            )
        else:
            prompt_ids = oai.parse_prompt(body, self.encode,
                                          self.vocab_size)
        if stream and self._active >= cfg.api_max_connections:
            raise ApiError(
                f"too many concurrent streams "
                f"({cfg.api_max_connections}) — retry shortly",
                status=503, err_type="server_error", code="overloaded",
            )
        # the backpressure probe consults FLEET-wide queue room when a
        # router fronts several replicas: one busy replica must not 503
        # a request a peer has capacity for (the router also retries
        # ranked candidates on a host-side queue-full rejection below)
        capacity = (self.router.capacity_left if self.router is not None
                    else self.engine.scheduler.capacity_left)
        if capacity == 0:
            raise ApiError(
                "waiting queue is full"
                + (" fleet-wide" if self.router is not None else "")
                + " — retry shortly", status=503,
                err_type="server_error", code="overloaded",
            )
        grammar = (JsonStepper(self.token_table, cache=self._grammar_cache)
                   if json_mode else None)
        bridge = _Stream(cfg.stream_queue)
        # parse boundary: body decoded, sampling/prompt validated, the
        # grammar built — the next stamp the request gets is its own
        # submit_time inside the locked engine call, so the gap between
        # here and there IS the submit-lock handoff
        t_parsed = smetrics.now()
        replica = None
        try:
            if self.router is not None:
                # prefix-affinity + SLO-burn + least-loaded routing,
                # with ranked retry on a full replica queue
                replica, req = self.router.submit(
                    np.asarray(prompt_ids, np.int32),
                    max_new_tokens=max_tokens, params=params,
                    deadline_s=timeout_s, grammar=grammar,
                    stream_cb=bridge, trace_id=trace_id,
                )
                if req is None:
                    raise ApiError(
                        "no replica is admitting (fleet draining or "
                        "unhealthy) — retry shortly", status=503,
                        err_type="server_error", code="engine_unhealthy",
                    )
            else:
                req = self.loop.submit(
                    np.asarray(prompt_ids, np.int32),
                    max_new_tokens=max_tokens, params=params,
                    deadline_s=timeout_s, grammar=grammar,
                    stream_cb=bridge,
                    # the engine journals under this id, so a restarted
                    # server can answer Last-Event-ID reconnects and
                    # /v1/requests/<id> for it
                    trace_id=trace_id,
                )
        except ValueError as e:
            code = ("context_length_exceeded"
                    if "exceeds the engine capacity" in str(e) else None)
            raise ApiError(str(e), code=code) from None
        if req.trace_id is not None and req.trace_id != trace_id:
            # the engine re-keyed a duplicate still-live X-Request-Id to
            # protect the journal (two streams must not merge commits):
            # the client must be told the id its stream is actually
            # addressable by — SSE cursors, the echoed header, the
            # registry entry and post-restart resume all use it (same
            # contract as minting over a malformed header)
            trace_id = req.trace_id
        rec = {
            "trace_id": trace_id, "req": req, "chat": chat,
            "stream": stream, "t_accept": t_accept, "t_body": t_body,
            "t_parsed": t_parsed, "t_done": None, "disconnected": False,
            # which replica admitted it (fleet mode) — the
            # X-Replica-Id response header, for debugging routing
            "replica": replica.rid if replica is not None else None,
            # how many ranked peers refused before one admitted it
            # (router retry-on-full) — the X-Fleet-Reroutes header
            "reroutes": int(getattr(req, "fleet_reroutes", 0) or 0),
            # migration hops: each drain that moved this stream swaps
            # rec["req"] to the adopted successor; the husk is kept
            # here FIRST, so /v1/requests/<id> can stitch the full
            # trail (original replica's phases + migrate gap + peer's)
            "hops": [],
        }
        with self._timeline_lock:
            self._timelines[trace_id] = rec
            self._timelines.move_to_end(trace_id)
            while len(self._timelines) > self.timeline_cap:
                self._timelines.popitem(last=False)
        tr = self.engine.trace
        if tr is not None:
            # HTTP-layer spans on the shared recorder, joined to the
            # engine's lifecycle spans by req id: contiguous boundaries
            # (t_accept -> t_body -> t_parsed -> submit_time) extend the
            # queue+prefill+decode partition across the HTTP boundary
            tr.complete("accept", "http", "http", ts=t_accept,
                        dur=t_body - t_accept, req=req.id,
                        trace_id=trace_id)
            tr.complete("parse", "http", "http", ts=t_body,
                        dur=t_parsed - t_body, req=req.id)
            tr.complete("queue_handoff", "http", "http", ts=t_parsed,
                        dur=max(req.submit_time - t_parsed, 0.0),
                        req=req.id)
        if req.state == "rejected":
            self._bump("rejected")
            rec["t_done"] = smetrics.now()
            why = req.reject_reason or ""
            if why == "unhealthy":
                err = ApiError(
                    "engine is unhealthy and draining — retry shortly",
                    status=503, err_type="server_error",
                    code="engine_unhealthy",
                )
            elif why.startswith("shed:"):
                shed_eng = (replica.engine if replica is not None
                            else self.engine)
                err = ApiError(
                    f"admissions for SLO class {why[5:]!r} are being "
                    f"load-shed (degradation rung "
                    f"{getattr(shed_eng, 'degradation_rung', 0)}) — "
                    "retry after the hinted delay",
                    status=503, err_type="server_error", code="overloaded",
                )
            else:
                err = ApiError(
                    "waiting queue is full"
                    + (" fleet-wide" if self.router is not None else "")
                    + " — retry shortly", status=503,
                    err_type="server_error", code="overloaded",
                )
            headers = {**self._retry_headers(), "X-Request-Id": trace_id}
            if rec["replica"] is not None:
                headers["X-Replica-Id"] = rec["replica"]
            if rec["reroutes"]:
                headers["X-Fleet-Reroutes"] = str(rec["reroutes"])
            self._send_json(h, 503, err.body(), headers)
            return
        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        if stream:
            self._bump("streams")
            self._stream_response(h, req, bridge, rid, chat, rec)
        else:
            self._blocking_response(h, req, bridge, rid, chat, rec)

    def _delta(self, tokens, upto: int, rendered: str) -> tuple[str, str]:
        """Text delta for tokens[:upto] given what was already rendered.
        Full re-decode (not per-token) so merge-y detokenizers stay
        correct; suffix-after-prefix keeps the stream append-only."""
        if self.decode is None:
            text = "".join(str(t) + " " for t in tokens[:upto])
        else:
            text = self.decode(list(tokens[:upto]))
        if text.startswith(rendered):
            return text[len(rendered):], text
        return text, text  # non-prefix-stable detokenizer: resend

    def _disconnected(self, h) -> bool:
        """Probe the socket for a client half-close without consuming
        request data (there is none after the body in this protocol)."""
        try:
            r, _, _ = select.select([h.connection], [], [], 0)
            if r:
                return h.connection.recv(1, socket.MSG_PEEK) == b""
        except OSError:
            return True
        return False

    def _mark_disconnect(self, req, rec) -> None:
        rec["disconnected"] = True
        rec["t_done"] = smetrics.now()
        self._bump("disconnects")
        tr = self.engine.trace
        if tr is not None:
            tr.instant("disconnect", "http", "http", req=req.id)

    def _mark_done(self, req, rec, events: int = 0) -> None:
        """Stamp the drain boundary: engine finish -> last response byte
        flushed (the tail the client observes after the engine is done —
        event rendering, detokenize, socket writes)."""
        t_done = smetrics.now()
        rec["t_done"] = t_done
        tr = self.engine.trace
        if tr is not None and req.finish_time is not None:
            tr.complete("sse_drain", "http", "http", ts=req.finish_time,
                        dur=max(t_done - req.finish_time, 0.0),
                        req=req.id, events=events)

    def _stream_response(self, h, req, bridge, rid: str,
                         chat: bool, rec: dict, start: int = 0) -> None:
        """`start` > 0 is a Last-Event-ID reconnect: tokens[:start] were
        already delivered to this client — replay resumes from there
        (the committed prefix re-renders so text deltas stay exact).
        Event framing (id: resume cursors + data: lines + the
        sse_write fault site) is `_sse_open`'s — one writer for live
        streams and journal replays."""
        event = self._sse_open(h, rec["trace_id"],
                               replica=rec.get("replica"),
                               reroutes=int(rec.get("reroutes") or 0))
        self._bump_active(1)
        emitted = start
        events = 0
        rendered = ""
        if start > 0:
            _, rendered = self._delta(req.tokens, start, "")

        def cancel_if_mine() -> None:
            # last reconnect wins: a Last-Event-ID re-attach flips
            # req.stream_cb to ITS bridge — an abandoned pre-reconnect
            # handler noticing its own dead socket afterwards must not
            # cancel the stream out from under the live client. The
            # owner lookup routes the cancel to the replica actually
            # decoding (it may have migrated since admission).
            if not req.done and req.stream_cb is bridge:
                self._loop_for(req).cancel(req)

        try:
            if chat:
                event(oai.chat_chunk(rid, self.model_name, None, role=True),
                      eid=emitted)
            while True:
                try:
                    _, finished = bridge.q.get(timeout=0.5)
                except queue.Empty:
                    if req.done:
                        finished = True  # cb raced the queue; finish now
                    elif self._disconnected(h):
                        cancel_if_mine()
                        self._mark_disconnect(req, rec)
                        return
                    else:
                        # SSE comment heartbeat: keeps proxies from
                        # timing the stream out AND surfaces a dead
                        # socket as a write error between tokens
                        h.wfile.write(b": ping\n\n")
                        h.wfile.flush()
                        continue
                # probe for a half-closed client BEFORE writing: a FIN
                # arrives long before a write raises (small SSE events
                # vanish into the send buffer and tiny models finish a
                # whole stream before the first EPIPE), and the peek is
                # two syscalls against a network round trip of tokens
                if self._disconnected(h):
                    cancel_if_mine()
                    self._mark_disconnect(req, rec)
                    return
                upto = len(req.tokens)
                if upto > emitted:
                    delta, rendered = self._delta(req.tokens, upto, rendered)
                    if chat:
                        event(oai.chat_chunk(rid, self.model_name, delta),
                              eid=upto)
                    else:
                        event(oai.completion_chunk(rid, self.model_name,
                                                   delta), eid=upto)
                    emitted = upto
                    events += 1
                if finished:
                    if req.finish_reason == "migrated":
                        # fleet drain: the stream CONTINUES on a peer
                        # replica — close WITHOUT a terminal chunk or
                        # [DONE] (an unterminated SSE stream is the
                        # standard "reconnect with your Last-Event-ID"
                        # signal; the cursor resolves on the adopting
                        # replica through the recovered-set path,
                        # token-exact from exactly this offset). The
                        # committed prefix was fully delivered above:
                        # force_drain froze the token list before the
                        # entries were snapshotted for adoption.
                        h.wfile.write(b": migrated - reconnect with "
                                      b"Last-Event-ID\n\n")
                        h.wfile.flush()
                        self._mark_done(req, rec, events=events)
                        return
                    if req.finish_reason == "error":
                        # SSE error protocol: a quarantined / engine-
                        # failed stream ends with a STRUCTURED error
                        # event before its terminal chunk — never a
                        # silently truncated stream
                        event(oai.error_event(
                            "the request failed in the engine "
                            "(finish_reason error) — partial output "
                            "above is complete as delivered",
                        ))
                    usage = oai.usage_block(req)
                    if chat:
                        event(oai.chat_chunk(rid, self.model_name, None,
                                             reason=req.finish_reason,
                                             usage=usage), eid=emitted)
                    else:
                        event(oai.completion_chunk(rid, self.model_name,
                                                   "",
                                                   reason=req.finish_reason,
                                                   usage=usage),
                              eid=emitted)
                    h.wfile.write(b"data: [DONE]\n\n")
                    h.wfile.flush()
                    self._mark_done(req, rec, events=events + 1)
                    return
        except (BrokenPipeError, ConnectionResetError, OSError):
            # client went away mid-stream: free the slot at the next
            # block boundary and count the disconnect
            cancel_if_mine()
            self._mark_disconnect(req, rec)
        except Exception as e:  # noqa: BLE001 — server-side failure
            # AFTER the 200 + SSE headers went out: the status line is
            # spent, so emit the structured error event + a terminal
            # chunk with finish_reason "error" + [DONE] (best-effort —
            # the socket may be the thing that broke), then release the
            # engine side
            cancel_if_mine()
            try:
                payload = (b"data: " + json.dumps(oai.error_event(
                    f"{type(e).__name__}: {e}")).encode() + b"\n\n")
                term = (oai.chat_chunk(rid, self.model_name, None,
                                       reason="error")
                        if chat else
                        oai.completion_chunk(rid, self.model_name, "",
                                             reason="error"))
                payload += (b"data: " + json.dumps(term).encode()
                            + b"\n\ndata: [DONE]\n\n")
                h.wfile.write(payload)
                h.wfile.flush()
            except OSError:
                pass
            self._mark_done(req, rec, events=events + 2)
        finally:
            self._bump_active(-1)

    def _blocking_response(self, h, req, bridge, rid: str,
                           chat: bool, rec: dict) -> None:
        self._bump_active(1)
        try:
            while True:
                while not req.done:
                    try:
                        _, finished = bridge.q.get(timeout=0.5)
                        if finished and req.done:
                            break
                    except queue.Empty:
                        if self._disconnected(h):
                            self._loop_for(req).cancel(req)
                            self._mark_disconnect(req, rec)
                            return
                if req.finish_reason != "migrated" or self.router is None:
                    break
                # fleet drain mid-request: no bytes have gone out on a
                # blocking response, so the migration is TRANSPARENT —
                # pick up the adopted request on the peer and keep
                # waiting (its committed prefix is this one's; SSE
                # clients get the reconnect protocol instead)
                nxt = self._find_recovered(req.trace_id)
                if nxt is None:
                    # the drain force-finishes the husk BEFORE the peer
                    # adopts it, so this thread can wake mid-migration:
                    # give the in-flight adoption a bounded window to
                    # land before honestly reporting the husk
                    deadline = time.monotonic() + 5.0
                    while nxt is None and time.monotonic() < deadline:
                        time.sleep(0.002)
                        nxt = self._find_recovered(req.trace_id)
                if nxt is None or nxt is req:
                    break  # adoption failed: report the husk honestly
                # keep the husk: its queue/prefill/decode up to the
                # "migrated" finish are the original replica's leg of
                # the request trail (/v1/requests/<id>)
                rec.setdefault("hops", []).append(
                    {"req": req, "replica": rec.get("replica")})
                req = nxt
                rec["req"] = req
                owner = self.router.owner(req.trace_id)
                rec["replica"] = owner.rid if owner is not None else None
                if not req.done:
                    req.stream_cb = bridge
                bridge(req, 0, req.done)  # re-prime past the 0.5s poll
            if self.decode is not None:
                text = self.decode(list(req.tokens))
            else:
                text = "".join(str(t) + " " for t in req.tokens)
            headers = {"X-Request-Id": rec["trace_id"]}
            if rec.get("replica") is not None:
                headers["X-Replica-Id"] = rec["replica"]
            if rec.get("reroutes"):
                headers["X-Fleet-Reroutes"] = str(rec["reroutes"])
            if req.finish_reason == "error":
                # no bytes have gone out on a blocking response: the
                # honest status is a 500 with the structured envelope,
                # not a 200 wrapping a failed stream
                self._send_json(h, 500, oai.error_event(
                    "the request failed in the engine "
                    "(finish_reason error)"), headers)
                self._mark_done(req, rec, events=1)
                return
            if chat:
                self._send_json(h, 200, oai.chat_response(
                    rid, self.model_name, req, text), headers)
            else:
                self._send_json(h, 200, oai.completion_response(
                    rid, self.model_name, req, text), headers)
            self._mark_done(req, rec, events=1)
        finally:
            self._bump_active(-1)

    # -------------------------------------------------------------- close

    def close(self) -> None:
        """Graceful shutdown, idempotent: refuse new work, drain active
        streams (up to `drain_timeout_s`, then cancel), stop the engine
        loop, close the engine, then the HTTP threads."""
        if self._closed:
            return
        self._closed = True
        self.closing.set()
        cfg = self.engine.config
        deadline = time.monotonic() + cfg.drain_timeout_s
        while self._active > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        if self.router is not None:
            # every replica's loop + engine, sharing the drain budget
            self.router.close(drain_timeout_s=max(
                0.0, deadline - time.monotonic()))
        else:
            self.loop.close(drain_timeout_s=max(
                0.0, deadline - time.monotonic()))
            self.engine.close()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


def serve_api(engine, *, encode=None, decode=None,
              model_name: str = "solvingpapers") -> ApiServer:
    """Start the front door for `engine` (reads its ServeConfig api_*
    knobs); returns the running server — call `.close()` to shut the
    whole stack down in order."""
    return ApiServer(engine, encode=encode, decode=decode,
                     model_name=model_name)
