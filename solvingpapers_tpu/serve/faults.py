"""Fault tolerance for the serving engine: seeded fault injection, a
failure taxonomy, and the SLO/ledger-driven degradation ladder.

Every defensive line in the serving stack used to be host-side INPUT
validation — once a request was admitted, a NaN-poisoned forward, a
device OOM mid-step, or a hung compiled program killed the engine-loop
thread and with it every concurrent stream. This module is the missing
correctness-under-failure layer, in three pieces the engine composes:

* `FaultPlan` — a deterministic, seeded fault-injection plane
  (`ServeConfig.fault_plan`; None = off, one `is not None` branch per
  hook, the flight recorder's discipline). Named SITES are threaded
  through the hot path — ``prefill`` (admission dispatch), ``decode``
  (the decode/spec block dispatch), ``scatter`` (the post-block output
  fetch / paged scatter boundary), ``prefix_splice`` (prefix-cache
  reuse), ``sse_write`` (the HTTP front door's event writer),
  ``journal_write`` (the write-ahead journal's append/fsync boundary,
  serve/journal.py) — and each
  visit of a site advances a per-site counter; a `FaultSpec` fires at an
  exact visit index, so a fault schedule replays bit-identically
  run-to-run. KINDS: ``nan``/``inf`` poison one slot's logits inside
  the compiled program (via the fault row riding the packed control
  transfer — exercising the traced finite-logits guard), ``xla_error``/
  ``oom`` raise a synthetic `InjectedFault` the failure classifier
  treats exactly like a real `XlaRuntimeError` / RESOURCE_EXHAUSTED,
  ``stall`` sleeps the step past the watchdog deadline,
  ``socket_reset`` breaks an SSE write mid-stream, and ``io_error``
  fails a journal write (exercising the degrade-to-journal-off path —
  or, under `journal_strict`, the loud failure). Every recovery path
  below is therefore testable on CPU in tier-1.

* `classify_failure` — the failure taxonomy the engine's supervised
  step boundary switches on: ``poisoned`` failures (non-finite logits)
  are pinned to a slot and quarantined (that request finishes
  ``"error"``, its slot/pages/exact lane reclaimed leak-free, every
  other stream continues byte-identically); ``systemic`` failures
  (device runtime errors, OOM, anything escaping a program call) cost
  a bounded pool-rebuild retry with exponential backoff, then flip the
  engine to a draining ``unhealthy`` state that /healthz reports as
  503 until recovery.

* `DegradationLadder` — graceful degradation with hysteresis. Under
  page exhaustion, HBM-projection breach, or SLO error-budget burn the
  engine climbs one rung at a time: shed prefix-cache leaves (rung 1),
  hold speculation (rung 2), load-shed admissions by SLO class — batch
  first (rung 3), then standard (rung 4) — answering 503 with a
  JITTERED Retry-After so retry herds never synchronize. Escalation
  needs `up_steps` consecutive pressured evaluations, de-escalation
  `down_steps` clear ones, so the ladder cannot flap on a noisy
  signal; recovery re-arms in reverse order (admissions first, the
  prefix cache last). Each rung is a gauge
  (``serve/degradation_rung``), each transition a trace instant.
"""

from __future__ import annotations

import dataclasses
import threading

__all__ = [
    "FAULT_SITES",
    "FAULT_KINDS",
    "RUNGS",
    "InjectedFault",
    "FaultSpec",
    "FaultPlan",
    "DegradationLadder",
    "classify_failure",
]

FAULT_SITES = ("prefill", "decode", "scatter", "prefix_splice",
               "sse_write", "journal_write")
FAULT_KINDS = ("nan", "inf", "xla_error", "oom", "stall", "socket_reset",
               "io_error")

# fault-row codes the compiled programs decode (0 = clean slot); the
# poison is applied with jnp.where, so an all-zero row is bitwise a
# no-op and fault-free streams stay token-exact
FAULT_NONE = 0
FAULT_NAN = 1
FAULT_INF = 2

# substrings that mark a runtime failure as systemic even when it is a
# real exception rather than an InjectedFault: XLA's runtime error type
# and the canonical OOM status it carries
_SYSTEMIC_MARKERS = ("XlaRuntimeError", "RESOURCE_EXHAUSTED",
                     "Resource exhausted", "out of memory")


class InjectedFault(RuntimeError):
    """Synthetic device-runtime failure raised by a `FaultPlan` — shaped
    so `classify_failure` cannot tell it from the real thing (that is
    the point: the recovery path under test is the production one)."""

    def __init__(self, kind: str, site: str):
        if kind == "oom":
            tag = "RESOURCE_EXHAUSTED: injected device OOM"
        elif kind == "io_error":
            tag = "injected journal I/O error"
        else:
            tag = "injected XlaRuntimeError"
        super().__init__(f"{tag} at site {site!r}")
        self.kind = kind
        self.site = site


def classify_failure(exc: BaseException) -> str:
    """The taxonomy the supervised step boundary switches on:
    ``"systemic"`` for device-runtime failures (injected or real XLA
    runtime errors / OOM — the pool may hold donated garbage, so the
    remedy is rebuild-and-recompute), ``"io"`` for host I/O failures
    (OSError, the journal's JournalError, or an injected ``io_error``
    — the DEVICE pool is untouched, so the remedy is degrade-the-
    durability-plane, not rebuild; the engine's journal boundary
    handles these before they ever reach the step boundary unless
    `journal_strict` deliberately lets them escape), ``"host"`` for
    everything else (a host-side bug; the pool was never touched, but
    the step's outcome is unknown — treated with the same rebuild
    remedy, the conservative choice)."""
    if isinstance(exc, InjectedFault):
        return "io" if exc.kind == "io_error" else "systemic"
    name = type(exc).__name__
    if isinstance(exc, OSError) or "JournalError" in name:
        return "io"
    text = f"{name}: {exc}"
    if any(m in text for m in _SYSTEMIC_MARKERS):
        return "systemic"
    return "host"


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault: fire `count` times starting at the `visit`-th
    poke of `site` (per-site visit counters start at 0 and advance on
    every poke, fired or not — which is what makes a schedule replay
    deterministically). `slot` targets nan/inf poison; `stall_s` is the
    sleep for ``stall``."""

    site: str
    kind: str
    visit: int
    slot: int = 0
    stall_s: float = 0.0
    count: int = 1

    def __post_init__(self):
        if self.site not in FAULT_SITES:
            raise ValueError(
                f"unknown fault site {self.site!r} (sites: {FAULT_SITES})"
            )
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (kinds: {FAULT_KINDS})"
            )
        if self.visit < 0:
            raise ValueError(f"visit must be >= 0, got {self.visit}")
        if self.count < 1:
            raise ValueError(f"count must be >= 1, got {self.count}")
        if self.kind == "stall" and not self.stall_s > 0:
            raise ValueError("stall faults need stall_s > 0")
        if self.kind == "socket_reset" and self.site != "sse_write":
            raise ValueError(
                "socket_reset only makes sense at the sse_write site"
            )
        if self.kind in ("xla_error", "oom") and self.site in (
            "sse_write", "journal_write"
        ):
            raise ValueError(
                f"{self.kind} is a device-runtime failure and needs an "
                "engine site (the sse_write/journal_write hooks only act "
                "on their own kinds — the spec would fire and count as "
                "injected while exercising nothing)"
            )
        if self.kind == "io_error" and self.site != "journal_write":
            raise ValueError(
                "io_error models a journal write/fsync failure and only "
                "makes sense at the journal_write site"
            )
        if self.kind in ("nan", "inf") and self.site not in (
            "prefill", "decode"
        ):
            raise ValueError(
                f"{self.kind} poison lands in program logits and needs "
                "site 'prefill' or 'decode'"
            )
        if self.slot < 0:
            raise ValueError(f"slot must be >= 0, got {self.slot}")


class FaultPlan:
    """A deterministic fault schedule over the engine's named sites.

    Construct from a sequence of `FaultSpec` (or spec-shaped dicts —
    the `ServeConfig.fault_plan` spelling). `poke(site)` is the hot-path
    hook: it advances the site's visit counter and returns the specs
    firing at THIS visit (usually none — the common case is one dict
    lookup + one increment). The plan is pure host-side state: two
    engines built from the same plan replay the same schedule.

    Thread-safe by construction: engine sites poke under the engine
    loop's lock while the front door's ``sse_write`` site pokes from
    HTTP handler threads, so `poke` serializes internally — per-site
    visit counters and the shared `fired` tally cannot lose updates
    across those lock domains.
    """

    def __init__(self, specs):
        parsed = []
        for s in specs:
            if isinstance(s, FaultSpec):
                parsed.append(s)
            elif isinstance(s, dict):
                parsed.append(FaultSpec(**s))
            else:
                raise ValueError(
                    f"fault_plan entries must be FaultSpec or dicts, got "
                    f"{type(s).__name__}"
                )
        self.specs = tuple(parsed)
        self._visits = dict.fromkeys(FAULT_SITES, 0)
        # site -> visit -> [specs]: O(1) per poke on the hot path
        self._by_site: dict[str, dict[int, list[FaultSpec]]] = {
            site: {} for site in FAULT_SITES
        }
        for spec in self.specs:
            for i in range(spec.count):
                self._by_site[spec.site].setdefault(
                    spec.visit + i, []
                ).append(spec)
        self.fired = 0
        self._lock = threading.Lock()

    @classmethod
    def from_config(cls, plan) -> "FaultPlan | None":
        """`ServeConfig.fault_plan` -> a live plan (None passes through:
        the engine keeps the None-pattern hooks)."""
        if plan is None:
            return None
        if isinstance(plan, FaultPlan):
            # each engine replays the schedule from visit 0: a shared
            # plan object must not leak one engine's counters into the
            # next (bench arms reuse one config)
            return cls(plan.specs)
        return cls(plan)

    def poke(self, site: str) -> list[FaultSpec]:
        """One visit of `site`; returns the specs that fire now."""
        with self._lock:
            visit = self._visits[site]
            self._visits[site] = visit + 1
            fired = self._by_site[site].get(visit)
            if not fired:
                return []
            self.fired += len(fired)
            return fired

    def stats(self) -> dict:
        """The /statusz `health.fault_plan` section."""
        with self._lock:
            return {
                "specs": len(self.specs),
                "fired": self.fired,
                "visits": dict(self._visits),
            }


# --------------------------------------------------------------- ladder


RUNGS = ("normal", "shed_prefix", "hold_spec", "shed_batch",
         "shed_standard")

# SLO classes shed per rung, most-expendable first; interactive traffic
# is never shed by the ladder (at that point the engine is unhealthy,
# not degraded)
_SHED_BY_RUNG = {3: ("batch",), 4: ("batch", "standard")}


class DegradationLadder:
    """Hysteretic escalation controller. `observe(pressured, reasons)`
    runs once per engine step; the return value is the new rung when a
    transition happened (None otherwise), so the engine can stamp a
    trace instant per transition without polling."""

    def __init__(self, up_steps: int = 2, down_steps: int = 16,
                 max_rung: int = len(RUNGS) - 1):
        if up_steps < 1 or down_steps < 1:
            raise ValueError("up_steps and down_steps must be >= 1")
        if not 1 <= max_rung < len(RUNGS):
            raise ValueError(
                f"max_rung must be in [1, {len(RUNGS) - 1}], got {max_rung}"
            )
        self.up_steps = up_steps
        self.down_steps = down_steps
        self.max_rung = max_rung
        self.rung = 0
        self.transitions = 0
        self.last_reasons: tuple = ()
        self._up = 0
        self._down = 0

    def observe(self, pressured: bool, reasons=()) -> int | None:
        """Feed one evaluation of the pressure signals; returns the new
        rung iff this observation caused a transition. Escalation and
        de-escalation both move ONE rung at a time (recovery re-arms in
        reverse order by construction), and both counters reset on any
        transition so a fresh rung gets a fresh hysteresis window."""
        if pressured:
            self.last_reasons = tuple(reasons)
            self._down = 0
            self._up += 1
            if self._up >= self.up_steps and self.rung < self.max_rung:
                self.rung += 1
                self.transitions += 1
                self._up = 0
                return self.rung
        else:
            self._up = 0
            self._down += 1
            if self._down >= self.down_steps and self.rung > 0:
                self.rung -= 1
                self.transitions += 1
                self._down = 0
                return self.rung
        return None

    def shed_classes(self) -> tuple:
        """SLO classes admissions are currently shed for (empty below
        rung 3)."""
        return _SHED_BY_RUNG.get(self.rung, ())

    @property
    def name(self) -> str:
        return RUNGS[self.rung]

    def stats(self) -> dict:
        """The /statusz `health.ladder` section."""
        return {
            "rung": self.rung,
            "name": self.name,
            "transitions": self.transitions,
            "shedding": list(self.shed_classes()),
            "pressure_reasons": list(self.last_reasons),
        }
