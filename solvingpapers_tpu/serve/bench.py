"""Serving benchmark: continuous batching vs sequential one-shot generate.

Synthetic open-loop workload: request arrivals are a Poisson process
(exponential interarrivals, seeded), prompts are slices of the
deterministic synthetic corpus (`data/synthetic.synthetic_text`) encoded
to model token ids. Two arms replay the SAME arrival offsets:

* engine — one `ServeEngine`; the driver submits each request when the
  wall clock passes its arrival offset and keeps calling `step()`.
* sequential — the status quo ante: per-request one-shot
  `infer.generate` (batch 1), each request starting at
  ``max(previous finish, its arrival)``.

Both arms are warmed first (every compiled shape traced before timing)
so the comparison is steady-state serving throughput, not tracing time.
Requests/s = n_requests / (last finish - first arrival).

`run_prefix_bench` is the second workload: N requests over K distinct
shared system prompts (`shared_prefix_requests`), engine vs engine with
the radix prefix cache on vs off — the TTFT win of splicing a cached
prefix instead of re-prefilling it (`cli serve-bench --shared-prefix`).

`run_sampling_bench` is the third: the same Poisson trace decoded twice,
all-greedy vs a per-request temperature/top-p/top-k/min-p mix
(`cli serve-bench --sampling`) — the cost of the fused per-slot sampler's
sort-based masking relative to the sort-free greedy fast path, i.e. the
price of SamplingParams when a batch actually uses them.

`run_paged_bench` (`cli serve-bench --paged`) is the fourth: the paged
KV pool against the lane pool — ABBA-paired Poisson throughput at equal
slots+HBM (the paging tax), a capacity arm at EQUAL HBM with double the
slots (peak concurrency > lane slot count = the decoupling claim), and
an ABBA-paired shared-prefix arm whose zero-copy page-sharing hit TTFT
is proven copy-free by the compile registry (no splice program exists).

`run_spec_bench` (`cli serve-bench --speculative`) is the fifth:
speculative decoding (serve/spec.py) on a briefly-trained model —
ABBA-paired spec-on vs spec-off delivered tokens/sec on the greedy
Poisson trace (with a handle-for-handle token-exactness check), plus a
temperature-2.0 adversarial arm where drafts cannot accept and the
adaptive fallback must hold the overhead inside a 10% budget.

With `trace=True` every workload runs one EXTRA arm — the same arrival
trace with the flight recorder on (`metrics/trace.py`) — and records
`trace_overhead_pct` (tracing-on vs tracing-off req/s) in its detail,
the budget the tracer's "single branch when off / bounded ring when on"
design is held to. `trace_out` exports the traced arm's Chrome
trace-event JSON (load in Perfetto or feed `cli trace-summary`);
`trace_dump` arms the anomaly JSONL dumper.

Every workload also runs a compile-&-memory-observatory PROBE first
(`metrics/xla_obs.py`, on the warm trace, BEFORE the plain warmup — so
the recorded XLA compiles are cold): each BENCH_serve.json entry gains
`compile_time_s`, `compile_programs`, `compile_compilations` and
`peak_hbm_bytes`, making compile-time and memory regressions visible in
the bench trajectory, not just req/s. `obs=True` adds a paired
observatory-on-vs-off arm (`obs_overhead_pct`, same ABBA/mean
methodology and < 2% budget as the tracer), and `status_port` keeps the
probe engine's /healthz /metrics /statusz endpoint live for the rest of
the bench (the CI smoke curls it; `status_hold_s` keeps it up after the
arms finish).
"""

from __future__ import annotations

import dataclasses
import math
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from solvingpapers_tpu import ops
from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine
from solvingpapers_tpu.serve.sampling import SamplingParams

_DECODER_FAMILIES = ("gpt", "llama3", "gemma", "deepseekv3")

# BENCH_serve.json entry schema:
#   0 (implicit) — PR 1-10 entries: {metric, value, unit, vs_baseline,
#     detail} with no identity stamp
#   1 — schema 0 plus a BACKFILLED provenance block (git sha + commit
#     timestamp recovered from history; jax/host unknown, marked
#     "backfilled": true)
#   2 — provenance recorded at measurement time: git sha, timestamp
#     (INJECTED by the entry writer — cli cmd_serve_bench stamps one
#     clock reading per run; nothing in here reads the clock ambiently,
#     so tests pin entries byte-stable), jax/jaxlib versions, host
#     platform + device kind. tools/bench_check.py keys its trajectory
#     on these.
BENCH_SCHEMA_VERSION = 2


def bench_provenance(timestamp: float, git_sha: str | None = None) -> dict:
    """The identity stamp every BENCH_serve.json entry carries (schema
    v2): WHO measured this (git sha, jax/jaxlib, host device) and WHEN.
    `timestamp` is required — injected by the caller, one clock reading
    per bench run — so entries are reproducible under test and two
    workloads written by one run share one timestamp."""
    from solvingpapers_tpu.buildinfo import build_info

    info = build_info()
    return {
        "schema_version": BENCH_SCHEMA_VERSION,
        "provenance": {
            "git_sha": git_sha if git_sha is not None else info["git_sha"],
            "timestamp": round(float(timestamp), 3),
            "jax": info["jax"],
            "jaxlib": info["jaxlib"],
            "python": info["python"],
            "platform": info["platform"],
            "device_kind": info["device_kind"],
        },
    }


def build_serve_model(config_name: str):
    """(model, params, extra_variables, vocab_size) for a registered
    decoder config — the serve-side analogue of `cli.cmd_sample`'s setup,
    minus data/tokenizer plumbing (the bench feeds raw token ids)."""
    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_model

    cfg = get_config(config_name)
    if cfg.model_family not in _DECODER_FAMILIES:
        raise ValueError(
            f"config {config_name!r} is family {cfg.model_family!r}; "
            f"serve-bench needs a decoder family {_DECODER_FAMILIES}"
        )
    if cfg.train.pipeline_parallel:
        raise ValueError(
            "pipeline-parallel configs have stage-stacked params; export "
            "to the dense family before serving"
        )
    if getattr(cfg.model, "context_parallel", False):
        # params are replicated at rest: serve through the dense twin,
        # exactly like cmd_sample's single-chip path
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, context_parallel=False)
        )
    model = build_model(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.key(0)}, toks)
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    return model, params, extra or None, cfg.model.vocab_size


def synthetic_requests(
    n: int,
    vocab_size: int,
    prompt_lens=(8, 16, 24, 32),
    mean_interarrival_s: float = 0.002,
    seed: int = 0,
):
    """[(arrival_offset_s, prompt ids)] — Poisson arrivals, corpus prompts.

    Prompt lengths cycle through a small fixed set so both arms compile a
    bounded number of shapes (the sequential arm retraces `generate` per
    distinct prompt length).
    """
    from solvingpapers_tpu.data.synthetic import synthetic_text

    rng = np.random.default_rng(seed)
    text = synthetic_text(n_chars=max(4096, n * max(prompt_lens) * 2),
                          seed=seed)
    corpus = np.frombuffer(text.encode("ascii", "replace"), np.uint8)
    ids = corpus.astype(np.int32) % vocab_size
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s, size=n))
    out = []
    for i in range(n):
        length = prompt_lens[i % len(prompt_lens)]
        start = int(rng.integers(0, ids.size - length))
        out.append((float(arrivals[i]), ids[start:start + length]))
    return out


def shared_prefix_requests(
    n: int,
    vocab_size: int,
    n_prefixes: int = 4,
    prefix_len: int = 64,
    suffix_len: int = 8,
    mean_interarrival_s: float = 0.002,
    seed: int = 0,
):
    """[(arrival_offset_s, prompt ids)] — N requests over `n_prefixes`
    distinct system prompts: each prompt is one of K shared `prefix_len`
    stems plus a unique `suffix_len` tail. The workload real serving
    traffic looks like (system prompts / few-shot templates), and the one
    the radix prefix cache exists for: after each stem's first request,
    only the tail needs prefill."""
    rng = np.random.default_rng(seed)
    stems = [
        rng.integers(0, vocab_size, size=prefix_len).astype(np.int32)
        for _ in range(n_prefixes)
    ]
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s, size=n))
    out = []
    for i in range(n):
        tail = rng.integers(0, vocab_size, size=suffix_len).astype(np.int32)
        out.append(
            (float(arrivals[i]),
             np.concatenate([stems[i % n_prefixes], tail]))
        )
    return out


def _round_if_present(snap: dict, key: str, out_key: str, digits: int) -> dict:
    """{out_key: rounded value} when the metric was observed, else {}."""
    if key in snap:
        return {out_key: round(snap[key], digits)}
    return {}


def _kv_entry_fields(eng, agreement: float = 1.0) -> dict:
    """The KV-storage triple EVERY BENCH_serve.json entry records so the
    trajectory stays comparable across quantized and exact rounds:
    `kv_dtype` (the pool's storage dtype — "int8" for quantized pools),
    `kv_pool_bytes` (resident pool bytes incl. scale/exact sidecars),
    and `greedy_agreement_rate` (token agreement vs the full-precision
    pool; exact pools report 1.0 by definition — they ARE the
    reference)."""
    pool = eng.pool
    if getattr(pool, "quant", None):
        dtype = pool.quant
    else:
        caches = pool.phys if hasattr(pool, "phys") else pool.caches
        dtype = str(jax.tree_util.tree_leaves(caches)[0].dtype)
    return {
        "kv_dtype": dtype,
        "kv_pool_bytes": int(pool.nbytes),
        "greedy_agreement_rate": round(float(agreement), 4),
    }


def _token_agreement(ref_handles, handles) -> float:
    """Position-wise greedy-token agreement between two arms' request
    handles (same prompts, same order): matching tokens at the same
    stream index over the reference arm's total tokens. After a first
    divergence later positions usually disagree too — that is the
    honest penalty of the metric, not a flaw."""
    total = sum(len(r.tokens) for r in ref_handles)
    if total == 0:
        return 1.0
    same = sum(
        int(a == b)
        for r, h in zip(ref_handles, handles)
        for a, b in zip(r.tokens, h.tokens)
    )
    return same / total


def _paired_makespans(model, params, extra, requests, on_cfg, off_cfg,
                      max_new, params_for=None, reps: int = 4):
    """ABBA-paired makespans for an instrumented-vs-plain engine config.

    The measurement discipline every overhead number in BENCH_serve.json
    shares: even reps run on-then-off, odd reps flip, and each side
    averages its runs. Single back-to-back pairs are dominated by
    scheduler/thermal noise on a shared host (single-run makespans here
    swing +-10% in both directions while the instrumentation's true cost
    is well under 1%), and taking min-of-reps re-biases under monotonic
    load drift (one side owns the last slot); ABBA + mean cancels linear
    drift exactly, and `reps=4` (8 runs) averages the residual noise
    below the 2% acceptance budget. Returns (mk_on, mk_off, last on-arm
    engine). Thin view over `_paired_arm_stats` — ONE implementation of
    the pairing discipline every overhead number depends on."""
    runs, engines = _paired_arm_stats(
        model, params, extra, requests, on_cfg, off_cfg, max_new,
        reps=reps, params_for=params_for,
    )
    return ([mk for mk, _ in runs["on"]],
            [mk for mk, _ in runs["off"]], engines["on"])


def _traced_arm_fields(model, params, extra, requests, serve_cfg, max_new,
                       trace_out: str | None, trace_dump: str | None,
                       params_for=None, reps: int = 4) -> dict:
    """Measure the flight recorder's throughput cost and return the
    detail fields: `trace_overhead_pct` = (1 - traced/untraced req/s) x
    100 — the acceptance budget is < 2 on the Poisson workload — plus
    the traced arm's req/s and event count. Exports the last traced
    run's Chrome trace to `trace_out`; `trace_dump` arms the anomaly
    dumper. Methodology: `_paired_makespans`."""
    tcfg = dataclasses.replace(
        serve_cfg, trace=True, trace_dump_path=trace_dump
    )
    mk_on, mk_off, eng = _paired_makespans(
        model, params, extra, requests, tcfg, serve_cfg, max_new,
        params_for=params_for, reps=reps,
    )
    traced_rps = len(requests) / (sum(mk_on) / len(mk_on))
    untraced_rps = len(requests) / (sum(mk_off) / len(mk_off))
    fields = {
        "trace_overhead_pct": round(
            (1.0 - traced_rps / untraced_rps) * 100.0, 2
        ),
        "traced_requests_per_sec": round(traced_rps, 2),
        "trace_events": eng.trace.total_recorded,
    }
    if trace_out:
        eng.trace.export_chrome(trace_out)
        fields["trace_out"] = trace_out
    return fields


def _obs_arm_fields(model, params, extra, requests, serve_cfg, max_new,
                    params_for=None, reps: int = 4,
                    prefix: str = "obs") -> dict:
    """Compile-&-memory-observatory on vs off, same ABBA/mean pairing as
    the tracer — `<prefix>_overhead_pct` is the budget the registry's
    fenced AOT dispatch is held to (< 2, matching the flight
    recorder's). ONE pairing implementation behind two field names:
    `obs` (the PR-5 budget) and `anatomy` (the paged/kv-quant entries'
    armed-anatomy budget — since the per-op HLO parse rides `xla_obs`
    unconditionally, the armed configuration is identical; the distinct
    name records WHICH surface the entry pinned its budget with)."""
    ocfg = dataclasses.replace(serve_cfg, xla_obs=True)
    mk_on, mk_off, _ = _paired_makespans(
        model, params, extra, requests, ocfg, serve_cfg, max_new,
        params_for=params_for, reps=reps,
    )
    on_rps = len(requests) / (sum(mk_on) / len(mk_on))
    off_rps = len(requests) / (sum(mk_off) / len(mk_off))
    return {
        f"{prefix}_overhead_pct": round(
            (1.0 - on_rps / off_rps) * 100.0, 2
        ),
        f"{prefix}_requests_per_sec": round(on_rps, 2),
    }


def _decode_step_wall_s(registry) -> float | None:
    """Fenced per-call wall of the steady-state decode program from a
    live CompileRegistry, or None before any decode ran — the measured
    denominator `paged_decode_decomposition` attributes against."""
    snap = registry.snapshot()
    d = snap["programs"].get("decode_block")
    if not d or not d["calls"] or d["run_time_s"] <= 0:
        return None
    return d["run_time_s"] / d["calls"]


def _obs_probe(model, params, extra, warm_requests, serve_cfg, max_new,
               status_port: int | None = None, params_for=None,
               obs_hlo_dir: str | None = None):
    """Run the warm trace through an observatory-enabled engine FIRST
    (before the plain warmup populates jax's jit cache) so the recorded
    `compile_time_s` is true cold-compile wall time, and read the
    HBM-ledger projected peak off the live engine. Returns (detail
    fields, engine). With `status_port` set the engine is returned OPEN
    so its /healthz /metrics /statusz endpoint stays up for the rest of
    the bench (the CI smoke curls it while the timed arms run; the
    caller closes it on exit); WITHOUT one the engine is dropped here
    (returns None) so its slot pool and prefix segments free before the
    timed arms allocate theirs — the probe must not double the device
    memory it exists to measure."""
    import sys

    ocfg = dataclasses.replace(serve_cfg, xla_obs=True,
                               obs_hlo_dir=obs_hlo_dir)
    if status_port is not None:
        ocfg = dataclasses.replace(ocfg, status_port=status_port)
    eng, _, _ = _run_engine_arm(
        model, params, extra, warm_requests, ocfg, max_new,
        params_for=params_for,
    )
    snap = eng.registry.snapshot()
    fields = {
        # compile + memory trajectory gauges: regressions here (a new
        # shape that stops bucketing, a cache that balloons) show up in
        # BENCH_serve.json even when req/s alone still looks fine
        "compile_time_s": round(eng.registry.total_compile_s, 4),
        "compile_programs": len(snap["programs"]),
        "compile_compilations": sum(
            d["compilations"] for d in snap["programs"].values()
        ),
        "peak_hbm_bytes": int(eng.ledger.projected_peak_bytes()),
    }
    # the fenced decode-program per-call wall: the denominator the
    # paged/kv-quant entries decompose into gather/dequant/scatter/
    # attention shares (serve/kernel_bench.py)
    step_wall = _decode_step_wall_s(eng.registry)
    if step_wall is not None:
        fields["decode_step_wall_s"] = round(step_wall, 6)
    if eng.status is not None:
        fields["status_port"] = eng.status.port
        print(f"[serve-bench] status endpoint live at "
              f"http://127.0.0.1:{eng.status.port} "
              "(/healthz /metrics /statusz)", file=sys.stderr)
        return fields, eng
    return fields, None


def _run_engine_arm(model, params, extra, requests, serve_cfg, max_new,
                    params_for=None):
    """`params_for` (index -> SamplingParams | None) attaches per-request
    sampling params; None keeps every request greedy (the default)."""
    eng = ServeEngine(model, params, serve_cfg, extra_variables=extra)
    pending = sorted(requests, key=lambda r: r[0])
    handles = []
    t0 = time.monotonic()
    i = 0
    while i < len(pending) or eng.has_work():
        elapsed = time.monotonic() - t0
        while i < len(pending) and pending[i][0] <= elapsed:
            handles.append(eng.submit(
                pending[i][1], max_new_tokens=max_new,
                params=params_for(i) if params_for is not None else None,
            ))
            i += 1
        if eng.has_work():
            eng.step()
        elif i < len(pending):
            # engine idle before the next arrival: busy-wait is pointless
            # on a bench box, sleep the remaining gap
            time.sleep(max(0.0, pending[i][0] - (time.monotonic() - t0)))
    makespan = (time.monotonic() - t0) - pending[0][0]
    assert all(h.done for h in handles), "engine drained with unfinished work"
    return eng, handles, makespan


def _run_sequential_arm(model, params, extra, requests, max_new):
    """Per-request one-shot generate at the same arrival offsets."""
    from solvingpapers_tpu.infer import generate

    rng = jax.random.key(0)
    ttfts = []
    cursor = None
    for arrival, prompt in sorted(requests, key=lambda r: r[0]):
        start = arrival if cursor is None else max(cursor, arrival)
        t0 = time.monotonic()
        out = generate(
            model, params, jnp.asarray(prompt)[None, :], rng,
            max_new_tokens=max_new, sampler=ops.sample_greedy,
            extra_variables=extra,
        )
        jax.block_until_ready(out)
        dur = time.monotonic() - t0
        cursor = start + dur
        # one-shot generate emits nothing until the whole batch finishes:
        # first-token latency == completion latency
        ttfts.append(cursor - arrival)
    makespan = cursor - min(a for a, _ in requests)
    return makespan, float(np.mean(ttfts))


def run_serve_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    seed: int = 0,
    skip_sequential: bool = False,
    trace: bool = False,
    trace_out: str | None = None,
    trace_dump: str | None = None,
    obs: bool = False,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
    obs_hlo_dir: str | None = None,
) -> dict:
    """Run both arms, return the BENCH-shaped result dict."""
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    serve_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_prompt + max_new,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        # throughput-oriented: refill the whole pool in one iteration
        # (the default 1-prefill/step decode-priority protects ITL, but
        # under a drain-the-queue workload it leaves slots idle)
        max_prefills_per_step=n_slots,
        # open-loop arrivals can queue every request at once; the bench
        # must never shed load or the drained-handles assert trips
        max_waiting=max(256, n_requests),
        seed=seed,
    )

    # warm both arms: trace every compiled shape outside the timed window
    # (one request per distinct prompt length covers every prefill bucket
    # and every sequential-arm generate trace; decode is one shape)
    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    # observatory probe first (cold AOT compiles => honest compile_time_s
    # and per-entry peak-HBM gauges); its engine keeps the live status
    # endpoint up for the rest of the bench when --status-port is set
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, serve_cfg, max_new,
        status_port=status_port, obs_hlo_dir=obs_hlo_dir,
    )
    try:
        _run_engine_arm(model, params, extra, warm, serve_cfg, max_new)
        if not skip_sequential:
            _run_sequential_arm(model, params, extra, warm, max_new)

        eng, handles, makespan = _run_engine_arm(
            model, params, extra, requests, serve_cfg, max_new
        )
        snap = eng.metrics.snapshot()
        rps = n_requests / makespan
        detail = {
            "config": config,
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "engine_requests_per_sec": round(rps, 2),
            "engine_tokens_per_sec": round(
                snap.get("serve/tokens_per_sec", 0.0), 1
            ),
            # absent beats NaN (json.dumps would emit a non-RFC-8259 'NaN'
            # token): e.g. max_new=1 finishes every request at prefill and
            # the ITL ring never gets an observation
            **_round_if_present(snap, "serve/ttft_s_mean", "mean_ttft_s", 4),
            **_round_if_present(snap, "serve/ttft_s_p95", "ttft_p95_s", 4),
            **_round_if_present(snap, "serve/itl_s_p95", "itl_p95_s", 5),
            "slot_occupancy": round(snap.get("serve/slot_occupancy", 0.0), 3),
            # present only when the engine's prefix cache actually ran
            # lookups (snapshot omits serve/prefix_* otherwise) — an
            # unconditional 0.0 would be indistinguishable from "cache
            # on, nothing shared"
            **_round_if_present(snap, "serve/prefix_hit_rate",
                                "prefix_hit_rate", 3),
            **({"tokens_prefilled_saved":
                int(snap["serve/tokens_prefilled_saved"])}
               if "serve/tokens_prefilled_saved" in snap else {}),
            **_kv_entry_fields(eng),
            **probe_fields,
        }
        if obs:
            detail.update(_obs_arm_fields(
                model, params, extra, requests, serve_cfg, max_new,
            ))
        if trace:
            detail.update(_traced_arm_fields(
                model, params, extra, requests, serve_cfg, max_new,
                trace_out, trace_dump,
            ))
        result = {
            "metric": "serve_requests_per_sec",
            "value": round(rps, 2),
            "unit": "req/s",
            "detail": detail,
        }
        if not skip_sequential:
            seq_makespan, seq_ttft = _run_sequential_arm(
                model, params, extra, requests, max_new
            )
            seq_rps = n_requests / seq_makespan
            detail["sequential_requests_per_sec"] = round(seq_rps, 2)
            detail["sequential_mean_ttft_s"] = round(seq_ttft, 4)
            result["vs_baseline"] = round(rps / seq_rps, 2)
        if probe_eng is not None and status_hold_s > 0:
            time.sleep(status_hold_s)
        return result
    finally:
        if probe_eng is not None:
            probe_eng.close()


def run_prefix_bench(
    config: str = "gpt_shakespeare",
    n_requests: int = 48,
    n_slots: int = 8,
    max_new: int = 4,
    decode_block: int = 4,
    n_prefixes: int = 4,
    prefix_len: int | None = None,
    suffix_len: int = 8,
    mean_interarrival_s: float = 0.002,
    prefix_page: int = 16,
    prefix_cache_bytes: int = 64 << 20,
    seed: int = 0,
    trace: bool = False,
    trace_out: str | None = None,
    trace_dump: str | None = None,
    obs: bool = False,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """Shared-prefix workload, prefix cache ON vs OFF — same engine, same
    arrival trace; returns the BENCH-shaped dict with the TTFT speedup as
    the headline (`vs_baseline` = cache-off mean TTFT / cache-on).

    `prefix_len=None` stretches the shared stem to the model's position
    budget (page-aligned), the regime the cache exists for — a long system
    prompt ahead of a short per-request tail."""
    model, params, extra, vocab = build_serve_model(config)
    limit = getattr(model, "max_positions", None)
    if prefix_len is None:
        room = (limit or 256) - suffix_len - max_new
        prefix_len = max(prefix_page, room // prefix_page * prefix_page)
    requests = shared_prefix_requests(
        n_requests, vocab, n_prefixes=n_prefixes, prefix_len=prefix_len,
        suffix_len=suffix_len, mean_interarrival_s=mean_interarrival_s,
        seed=seed,
    )
    max_prompt = prefix_len + suffix_len
    if limit is not None and max_prompt + max_new > limit:
        raise ValueError(
            f"prefix_len + suffix_len + max_new = {max_prompt + max_new} "
            f"exceeds the model's max positions {limit}"
        )

    def cfg(cache_on: bool) -> ServeConfig:
        return ServeConfig(
            n_slots=n_slots,
            max_len=max_prompt + max_new,
            decode_block=decode_block,
            # tight bucket: a hit prefills ~suffix_len tokens, not a
            # 32-padded program — the whole point of the workload
            bucket=max(8, -(-suffix_len // 8) * 8),
            max_prefills_per_step=n_slots,
            max_waiting=max(256, n_requests),
            seed=seed,
            prefix_cache=cache_on,
            prefix_page=prefix_page,
            prefix_cache_bytes=prefix_cache_bytes,
        )

    # observatory probe on the cache-on config (the headline arm): cold
    # AOT compile times + the ledger's peak including the radix tree
    probe_warm = shared_prefix_requests(
        2 * n_prefixes, vocab, n_prefixes=n_prefixes,
        prefix_len=prefix_len, suffix_len=suffix_len,
        mean_interarrival_s=0.0, seed=seed + 1,
    )
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, probe_warm, cfg(True), max_new,
        status_port=status_port,
    )
    arms = {}
    raw_ttft = {}
    on_eng = None
    try:
        for cache_on in (True, False):
            # warm: a 2-requests-per-stem mini-trace compiles every shape
            # both arms hit (miss-path full prefill AND hit-path suffix
            # prefill — the jit cache is process-global, the prefix tree
            # is per-engine so the TIMED engine still starts cold)
            warm = shared_prefix_requests(
                2 * n_prefixes, vocab, n_prefixes=n_prefixes,
                prefix_len=prefix_len, suffix_len=suffix_len,
                mean_interarrival_s=0.0, seed=seed + 1,
            )
            _run_engine_arm(model, params, extra, warm, cfg(cache_on),
                            max_new)
            eng, _, makespan = _run_engine_arm(
                model, params, extra, requests, cfg(cache_on), max_new
            )
            if cache_on:
                on_eng = eng
            snap = eng.metrics.snapshot()
            arm = "cache_on" if cache_on else "cache_off"
            raw_ttft[arm] = snap["serve/ttft_s_mean"]  # unrounded ratio
            arms[arm] = {
                "requests_per_sec": round(n_requests / makespan, 2),
                "mean_ttft_s": round(raw_ttft[arm], 4),
                **_round_if_present(snap, "serve/ttft_s_p95",
                                    "ttft_p95_s", 4),
                "prefix_hit_rate": round(
                    snap.get("serve/prefix_hit_rate", 0.0), 3
                ),
                "prefix_evictions": int(
                    snap.get("serve/prefix_evictions", 0.0)
                ),
                "tokens_prefilled_saved": int(
                    snap.get("serve/tokens_prefilled_saved", 0.0)
                ),
                "prefix_hbm_bytes": int(
                    snap.get("serve/prefix_hbm_bytes", 0.0)
                ),
            }
        trace_fields = {}
        if obs:
            trace_fields.update(_obs_arm_fields(
                model, params, extra, requests, cfg(True), max_new,
            ))
        if trace:
            # the traced arm mirrors the headline (cache-on) arm: splice +
            # snapshot + lookup events are the ones this workload exercises
            trace_fields.update(_traced_arm_fields(
                model, params, extra, requests, cfg(True), max_new,
                trace_out, trace_dump,
            ))
        if probe_eng is not None and status_hold_s > 0:
            time.sleep(status_hold_s)
    finally:
        if probe_eng is not None:
            probe_eng.close()
    # ratio of the UNROUNDED means: 4-decimal-rounded values would distort
    # (or zero-divide) on hardware where TTFT is tens of microseconds
    speedup = raw_ttft["cache_off"] / raw_ttft["cache_on"]
    return {
        "metric": "serve_prefix_cache_ttft_speedup",
        "value": round(speedup, 2),
        "unit": "x (mean TTFT, cache off / on)",
        "vs_baseline": round(speedup, 2),
        "detail": {
            "config": config,
            "workload": "shared-prefix",
            "n_requests": n_requests,
            "n_prefixes": n_prefixes,
            "prefix_len": prefix_len,
            "suffix_len": suffix_len,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "mean_interarrival_s": mean_interarrival_s,
            "prefix_page": prefix_page,
            **{f"{arm}_{k}": v for arm, d in arms.items()
               for k, v in d.items()},
            **_kv_entry_fields(on_eng),
            **probe_fields,
            **trace_fields,
        },
    }


def _paired_arm_stats(model, params, extra, requests, on_cfg, off_cfg,
                      max_new, reps: int = 2, params_for=None):
    """ABBA-paired runs keeping each side's last engine + per-run
    (makespan, metrics snapshot). THE single implementation of the
    pairing discipline (`_paired_makespans` is a thin view over it) —
    see that docstring for why ABBA + mean is the shape every overhead
    number in BENCH_serve.json uses."""
    runs = {"on": [], "off": []}
    engines = {"on": None, "off": None}
    for rep in range(reps):
        order = ("on", "off") if rep % 2 == 0 else ("off", "on")
        for arm in order:
            eng, _, mk = _run_engine_arm(
                model, params, extra, requests,
                on_cfg if arm == "on" else off_cfg, max_new,
                params_for=params_for,
            )
            runs[arm].append((mk, eng.metrics.snapshot()))
            engines[arm] = eng
    return runs, engines


def _peak_concurrency(handles) -> int:
    """Max simultaneously-active slots, reconstructed from the
    requests' own [admit, finish) intervals — no per-step polling in
    the timed loop."""
    events = []
    for h in handles:
        if h.admit_time is not None and h.finish_time is not None:
            events.append((h.admit_time, 1))
            events.append((h.finish_time, -1))
    events.sort()
    cur = peak = 0
    for _, d in events:
        cur += d
        peak = max(peak, cur)
    return peak


def run_paged_bench(
    config: str = "gpt_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    n_prefixes: int = 4,
    prefix_requests: int | None = None,
    suffix_len: int = 8,
    page_size: int = 16,
    seed: int = 0,
    reps: int = 2,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """Paged KV pool vs the lane pool — three sub-workloads, one entry.

    1. Poisson (ABBA-paired, same slots, same HBM): the paging tax —
       gather/scatter page translation vs contiguous lanes
       (`paged_overhead_pct` on req/s).
    2. Capacity at EQUAL HBM: a paged engine with 2x the slots but a
       page budget equal to the lane pool's byte footprint, on a
       shorter-stream workload; `capacity_peak_active` > `n_slots`
       demonstrates slot count decoupled from max_seq (the HBM-ledger
       bytes for both pools are in the entry).
    3. Shared-prefix (ABBA-paired, paged cache-on vs cache-off): the
       prefix-hit TTFT win with ZERO-COPY page sharing — the observatory
       probe proves no splice/extract program is ever dispatched
       (`splice_programs_dispatched` stays 0).
    """
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    # page tables need whole pages per lane: round max_len up
    max_len = -(-(max_prompt + max_new) // page_size) * page_size
    limit = getattr(model, "max_positions", None)
    if limit is not None and max_len > limit:
        max_len = limit // page_size * page_size
    base = dict(
        n_slots=n_slots, max_len=max_len, decode_block=decode_block,
        bucket=min(32, max_prompt), max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests), seed=seed,
    )
    lane_cfg = ServeConfig(**base)
    paged_cfg = ServeConfig(**base, paged=True, page_size=page_size)

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    # observatory probe on the paged arm: cold compile times + the
    # ledger's projected peak with the page pool booked
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, paged_cfg, max_new,
        status_port=status_port,
    )
    try:
        # ---- 1. Poisson: paged vs lane at the same slots + HBM -------
        _run_engine_arm(model, params, extra, warm, lane_cfg, max_new)
        runs, engines = _paired_arm_stats(
            model, params, extra, requests, paged_cfg, lane_cfg, max_new,
            reps=reps,
        )
        paged_rps = len(requests) / (
            sum(mk for mk, _ in runs["on"]) / len(runs["on"]))
        lane_rps = len(requests) / (
            sum(mk for mk, _ in runs["off"]) / len(runs["off"]))
        detail = {
            "config": config,
            "workload": "paged-vs-lane",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "page_size": page_size,
            "max_len": max_len,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "paged_requests_per_sec": round(paged_rps, 2),
            "lane_requests_per_sec": round(lane_rps, 2),
            "paged_overhead_pct": round(
                (1.0 - paged_rps / lane_rps) * 100.0, 2
            ),
            "paged_kv_pool_bytes": int(engines["on"].pool.nbytes),
            "lane_kv_pool_bytes": int(engines["off"].pool.nbytes),
            **_kv_entry_fields(engines["on"]),
            **probe_fields,
        }

        # ---- 1b. decompose the paged decode tax ----------------------
        # microbenched gather/scatter walls at THIS entry's shapes
        # against the probe's fenced decode-program wall: the measured
        # per-component baseline ROADMAP item 1's fused kernel is
        # diffed against (serve/kernel_bench.py)
        if "decode_step_wall_s" in probe_fields:
            from solvingpapers_tpu.serve.kernel_bench import (
                paged_decode_decomposition,
            )

            detail.update(paged_decode_decomposition(
                model, n_slots=n_slots, max_len=max_len,
                page_size=page_size, decode_block=decode_block,
                step_wall_s=probe_fields["decode_step_wall_s"],
                kv_quant=None, reps=3, seed=seed,
            ))
        # armed-anatomy overhead, ABBA-paired like every other
        # instrumentation budget (<= 2%)
        detail.update(_obs_arm_fields(
            model, params, extra, requests, paged_cfg, max_new, reps=reps,
            prefix="anatomy",
        ))

        # ---- 2. capacity at equal HBM: 2x slots, lane-pool bytes -----
        cap_new = max(8, max_new // 4)  # shorter streams: the mixed-
        # length regime where per-page booking beats whole-lane booking
        cap_budget = n_slots * (max_len // page_size)
        cap_cfg = ServeConfig(**{**base, "n_slots": 2 * n_slots},
                              paged=True, page_size=page_size,
                              page_budget=cap_budget)
        # observatory pass first: the "equal HBM" claim is about
        # RESIDENT pool bytes; the paged decode's gather materializes a
        # (2S, max_len, ...) lane view as PROGRAM TEMP, which must be
        # reported alongside it, not hidden (on a capacity-squeezed
        # device temp is the difference between fitting and OOM)
        cap_obs = dataclasses.replace(cap_cfg, xla_obs=True)
        obs_cap_eng, _, _ = _run_engine_arm(
            model, params, extra, warm, cap_obs, cap_new,
        )
        cap_temp = int(obs_cap_eng.registry.max_temp_bytes())
        _run_engine_arm(model, params, extra, warm, cap_cfg, cap_new)
        cap_eng, cap_handles, cap_mk = _run_engine_arm(
            model, params, extra, requests, cap_cfg, cap_new,
        )
        cap_snap = cap_eng.metrics.snapshot()
        detail.update({
            "capacity_n_slots": 2 * n_slots,
            "capacity_page_budget": cap_budget,
            "capacity_max_new_tokens": cap_new,
            "capacity_peak_active_slots": _peak_concurrency(cap_handles),
            "capacity_kv_pool_bytes": int(cap_eng.pool.nbytes),
            "capacity_program_temp_bytes": cap_temp,
            "capacity_requests_per_sec": round(n_requests / cap_mk, 2),
            "capacity_preemptions": int(
                cap_snap.get("serve/preemptions", 0.0)
            ),
        })

        # ---- 3. shared-prefix: zero-copy hit TTFT -------------------
        # run_prefix_bench's regime, where the TTFT story lives: long
        # stems, tiny generation budget — a hit skips the stem's
        # prefill, so prefill must dominate the request (the Poisson
        # arm's 64-token decode would bury it under queue wait)
        pmax_new = min(max_new, 4)
        pblock = min(decode_block, 4)
        # stretch the stem to the model's position budget (the regime
        # the prefix cache exists for — a long system prompt ahead of a
        # short tail), independent of the Poisson arm's tighter max_len
        pmax_len = (limit or 256) // page_size * page_size
        plen = max(page_size,
                   ((pmax_len - suffix_len - pmax_new) // page_size)
                   * page_size)
        # run_prefix_bench's measurement regime (48 requests, 2 ms mean
        # gap, the long-stem config): a tighter flood makes mean TTFT
        # queue-wait-dominated and the speedup estimate noisy run-to-run
        pn = 48 if prefix_requests is None else prefix_requests
        preqs = shared_prefix_requests(
            pn, vocab, n_prefixes=n_prefixes, prefix_len=plen,
            suffix_len=suffix_len, mean_interarrival_s=0.002,
            seed=seed,
        )
        pbase = dict(base, max_len=pmax_len,
                     bucket=max(8, -(-suffix_len // 8) * 8),
                     decode_block=pblock)
        pcfg_on = ServeConfig(**pbase, paged=True, page_size=page_size,
                              prefix_cache=True, prefix_page=page_size)
        pcfg_off = ServeConfig(**pbase, paged=True, page_size=page_size)
        lane_on = ServeConfig(**pbase, prefix_cache=True,
                              prefix_page=page_size)
        pwarm = shared_prefix_requests(
            2 * n_prefixes, vocab, n_prefixes=n_prefixes, prefix_len=plen,
            suffix_len=suffix_len, mean_interarrival_s=0.0, seed=seed + 1,
        )
        _run_engine_arm(model, params, extra, pwarm, pcfg_on, pmax_new)
        _run_engine_arm(model, params, extra, pwarm, pcfg_off, pmax_new)
        _run_engine_arm(model, params, extra, pwarm, lane_on, pmax_new)
        # pair A: paged cache-on vs cache-off — the hit's TTFT win
        # (one extra rep over the throughput pairs: TTFT means are
        # noisier than makespans on the shared box)
        pruns, _ = _paired_arm_stats(
            model, params, extra, preqs, pcfg_on, pcfg_off, pmax_new,
            reps=reps + 1,
        )
        # pair B: paged cache-on vs LANE cache-on — zero-copy page
        # append vs the splice program's device copy, hit-for-hit
        lruns, _ = _paired_arm_stats(
            model, params, extra, preqs, pcfg_on, lane_on, pmax_new,
            reps=reps,
        )
        ttft_on = float(np.mean(
            [s["serve/ttft_s_mean"] for _, s in pruns["on"]]))
        ttft_off = float(np.mean(
            [s["serve/ttft_s_mean"] for _, s in pruns["off"]]))
        ttft_lane = float(np.mean(
            [s["serve/ttft_s_mean"] for _, s in lruns["off"]]))
        on_snap = pruns["on"][-1][1]
        # the zero-copy proof: run the cache-on arm once more under the
        # observatory and assert no splice/extract program ever compiled
        obs_on = dataclasses.replace(pcfg_on, xla_obs=True)
        obs_eng, _, _ = _run_engine_arm(
            model, params, extra, pwarm, obs_on, pmax_new,
        )
        splices = sum(
            1 for name in obs_eng.registry.snapshot()["programs"]
            if name in ("splice_program", "extract_program")
        )
        detail.update({
            "prefix_len": plen,
            "suffix_len": suffix_len,
            "n_prefixes": n_prefixes,
            "prefix_n_requests": pn,
            "paged_prefix_mean_ttft_s": round(ttft_on, 4),
            "paged_noprefix_mean_ttft_s": round(ttft_off, 4),
            "lane_prefix_mean_ttft_s": round(ttft_lane, 4),
            "paged_prefix_ttft_speedup": round(ttft_off / ttft_on, 2),
            "paged_vs_lane_prefix_ttft": round(ttft_lane / ttft_on, 2),
            "paged_prefix_hit_rate": round(
                on_snap.get("serve/prefix_hit_rate", 0.0), 3
            ),
            "splice_programs_dispatched": splices,
        })
        if probe_eng is not None and status_hold_s > 0:
            time.sleep(status_hold_s)
    finally:
        if probe_eng is not None:
            probe_eng.close()
    return {
        "metric": "serve_paged_slots_at_equal_hbm",
        "value": detail["capacity_peak_active_slots"],
        "unit": "concurrent slots (lane-pool HBM budget)",
        "vs_baseline": round(
            detail["capacity_peak_active_slots"] / n_slots, 2
        ),
        "detail": detail,
    }


def _train_bench_model(model, corpus_ids, steps: int, seed: int = 0):
    """Briefly fit the bench model on the synthetic corpus (default LM
    loss) and return host params. Speculative decoding's speedup is
    conditional on DRAFT QUALITY: a random-init model's greedy stream is
    noise its own history cannot predict, so benchmarking speculation on
    one would measure the all-reject fallback, not the mechanism. A few
    hundred steps on the tiny bench model (~10 s) give the honest
    regime — a model that actually models its corpus, whose
    continuations reuse n-grams the prompt-lookup drafter finds."""
    import dataclasses as _dc

    from solvingpapers_tpu.data.batches import lm_batch_iterator
    from solvingpapers_tpu.train import OptimizerConfig, TrainConfig, Trainer

    # train at the model's FULL context: learned position embeddings
    # beyond the training length are garbage, and a serve stream that
    # decodes past them goes chaotic — which would silently turn the
    # acceptance measurement into noise
    limit = getattr(model, "max_positions", None) or 64
    seq = min(256, limit)
    tcfg = TrainConfig(
        steps=steps, batch_size=16, log_every=10 * steps, eval_every=0,
        optimizer=OptimizerConfig(max_lr=3e-3, warmup_steps=10,
                                  total_steps=steps),
    )
    tcfg = _dc.replace(tcfg, checkpoint_dir=None, ckpt_every=0)
    trainer = Trainer(model, tcfg)
    state = trainer.fit(lm_batch_iterator(corpus_ids, 16, seq, seed=seed))
    return jax.device_get(state.params)


# the period (21 tokens) must fit inside the shortest prompt so every
# stream's history holds a full cycle for the lookup from token one
SPEC_BENCH_TEXT = "the lazy dog sleeps. "


def run_spec_bench(
    config: str = "gpt_tiny_long",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 160,
    decode_block: int = 8,
    spec_k: int = 16,
    spec_rounds: int | None = 6,
    prompt_lens=(24, 32, 40, 48),
    mean_interarrival_s: float = 0.001,
    train_steps: int = 300,
    seed: int = 0,
    reps: int = 2,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """`cli serve-bench --speculative`: speculative vs plain decoding.

    Two ABBA-paired sub-workloads on the same Poisson arrival trace:

    1. GREEDY (the headline): spec-on (`speculative="ngram"`) vs
       spec-off delivered tokens/sec on a PREDICTABLE-CONTINUATION
       workload — the model is briefly fit on a repeated paragraph
       (`SPEC_BENCH_TEXT`) it memorizes, so greedy continuations of
       corpus-slice prompts reuse n-grams the lookup drafter finds.
       This is the regime speculative decoding exists for (grounded
       generation / repetitive completions); `acceptance_rate` in the
       entry discloses it, and the adversarial arm brackets the other
       end. Every spec-on stream is also checked token-exact against
       its spec-off twin (`greedy_token_exact` — CI asserts it).
    2. ADVERSARIAL: the same trace at temperature 2.0 (seeded) —
       near-random continuations the n-gram drafter cannot predict, so
       acceptance collapses and the controller must settle onto plain
       blocks with cheap exponential-backoff probes.
       `spec_adversarial_overhead_pct` is the budget (<= 10%) it is
       held to.

    The entry records acceptance_rate / spec_tokens_per_step from the
    spec arm's gauges plus the usual compile/peak-HBM probe fields."""
    model, params, extra, vocab = build_serve_model(config)
    text = SPEC_BENCH_TEXT * (80000 // len(SPEC_BENCH_TEXT))
    ids = np.frombuffer(text.encode("ascii", "replace"),
                        np.uint8).astype(np.int32) % vocab
    if train_steps > 0:
        params = _train_bench_model(model, ids, train_steps, seed=seed)
    # prompts are slices of the TRAINING corpus (the serving traffic the
    # brief fit models), at the usual Poisson arrival offsets
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s,
                                         size=n_requests))
    requests = []
    for i in range(n_requests):
        length = prompt_lens[i % len(prompt_lens)]
        start = int(rng.integers(0, ids.size - length))
        requests.append((float(arrivals[i]), ids[start:start + length]))
    max_prompt = max(len(p) for _, p in requests)
    limit = getattr(model, "max_positions", None)
    max_len = max_prompt + max_new
    if limit is not None and max_len > limit:
        raise ValueError(
            f"prompt + max_new = {max_len} exceeds the model's max "
            f"positions {limit}"
        )
    base = dict(
        n_slots=n_slots, max_len=max_len, decode_block=decode_block,
        bucket=min(32, max_prompt), max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests), seed=seed,
    )
    off_cfg = ServeConfig(**base)
    on_cfg = ServeConfig(**base, speculative="ngram", spec_k=spec_k,
                         spec_rounds=spec_rounds)

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, on_cfg, max_new,
        status_port=status_port,
    )
    try:
        _run_engine_arm(model, params, extra, warm, off_cfg, max_new)
        _run_engine_arm(model, params, extra, warm, on_cfg, max_new)

        # ---- 1. greedy headline: delivered tokens/sec, ABBA ----------
        runs, engines = _paired_arm_stats(
            model, params, extra, requests, on_cfg, off_cfg, max_new,
            reps=reps,
        )
        total_tokens = n_requests * max_new
        on_tps = total_tokens / (
            sum(mk for mk, _ in runs["on"]) / len(runs["on"]))
        off_tps = total_tokens / (
            sum(mk for mk, _ in runs["off"]) / len(runs["off"]))
        on_snap = runs["on"][-1][1]
        # token-exactness across arms: rerun both once on the same
        # trace and compare handle-for-handle (greedy, so each arm is
        # deterministic — the pairing above only kept makespans)
        _, on_handles, _ = _run_engine_arm(
            model, params, extra, requests, on_cfg, max_new)
        _, off_handles, _ = _run_engine_arm(
            model, params, extra, requests, off_cfg, max_new)
        exact = all(a.tokens == b.tokens
                    for a, b in zip(on_handles, off_handles))

        # ---- 2. adversarial: zero-acceptance random-token streams ----
        # RANDOM-TOKEN prompts + temperature 2.0: the history holds no
        # structure for the lookup and the sampled continuations match
        # nothing — acceptance collapses toward zero, the regime the
        # adaptive controller's backoff exists for
        def hot(i: int) -> SamplingParams:
            return SamplingParams(temperature=2.0, seed=seed * 1000 + i)

        adv_requests = [
            (a, rng.integers(0, vocab, size=len(p)).astype(np.int32))
            for a, p in requests
        ]
        _run_engine_arm(model, params, extra, warm, on_cfg, max_new,
                        params_for=hot)
        aruns, _ = _paired_arm_stats(
            model, params, extra, adv_requests, on_cfg, off_cfg, max_new,
            reps=reps, params_for=hot,
        )
        adv_on = total_tokens / (
            sum(mk for mk, _ in aruns["on"]) / len(aruns["on"]))
        adv_off = total_tokens / (
            sum(mk for mk, _ in aruns["off"]) / len(aruns["off"]))
        adv_snap = aruns["on"][-1][1]

        detail = {
            "config": config,
            "workload": "speculative-decode",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "spec_k": spec_k,
            "spec_rounds": spec_rounds or decode_block,
            "train_steps": train_steps,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "spec_tokens_per_sec": round(on_tps, 1),
            "plain_tokens_per_sec": round(off_tps, 1),
            "spec_speedup": round(on_tps / off_tps, 2),
            "acceptance_rate": round(
                on_snap.get("serve/spec_acceptance_rate", 0.0), 3),
            "spec_tokens_per_step": round(
                on_snap.get("serve/spec_tokens_per_step", 0.0), 1),
            "greedy_token_exact": bool(exact),
            "adversarial_spec_tokens_per_sec": round(adv_on, 1),
            "adversarial_plain_tokens_per_sec": round(adv_off, 1),
            "spec_adversarial_overhead_pct": round(
                (1.0 - adv_on / adv_off) * 100.0, 2),
            "adversarial_acceptance_rate": round(
                adv_snap.get("serve/spec_acceptance_rate", 0.0), 3),
            **_kv_entry_fields(engines["on"]),
            **probe_fields,
        }
        if probe_eng is not None and status_hold_s > 0:
            time.sleep(status_hold_s)
    finally:
        if probe_eng is not None:
            probe_eng.close()
    return {
        "metric": "serve_speculative_tokens_per_sec",
        "value": detail["spec_tokens_per_sec"],
        "unit": "tok/s (greedy Poisson, briefly-trained model)",
        "vs_baseline": detail["spec_speedup"],
        "detail": detail,
    }


def run_quant_bench(
    config: str = "gpt_tiny_long",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    page_size: int = 16,
    kv_quant_block: int = 16,
    train_steps: int = 200,
    seed: int = 0,
    reps: int = 2,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """`cli serve-bench --kv-quant int8`: int8 KV storage vs exact.

    Three sub-claims, one entry, on a BRIEFLY-TRAINED model (the same
    discipline as `run_spec_bench`, for the same reason: a random-init
    model's greedy argmax is a coin toss over near-uniform logits, so
    agreement under ANY perturbation measures tie-breaking, not quality
    — measured 0.89 on random init vs the trained corpus model's
    regime; the `train_steps` field discloses it, 0 = random init):

    1. QUALITY: greedy-token agreement between the quantized and exact
       lane pools, measured TEACHER-FORCED (`greedy_agreement_rate`,
       the >= 0.99 gate CI asserts): the exact arm's streams are cut
       every 8 positions and each prefix replays through the quantized
       engine for ONE token — does int8 storage of the same history
       flip the next argmax? That is the metric KV-quant quality is
       comparable on; free-running ROLLOUT agreement is also recorded
       (`rollout_agreement_rate`) but not gated — a single flip at a
       genuine branch point (near-tied argmax margins survive any
       finite perturbation, including bf16 rounding) cascades over the
       whole tail, so rollout exact-match decays with stream length for
       ANY lossy storage and measures divergence persistence, not
       per-step quality.
    2. OVERHEAD (ABBA-paired, lane pool, same slots): like-for-like
       Poisson req/s with kv_quant on vs off — the dequant/requant tax
       (`quant_overhead_pct`, <= 10 budget).
    3. CAPACITY at EQUAL HBM (paged pools): the f32 pool's resident
       byte budget buys `budget // quant_page_nbytes` int8+scale pages;
       the quantized engine books the slots those pages cover and the
       short-stream flood drives them all live (`capacity_peak_active_
       slots` vs the f32 pool's `n_slots` — the >= 1.8x servable-slots
       headline), with both pools' ledger bytes pinned analytically
       (`quant_pool_bytes` must reproduce `pool.nbytes` EXACTLY, and
       the quantized pool must fit the budget)."""
    from solvingpapers_tpu.data.synthetic import synthetic_text
    from solvingpapers_tpu.serve.kv_pool import (
        PagedKVPool,
        quant_pool_bytes,
    )

    model, params, extra, vocab = build_serve_model(config)
    text = synthetic_text(n_chars=80000, seed=seed)
    ids = np.frombuffer(text.encode("ascii", "replace"),
                        np.uint8).astype(np.int32) % vocab
    if train_steps > 0:
        params = _train_bench_model(model, ids, train_steps, seed=seed)
    # prompts are slices of the TRAINING corpus: the agreement rate is
    # measured where the model actually models its input (the "bench
    # corpus" of the quality gate), not on noise
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s,
                                         size=n_requests))
    requests = []
    for i in range(n_requests):
        length = prompt_lens[i % len(prompt_lens)]
        start = int(rng.integers(0, ids.size - length))
        requests.append((float(arrivals[i]), ids[start:start + length]))
    max_prompt = max(len(p) for _, p in requests)
    # lane scale rows and page tables both need whole blocks/pages (and
    # max_len must divide by BOTH — max() crashes the pools on combos
    # where neither divides the other, e.g. block 12 x page 16)
    grain = math.lcm(page_size, kv_quant_block)
    max_len = -(-(max_prompt + max_new) // grain) * grain
    limit = getattr(model, "max_positions", None)
    if limit is not None and max_len > limit:
        max_len = limit // grain * grain
    base = dict(
        n_slots=n_slots, max_len=max_len, decode_block=decode_block,
        bucket=min(32, max_prompt), max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests), seed=seed,
    )
    exact_cfg = ServeConfig(**base)
    quant_cfg = ServeConfig(**base, kv_quant="int8",
                            kv_quant_block=kv_quant_block)

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, quant_cfg, max_new,
        status_port=status_port,
    )
    try:
        _run_engine_arm(model, params, extra, warm, exact_cfg, max_new)
        _run_engine_arm(model, params, extra, warm, quant_cfg, max_new)

        # ---- 1. quality: rollout + teacher-forced agreement ----------
        quant_eng, q_handles, _ = _run_engine_arm(
            model, params, extra, requests, quant_cfg, max_new)
        _, x_handles, _ = _run_engine_arm(
            model, params, extra, requests, exact_cfg, max_new)
        rollout = _token_agreement(x_handles, q_handles)
        # teacher-forced cuts: the exact stream at prefix (prompt +
        # gen[:j]) continues with gen[j] BY CONSTRUCTION (greedy), so
        # the reference needs no second engine — replay each cut prefix
        # through the quantized engine for one token and compare
        cuts, expected = [], []
        for (_, p), h in zip(requests, x_handles):
            seq = np.concatenate(
                [p, np.asarray(h.tokens, np.int32)])
            for j in range(0, len(h.tokens), 8):
                cuts.append((0.0, seq[:len(p) + j]))
                expected.append(h.tokens[j])
        cut_cfg = dataclasses.replace(
            quant_cfg, max_waiting=max(quant_cfg.max_waiting, len(cuts)))
        _, cut_handles, _ = _run_engine_arm(
            model, params, extra, cuts, cut_cfg, 1)
        agreement = sum(
            int(h.tokens[0] == e)
            for h, e in zip(cut_handles, expected)
        ) / len(expected)

        # ledger honesty, pinned where the capacity claim is made: the
        # pool's nbytes must decompose exactly into the analytic
        # int8-payload + f32-scale-row sums
        q_bytes, s_bytes, e_bytes, base_bytes = quant_pool_bytes(
            quant_eng.pool.caches)
        if quant_eng.pool.nbytes != q_bytes + e_bytes:
            raise AssertionError(
                f"quantized lane pool nbytes {quant_eng.pool.nbytes} != "
                f"analytic int8+scales+exact {q_bytes + e_bytes}"
            )

        # ---- 2. overhead: ABBA-paired quant vs exact, same slots -----
        runs, engines = _paired_arm_stats(
            model, params, extra, requests, quant_cfg, exact_cfg, max_new,
            reps=reps,
        )
        quant_rps = len(requests) / (
            sum(mk for mk, _ in runs["on"]) / len(runs["on"]))
        exact_rps = len(requests) / (
            sum(mk for mk, _ in runs["off"]) / len(runs["off"]))

        # ---- 3. capacity at the f32 paged pool's byte budget ---------
        f32_pool = PagedKVPool(model, n_slots, max_len, page_size)
        budget_bytes = int(f32_pool.nbytes)
        del f32_pool
        # per-page cost of int8 payload + its scale rows, probed on a
        # minimal pool (1 lane + trash) rather than derived — the probe
        # IS the accounting the ledger uses
        probe_pool = PagedKVPool(model, 1, page_size, page_size,
                                 quant="int8")
        quant_page_nbytes = probe_pool.page_nbytes
        del probe_pool
        pages_per_lane = max_len // page_size
        # the budget affords this many quantized pages (one reserved for
        # the trash page the pool books on top of the budget)
        cap_budget = budget_bytes // quant_page_nbytes - 1
        cap_slots = cap_budget // pages_per_lane
        cap_new = max(8, max_new // 4)  # short streams: the capacity
        # regime (many live contexts, shallow decode)
        cap_n = max(n_requests, cap_slots + 2)
        cap_requests = []
        cap_arrivals = np.cumsum(rng.exponential(mean_interarrival_s,
                                                 size=cap_n))
        for i in range(cap_n):
            length = prompt_lens[i % len(prompt_lens)]
            start = int(rng.integers(0, ids.size - length))
            cap_requests.append(
                (float(cap_arrivals[i]), ids[start:start + length]))
        cap_cfg = ServeConfig(**{**base, "n_slots": cap_slots,
                                 "max_prefills_per_step": cap_slots,
                                 "max_waiting": max(256, cap_n)},
                              paged=True, page_size=page_size,
                              page_budget=cap_budget, kv_quant="int8")
        # observatory pass first: the gather's dequantized lane view is
        # PROGRAM TEMP that an equal-HBM claim must disclose, not hide
        cap_obs = dataclasses.replace(cap_cfg, xla_obs=True)
        obs_cap_eng, _, _ = _run_engine_arm(
            model, params, extra, warm, cap_obs, cap_new,
        )
        cap_temp = int(obs_cap_eng.registry.max_temp_bytes())
        # decompose the QUANTIZED paged decode step at the capacity
        # arm's exact shapes: the int8 gather+dequant+scatter shares of
        # the fenced decode wall — the kv-quant half of the per-
        # component baseline ROADMAP item 1 diffs against
        cap_step_wall = _decode_step_wall_s(obs_cap_eng.registry)
        decomp_fields: dict = {}
        if cap_step_wall is not None:
            from solvingpapers_tpu.serve.kernel_bench import (
                paged_decode_decomposition,
            )

            decomp_fields = paged_decode_decomposition(
                model, n_slots=cap_slots, max_len=max_len,
                page_size=page_size, decode_block=decode_block,
                step_wall_s=cap_step_wall, kv_quant="int8", reps=3,
                seed=seed,
            )
        _run_engine_arm(model, params, extra, warm, cap_cfg, cap_new)
        cap_eng, cap_handles, cap_mk = _run_engine_arm(
            model, params, extra, cap_requests, cap_cfg, cap_new,
        )
        cap_resident = int(cap_eng.pool.nbytes)
        if cap_resident > budget_bytes:
            raise AssertionError(
                f"quantized paged pool resident bytes {cap_resident} "
                f"exceed the f32 budget {budget_bytes}"
            )
        cap_snap = cap_eng.metrics.snapshot()

        detail = {
            "config": config,
            "workload": "quant-kv",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "page_size": page_size,
            "kv_quant_block": kv_quant_block,
            "max_len": max_len,
            "train_steps": train_steps,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "quant_requests_per_sec": round(quant_rps, 2),
            "exact_requests_per_sec": round(exact_rps, 2),
            "quant_overhead_pct": round(
                (1.0 - quant_rps / exact_rps) * 100.0, 2),
            "agreement_cuts": len(expected),
            "rollout_agreement_rate": round(rollout, 4),
            # int8 payload + scale rows over the same pool at the
            # compute dtype — the CI smoke gates <= 0.6
            "kv_bytes_ratio": round(q_bytes / base_bytes, 4),
            "kv_scale_bytes": int(s_bytes),
            "exact_kv_pool_bytes": int(engines["off"].pool.nbytes),
            "f32_paged_kv_pool_bytes": budget_bytes,
            "quant_page_nbytes": int(quant_page_nbytes),
            "capacity_n_slots": cap_slots,
            "capacity_page_budget": cap_budget,
            "capacity_n_requests": cap_n,
            "capacity_max_new_tokens": cap_new,
            "capacity_peak_active_slots": _peak_concurrency(cap_handles),
            "capacity_kv_pool_bytes": cap_resident,
            "capacity_program_temp_bytes": cap_temp,
            "capacity_requests_per_sec": round(cap_n / cap_mk, 2),
            "capacity_preemptions": int(
                cap_snap.get("serve/preemptions", 0.0)
            ),
            **_kv_entry_fields(quant_eng, agreement),
            **probe_fields,
            # LAST: the shares' decode_step_wall_s is the capacity
            # arm's quantized paged wall (what they decompose), not the
            # probe's lane-pool one — decomp wins the key
            **decomp_fields,
        }
        # armed-anatomy overhead on the like-for-like quant arm, the
        # same <= 2% ABBA budget as the paged entry's
        detail.update(_obs_arm_fields(
            model, params, extra, requests, quant_cfg, max_new, reps=reps,
            prefix="anatomy",
        ))
        if probe_eng is not None and status_hold_s > 0:
            time.sleep(status_hold_s)
    finally:
        if probe_eng is not None:
            probe_eng.close()
    return {
        "metric": "serve_quant_slots_at_equal_hbm",
        "value": detail["capacity_peak_active_slots"],
        "unit": "concurrent slots (f32 paged-pool HBM budget)",
        "vs_baseline": round(
            detail["capacity_peak_active_slots"] / n_slots, 2
        ),
        "detail": detail,
    }


def sampling_params_mix(i: int) -> SamplingParams:
    """Request i's params in the --sampling workload: one greedy slot in
    four, the rest a temperature/top-p/top-k/min-p rotation (seeded per
    request so the workload itself is reproducible). The mix keeps every
    decode block heterogeneous — the exact situation the fused per-slot
    sampler exists for."""
    mix = (
        SamplingParams(),  # greedy — must coexist with the rest
        SamplingParams(temperature=1.0, top_p=0.9, seed=i),
        SamplingParams(temperature=1.2, top_k=50, seed=i),
        SamplingParams(temperature=0.8, min_p=0.05, seed=i),
    )
    return mix[i % len(mix)]


def run_sampling_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    seed: int = 0,
    trace: bool = False,
    trace_out: str | None = None,
    trace_dump: str | None = None,
    obs: bool = False,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """Sampled vs greedy decode on the same Poisson trace.

    Both arms run the SAME engine over the SAME arrival offsets; the only
    difference is the per-request SamplingParams mix. The headline
    (`vs_baseline`) is sampled req/s / greedy req/s — the fused sampler's
    overhead when a batch actually mixes stochastic requests (greedy-only
    batches take a sort-free runtime fast path and cost what the old
    static greedy sampler did).
    """
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    serve_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_prompt + max_new,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests),
        seed=seed,
    )

    # warm every compiled shape (prefill buckets + decode; the sampled
    # path adds NO programs — that is the point — but warm both arms so
    # neither pays first-call dispatch differences)
    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    # probe mirrors the headline (sampled-mix) arm
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, serve_cfg, max_new,
        status_port=status_port, params_for=sampling_params_mix,
    )
    try:
        _run_engine_arm(model, params, extra, warm, serve_cfg, max_new)
        _run_engine_arm(model, params, extra, warm, serve_cfg, max_new,
                        params_for=sampling_params_mix)

        arms = {}
        last_eng = None
        for name, params_for in (("greedy", None),
                                 ("sampled", sampling_params_mix)):
            eng, _, makespan = _run_engine_arm(
                model, params, extra, requests, serve_cfg, max_new,
                params_for=params_for,
            )
            last_eng = eng
            snap = eng.metrics.snapshot()
            arms[name] = {
                "requests_per_sec": n_requests / makespan,
                "tokens_per_sec": snap.get("serve/tokens_per_sec", 0.0),
                **_round_if_present(snap, "serve/ttft_s_mean",
                                    "mean_ttft_s", 4),
                **_round_if_present(snap, "serve/itl_s_p95",
                                    "itl_p95_s", 5),
            }
        trace_fields = {}
        if obs:
            trace_fields.update(_obs_arm_fields(
                model, params, extra, requests, serve_cfg, max_new,
                params_for=sampling_params_mix,
            ))
        if trace:
            # traced arm mirrors the headline (sampled-mix) arm
            trace_fields.update(_traced_arm_fields(
                model, params, extra, requests, serve_cfg, max_new,
                trace_out, trace_dump, params_for=sampling_params_mix,
            ))
        if probe_eng is not None and status_hold_s > 0:
            time.sleep(status_hold_s)
    finally:
        if probe_eng is not None:
            probe_eng.close()
    ratio = arms["sampled"]["requests_per_sec"] / arms["greedy"][
        "requests_per_sec"]
    return {
        "metric": "serve_sampling_requests_per_sec",
        "value": round(arms["sampled"]["requests_per_sec"], 2),
        "unit": "req/s",
        # > 1 would mean sampling was free (noise); ~0.9 = 10% overhead
        "vs_baseline": round(ratio, 3),
        "detail": {
            "config": config,
            "workload": "sampling-mix",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "sampling_overhead_pct": round((1.0 - ratio) * 100.0, 1),
            **{f"{arm}_{k}": (round(v, 2) if isinstance(v, float) else v)
               for arm, d in arms.items() for k, v in d.items()},
            **_kv_entry_fields(last_eng),
            **probe_fields,
            **trace_fields,
        },
    }


def _run_http_arm(model, params, extra, requests, serve_cfg, max_new):
    """The same arrival trace served over the OpenAI HTTP front door:
    one SSE client thread per request, submitted at its Poisson offset.

    Latency is measured where a real user feels it — at the CLIENT side
    of the socket: TTFT = first text chunk - arrival, ITL = inter-chunk
    gaps amortized over the chunk's token count (the server has no
    tokenizer here, so tokens stream as "id " text and counts fall out
    of a split). Returns ``(makespan, stats)`` where stats carries
    per-request ttft/itl samples and the streamed token ids (the
    token-exactness check against the direct-submit arm)."""
    import http.client
    import json as _json
    import threading

    from solvingpapers_tpu.serve.api import ApiServer

    eng = ServeEngine(model, params, serve_cfg, extra_variables=extra)
    srv = ApiServer(eng)
    pending = sorted(requests, key=lambda r: r[0])
    results: list = [None] * len(pending)
    t0 = time.monotonic()

    def client(i: int, arrival: float, prompt) -> None:
        delay = arrival - (time.monotonic() - t0)
        if delay > 0:
            time.sleep(delay)
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=600)
        body = _json.dumps({
            "prompt": [int(t) for t in prompt], "max_tokens": max_new,
            "temperature": 0, "stream": True,
        })
        conn.request("POST", "/v1/completions", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()[:200]
        ttft = None
        last = None
        gaps: list[float] = []
        text_parts: list[str] = []
        reason = None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            payload = line[6:]
            if payload == b"[DONE]":
                break
            now = time.monotonic()
            chunk = _json.loads(payload)
            choice = chunk["choices"][0]
            reason = choice["finish_reason"] or reason
            text = choice["text"]
            n = len(text.split())
            if n == 0:
                continue
            text_parts.append(text)
            if ttft is None:
                ttft = now - (t0 + arrival)
                n -= 1  # the first token stamps TTFT, not an ITL gap
            if last is not None and n > 0:
                gaps.extend([(now - last) / n] * n)
            last = now
        conn.close()
        ids = [int(x) for x in "".join(text_parts).split()]
        results[i] = {
            "ttft": ttft, "gaps": gaps, "ids": ids, "reason": reason,
            "finish": time.monotonic() - t0,
        }

    threads = [
        threading.Thread(target=client, args=(i, a, p), daemon=True)
        for i, (a, p) in enumerate(pending)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    srv.close()
    assert all(r is not None and r["reason"] for r in results), \
        "an HTTP stream died or ended without a finish_reason"
    makespan = max(r["finish"] for r in results) - pending[0][0]
    return makespan, results


def run_http_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    seed: int = 0,
    reps: int = 2,
) -> dict:
    """`cli serve-bench --http`: the concurrent-SSE-connection soak.

    The same Poisson arrival trace runs ABBA-paired through (A) the
    OpenAI front door — n_requests concurrent SSE clients over real
    loopback sockets, engine driven by the ApiServer's EngineLoop
    thread — and (B) direct in-process `engine.submit` + `step()` (the
    `run_serve_bench` engine arm). `http_overhead_pct` is the full cost
    of the network path: HTTP parsing, the submit lock, per-block SSE
    writes, disconnect probes, client-side scheduling jitter. The
    acceptance budget is <= 10%. Every streamed id sequence is also
    checked token-exact against the direct arm's handle for the same
    prompt — the wire must not change the tokens."""
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    serve_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_prompt + max_new,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests),
        # every client streams concurrently: the front door's
        # per-connection cap must clear the client count or the soak
        # 503s itself
        api_max_connections=max(64, n_requests),
        seed=seed,
    )
    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, _ = _obs_probe(model, params, extra, warm, serve_cfg,
                                 max_new)
    _run_engine_arm(model, params, extra, warm, serve_cfg, max_new)

    http_mk: list[float] = []
    direct_mk: list[float] = []
    http_stats = None
    direct_handles = None
    direct_eng = None
    for r in range(reps):
        order = ("http", "direct") if r % 2 == 0 else ("direct", "http")
        for arm in order:
            if arm == "http":
                mk, http_stats = _run_http_arm(
                    model, params, extra, requests, serve_cfg, max_new
                )
                http_mk.append(mk)
            else:
                direct_eng, direct_handles, mk = _run_engine_arm(
                    model, params, extra, requests, serve_cfg, max_new
                )
                direct_mk.append(mk)
    # the wire must not change the tokens: every streamed id sequence
    # matches the direct arm's handle for the same prompt (both arms
    # process the arrival-sorted trace, so indexes align)
    exact = all(
        http_stats[j]["ids"] == direct_handles[j].tokens
        for j in range(len(requests))
    )
    http_rps = n_requests / (sum(http_mk) / len(http_mk))
    direct_rps = n_requests / (sum(direct_mk) / len(direct_mk))
    ttfts = [r["ttft"] for r in http_stats]
    gaps = [g for r in http_stats for g in r["gaps"]]
    return {
        "metric": "serve_http_stream_requests_per_sec",
        "value": round(http_rps, 2),
        "unit": "req/s",
        # ~1.0 = the front door is free; the acceptance budget is >= 0.9
        "vs_baseline": round(http_rps / direct_rps, 3),
        "detail": {
            "config": config,
            "workload": "http-stream-soak",
            "n_requests": n_requests,
            "n_clients": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "http_requests_per_sec": round(http_rps, 2),
            "direct_requests_per_sec": round(direct_rps, 2),
            "http_overhead_pct": round(
                (1.0 - http_rps / direct_rps) * 100.0, 2
            ),
            "http_mean_ttft_s": round(float(np.mean(ttfts)), 4),
            "http_ttft_p95_s": round(float(np.percentile(ttfts, 95)), 4),
            "http_itl_p99_s": round(float(np.percentile(gaps, 99)), 5)
            if gaps else None,
            "stream_token_exact": bool(exact),
            **_kv_entry_fields(direct_eng),
            **probe_fields,
        },
    }


SLO_CLASS_CYCLE = ("interactive", "standard", "batch")


def run_slo_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    seed: int = 0,
    reps: int = 4,
    slo_targets: dict | None = None,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """`cli serve-bench --slo`: the SLO-observatory workload.

    The like-for-like Poisson trace runs ABBA-paired through (A) an
    engine with the full request observatory on — `ServeConfig.
    slo_targets` set, every request tagged with an SLO class cycling
    interactive/standard/batch, per-class attainment + burn + goodput
    accounted on each finish — and (B) the plain engine. Both arms
    decode greedily and compile the same programs (SLO accounting is
    pure host-side finish-path bookkeeping), so `slo_overhead_pct` is
    the cost of the whole observatory layer: the histogram latency
    backend plus per-finish SLO accounting. The acceptance budget is
    the PR-4/5 instrumentation budget: <= 2% on this paired arm.

    The entry records per-class attainment/burn and
    `goodput_tokens_per_s` (tokens from SLO-attained requests only) —
    the serving quality trajectory `tools/bench_check.py` gates, and
    the number the DistServe-style disaggregation phase (ROADMAP item
    2) will optimize.
    """
    from solvingpapers_tpu.serve.slo import DEFAULT_SLO_TARGETS

    targets = slo_targets or DEFAULT_SLO_TARGETS
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    base_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_prompt + max_new,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests),
        seed=seed,
    )
    slo_cfg = dataclasses.replace(base_cfg, slo_targets=targets)

    def params_for(i: int) -> SamplingParams:
        # greedy everywhere — ONLY the class tag differs, so both arms
        # run identical compiled programs and identical tokens
        return SamplingParams(slo=SLO_CLASS_CYCLE[i % len(SLO_CLASS_CYCLE)])

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, slo_cfg, max_new,
        status_port=status_port, params_for=params_for,
    )
    _run_engine_arm(model, params, extra, warm, base_cfg, max_new)

    # ABBA pairing with PER-ARM request params (the shared
    # _paired_makespans helper applies one params_for to both arms;
    # here the off arm must stay untagged or its submits would reject)
    mk = {"slo": [], "plain": []}
    slo_eng = None
    for r in range(reps):
        order = ("slo", "plain") if r % 2 == 0 else ("plain", "slo")
        for arm in order:
            if arm == "slo":
                slo_eng, _, span = _run_engine_arm(
                    model, params, extra, requests, slo_cfg, max_new,
                    params_for=params_for,
                )
            else:
                _, _, span = _run_engine_arm(
                    model, params, extra, requests, base_cfg, max_new,
                )
            mk[arm].append(span)
    slo_rps = n_requests / (sum(mk["slo"]) / len(mk["slo"]))
    plain_rps = n_requests / (sum(mk["plain"]) / len(mk["plain"]))

    snap = slo_eng.metrics.snapshot()
    slo_doc = slo_eng.statusz()["slo"]
    per_class = {
        cls: {
            "finished": d["finished"],
            "attainment": d["attainment"],
            "burn_rate": d["burn_rate"],
            "violations": d["violations"],
        }
        for cls, d in slo_doc["classes"].items()
    }
    tokens_per_s = snap.get("serve/tokens_per_sec", 0.0)
    goodput_per_s = snap.get("serve/goodput_tokens_per_s", 0.0)
    if status_hold_s > 0 and probe_eng is not None:
        time.sleep(status_hold_s)
    if probe_eng is not None:
        probe_eng.close()
    return {
        "metric": "serve_slo_goodput_tokens_per_s",
        "value": round(goodput_per_s, 2),
        "unit": "tok/s from SLO-attained requests (last slo-on rep)",
        # goodput / raw throughput: 1.0 = every token was delivered
        # inside its class's latency targets
        "vs_baseline": round(goodput_per_s / tokens_per_s, 3)
        if tokens_per_s else 0.0,
        "detail": {
            "config": config,
            "workload": "slo-observatory",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "class_cycle": list(SLO_CLASS_CYCLE),
            "slo_targets": {
                cls: {k: v for k, v in spec.items()}
                for cls, spec in targets.items()
            },
            "slo_overhead_pct": round(
                (1.0 - slo_rps / plain_rps) * 100.0, 2
            ),
            "slo_requests_per_sec": round(slo_rps, 2),
            "plain_requests_per_sec": round(plain_rps, 2),
            "goodput_tokens_per_s": round(goodput_per_s, 2),
            "tokens_per_sec": round(tokens_per_s, 2),
            "goodput_ratio": round(goodput_per_s / tokens_per_s, 4)
            if tokens_per_s else 0.0,
            "attainment_by_class": per_class,
            "goodput_tokens": int(slo_doc["goodput_tokens"]),
            **_round_if_present(snap, "serve/ttft_s_p95", "ttft_p95_s", 4),
            **_round_if_present(snap, "serve/itl_s_p95", "itl_p95_s", 5),
            **_round_if_present(snap, "serve/e2e_s_p95", "e2e_p95_s", 4),
            **_kv_entry_fields(slo_eng),
            **probe_fields,
        },
    }


# ----------------------------------------------------------------- chaos


def chaos_fault_plan(n_slots: int, seed: int = 0,
                     stall_s: float = 0.05,
                     journal: bool = False) -> tuple:
    """The seeded chaos schedule `run_chaos_bench` drives: two slot
    poisons (NaN + Inf — the quarantine path, both finite-guard codes),
    one synthetic XlaRuntimeError and one prefill OOM (the
    rebuild-and-recompute path), and one step stall (the watchdog).
    With `journal`, one ``io_error`` at the ``journal_write`` site —
    the degraded-journal path (serving survives, durability is lost
    and says so). Deterministic given (n_slots, seed): the same
    schedule replays bit-identically across the ladder-on and
    ladder-off arms, which is what makes their goodput comparison a
    controlled experiment."""
    rng = np.random.default_rng(seed)
    slots = rng.permutation(n_slots)
    v = sorted(int(x) for x in rng.integers(8, 48, size=4))
    plan = (
        dict(site="prefill", kind="oom", visit=int(rng.integers(3, 8))),
        dict(site="decode", kind="nan", visit=v[0], slot=int(slots[0])),
        dict(site="decode", kind="inf", visit=v[1],
             slot=int(slots[1 % len(slots)])),
        dict(site="decode", kind="xla_error", visit=v[2]),
        dict(site="decode", kind="stall", visit=v[3], stall_s=stall_s),
    )
    if journal:
        plan += (dict(site="journal_write", kind="io_error",
                      visit=int(rng.integers(6, 24))),)
    return plan


def _run_chaos_arm(model, params, extra, requests, serve_cfg, max_new,
                   params_for=None):
    """`_run_engine_arm` that tolerates rejects: under the degradation
    ladder (or an unhealthy window) submissions may bounce — those are
    collected as `shed`, not crashed on. Returns (engine, accepted
    handles BY REQUEST INDEX (None = shed), shed count, makespan)."""
    eng = ServeEngine(model, params, serve_cfg, extra_variables=extra)
    pending = sorted(enumerate(requests), key=lambda r: r[1][0])
    handles: list = [None] * len(requests)
    shed = 0
    t0 = time.monotonic()
    i = 0
    while i < len(pending) or eng.has_work():
        elapsed = time.monotonic() - t0
        while i < len(pending) and pending[i][1][0] <= elapsed:
            ridx, (_, prompt) = pending[i]
            h = eng.submit(
                prompt, max_new_tokens=max_new,
                params=params_for(ridx) if params_for is not None else None,
            )
            if h.state == "rejected":
                shed += 1
            else:
                handles[ridx] = h
            i += 1
        if eng.has_work():
            eng.step()
        elif i < len(pending):
            time.sleep(max(0.0, pending[i][1][0]
                           - (time.monotonic() - t0)))
    makespan = (time.monotonic() - t0) - pending[0][1][0]
    live = [h for h in handles if h is not None]
    assert all(h.done for h in live), "chaos arm drained unfinished"
    return eng, handles, shed, makespan


def _zero_leak_fields(eng) -> dict:
    """The post-drain leak invariant as bench-entry facts (the test
    suite's `assert_no_leaks` as data): slot free-mask/free-list
    consistency, paged free-pages == budget with the refcount sum back
    at the trash page's 1 (the prefix tree is fully evicted first —
    its references are the one legitimate post-drain holder), and the
    exact-lane free list intact."""
    pool = eng.pool
    ok = (pool.n_active == 0 and bool(pool._free_mask.all())
          and sorted(pool._free) == list(range(pool.n_slots)))
    out = {"slots_clean": ok}
    if eng.prefix_cache is not None:
        while eng.prefix_cache.evict_one():
            pass
    if hasattr(pool, "refcount"):
        out["pages_free"] = pool.pages_free
        out["page_budget"] = pool.page_budget
        out["refcount_sum"] = int(pool.refcount.sum())
        ok = (ok and pool.pages_free == pool.page_budget
              and out["refcount_sum"] == 1)
    if getattr(pool, "exact_lanes", 0):
        ok = ok and sorted(eng._exact_free) == list(
            range(1, pool.exact_lanes + 1))
    out["zero_leak"] = ok
    return out


def run_chaos_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 48,
    n_slots: int = 4,
    max_new: int = 48,
    decode_block: int = 8,
    prompt_lens=(16, 32, 48, 64),
    # arrivals SPREAD (vs the other workloads' burst): load-shedding is
    # only observable while admissions keep arriving with the ladder up
    mean_interarrival_s: float = 0.15,
    seed: int = 0,
    reps: int = 4,
    # long enough that the injected stall ALONE exceeds the watchdog
    # deadline below — the soak must actually exercise the fire path
    stall_s: float = 0.75,
    slo_targets: dict | None = None,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """`cli serve-bench --chaos`: the fault-tolerance soak.

    One SEEDED fault schedule (`chaos_fault_plan`: NaN + Inf slot
    poisons, a synthetic XlaRuntimeError, a prefill OOM, a step stall)
    replays over the Poisson trace through three engines:

    * reference — fault-free, SLO-tracked: the token-exactness oracle.
    * chaos, ladder OFF — every request admitted; measures the blast
      radius: `streams_survived` (finished non-"error"),
      `survivors_token_exact` (every surviving stream byte-identical
      to the reference — quarantine contained the poison, rebuilds
      recomputed exactly), `fault_recovery_s` (first failure -> first
      clean step), and the post-drain `zero_leak` invariant.
    * chaos, ladder ON — same schedule plus the degradation ladder
      over DEFAULT_SLO_TARGETS under deliberate overload: burn-rate
      pressure climbs the rungs, admissions shed by class (batch
      first), and `goodput_ladder_on` vs `goodput_ladder_off` records
      whether shedding protected more SLO-attained tokens than it cost
      — the number the ladder exists for (>= 1.0 ratio is the claim).

    `fault_overhead_pct` is the ABBA-paired cost of an ARMED-BUT-QUIET
    fault plane (a schedule that never fires) vs `fault_plan=None` —
    the None-pattern budget (<= 2%, the tracer's). The always-traced
    finite-logits guard rides BOTH arms (it has no off switch by
    design), so the number isolates the plan hooks themselves.
    """
    from solvingpapers_tpu.serve.slo import DEFAULT_SLO_TARGETS

    targets = slo_targets or DEFAULT_SLO_TARGETS
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    max_len = -(-(max_prompt + max_new) // 16) * 16  # page multiple
    # the chaos arms run JOURNALED with an injected journal_write
    # io_error in the schedule: the soak deterministically exercises
    # the degraded-journal path (serving survives losing its journal;
    # the entry records that the degrade actually fired)
    plan = chaos_fault_plan(n_slots, seed=seed, stall_s=stall_s,
                            journal=True)
    journal_dir = tempfile.mkdtemp(prefix="serve_chaos_journal_")
    base_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_len,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests),
        paged=True,
        page_size=16,
        seed=seed,
    )
    ref_cfg = dataclasses.replace(base_cfg, slo_targets=targets)
    # deadline BELOW the injected stall (floored well above a normal
    # tiny-model step): the stall spec must trip the watchdog, not
    # sneak under its own deadline
    chaos_cfg = dataclasses.replace(
        ref_cfg, fault_plan=plan,
        fault_step_deadline_s=max(0.25, 0.75 * stall_s),
        journal_path=os.path.join(journal_dir, "chaos_off.jsonl"),
    )
    ladder_cfg = dataclasses.replace(
        chaos_cfg, degrade=True,
        journal_path=os.path.join(journal_dir, "chaos_on.jsonl"),
    )

    def params_for(i: int) -> SamplingParams:
        return SamplingParams(slo=SLO_CLASS_CYCLE[i % len(SLO_CLASS_CYCLE)])

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, ref_cfg, max_new,
        status_port=status_port, params_for=params_for,
    )
    # reference arm: the fault-free token oracle (also the jit warmup)
    ref_eng, ref_handles, _, _ = _run_chaos_arm(
        model, params, extra, requests, ref_cfg, max_new,
        params_for=params_for,
    )

    # chaos, ladder OFF: blast radius + recovery + leaks
    off_eng, off_handles, off_shed, _ = _run_chaos_arm(
        model, params, extra, requests, chaos_cfg, max_new,
        params_for=params_for,
    )
    off_snap = off_eng.metrics.snapshot()
    survivors = [(i, h) for i, h in enumerate(off_handles)
                 if h is not None and h.finish_reason != "error"]
    errored = sum(1 for h in off_handles
                  if h is not None and h.finish_reason == "error")
    exact = all(h.tokens == ref_handles[i].tokens for i, h in survivors)
    leak_fields = _zero_leak_fields(off_eng)
    goodput_off = off_snap.get("serve/goodput_tokens_per_s", 0.0)

    # chaos, ladder ON: same schedule + degradation under overload
    on_eng, on_handles, on_shed, _ = _run_chaos_arm(
        model, params, extra, requests, ladder_cfg, max_new,
        params_for=params_for,
    )
    on_snap = on_eng.metrics.snapshot()
    goodput_on = on_snap.get("serve/goodput_tokens_per_s", 0.0)
    ladder_stats = on_eng.statusz()["health"].get("ladder", {})
    on_leaks = _zero_leak_fields(on_eng)

    # armed-but-quiet plan vs None: the hook overhead (ABBA-paired)
    quiet = (dict(site="decode", kind="stall", visit=1_000_000_000,
                  stall_s=0.001),)
    quiet_cfg = dataclasses.replace(base_cfg, fault_plan=quiet)
    mk_on, mk_off, _ = _paired_makespans(
        model, params, extra, requests, quiet_cfg, base_cfg, max_new,
        reps=reps,
    )
    armed_rps = n_requests / (sum(mk_on) / len(mk_on))
    plain_rps = n_requests / (sum(mk_off) / len(mk_off))

    if status_hold_s > 0 and probe_eng is not None:
        time.sleep(status_hold_s)
    if probe_eng is not None:
        probe_eng.close()
    admitted = sum(1 for h in off_handles if h is not None)
    return {
        "metric": "serve_chaos_streams_survived",
        "value": len(survivors),
        "unit": (f"streams finished non-error of {admitted} admitted "
                 "under the seeded fault schedule (ladder-off arm)"),
        "vs_baseline": round(len(survivors) / admitted, 4) if admitted
        else 0.0,
        "detail": {
            "config": config,
            "workload": "chaos",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "fault_plan": [dict(s) for s in plan],
            "streams_survived": len(survivors),
            "streams_admitted": admitted,
            "streams_quarantined": errored,
            "streams_shed_off_arm": off_shed,
            "survivors_token_exact": exact,
            "faults_injected": int(
                off_snap.get("serve/fault_injected", 0)),
            "fault_retries": int(off_snap.get("serve/fault_retries", 0)),
            "fault_recovery_s": round(
                off_snap.get("serve/fault_recovery_s", 0.0), 4),
            "watchdog_stalls": int(
                off_snap.get("serve/watchdog_stalls", 0)),
            # the injected journal_write io_error must have degraded
            # the journal WITHOUT taking any stream down (streams_
            # survived above counts through the same arm)
            "journal_degraded_exercised": bool(off_eng._journal_degraded),
            **leak_fields,
            "ladder_zero_leak": on_leaks["zero_leak"],
            "goodput_ladder_on": round(goodput_on, 2),
            "goodput_ladder_off": round(goodput_off, 2),
            "goodput_ladder_ratio": round(goodput_on / goodput_off, 4)
            if goodput_off else None,
            "ladder_max_shed": on_shed,
            "ladder_rung_final": ladder_stats.get("rung"),
            "ladder_transitions": ladder_stats.get("transitions"),
            "fault_overhead_pct": round(
                (1.0 - armed_rps / plain_rps) * 100.0, 2),
            "armed_requests_per_sec": round(armed_rps, 2),
            "plain_requests_per_sec": round(plain_rps, 2),
            **_kv_entry_fields(ref_eng),
            **probe_fields,
        },
    }


def _journal_params_for(i: int) -> SamplingParams | None:
    """The kill-and-recover arm's per-request sampling cycle: greedy
    plus two SEEDED stochastic shapes — every stream is replayable
    (seeded chains fold only (seed, sample index)), so the recovered-vs-
    uninterrupted comparison covers stochastic sampling, not just
    argmax."""
    if i % 3 == 1:
        return SamplingParams(temperature=0.8, top_p=0.9, seed=1000 + i)
    if i % 3 == 2:
        return SamplingParams(temperature=1.2, top_k=8, seed=2000 + i)
    return None


def run_journal_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    seed: int = 0,
    reps: int = 4,
    kill_step: int | None = None,
    journal_dir: str | None = None,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """`cli serve-bench --journal`: the durability workload.

    Two arms, one entry:

    * overhead — ABBA-paired journal-on vs journal-off req/s on the
      Poisson trace (`journal_overhead_pct`; budget <= 2%%: records are
      buffered writes, fsync is batched ONCE per engine step).
    * kill-and-recover — every request submitted up front through a
      journaled engine; the engine is ABANDONED mid-decode (after a
      third of the requests finish, or at `kill_step`), a FRESH engine
      opens the same journal, `recover()` requeues the live set, and
      the drain completes every stream. `recovered_token_exact` pins
      every stream — finished-before-kill AND recovered — byte-
      identical to an uninterrupted reference run (greedy + seeded
      stochastic mix); `recovery_wall_s` is engine-construction ->
      last recovered finish; `zero_leak` holds after the drain.
    """
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    max_len = -(-(max_prompt + max_new) // 16) * 16
    jdir = journal_dir or tempfile.mkdtemp(prefix="serve_journal_bench_")
    base_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_len,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests),
        seed=seed,
    )
    jcfg = dataclasses.replace(
        base_cfg, journal_path=os.path.join(jdir, "overhead.jsonl")
    )

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, base_cfg, max_new,
        status_port=status_port,
    )

    # reference arm FIRST: the uninterrupted token oracle for the
    # kill-and-recover comparison, and — like the chaos bench's — the
    # plain-path jit warmup (the observatory probe populates only the
    # registry's AOT executables, so without this the paired arm's
    # first run would eat the cold compile and bias whichever side
    # drew it). All requests up front: recovery exactness is
    # per-request and independent of arrival timing.
    upfront = [(0.0, p) for _, p in requests]
    ref_eng, ref_handles, _ = _run_engine_arm(
        model, params, extra, upfront, base_cfg, max_new,
        params_for=_journal_params_for,
    )

    # ---- overhead arm: journal-on vs journal-off, ABBA + mean
    mk_on, mk_off, on_eng = _paired_makespans(
        model, params, extra, requests, jcfg, base_cfg, max_new,
        reps=reps,
    )
    on_rps = n_requests / (sum(mk_on) / len(mk_on))
    off_rps = n_requests / (sum(mk_off) / len(mk_off))
    jstats = on_eng.journal.stats()

    # ---- kill-and-recover arm
    kcfg = dataclasses.replace(
        base_cfg, journal_path=os.path.join(jdir, "recover.jsonl")
    )
    eng_a = ServeEngine(model, params, kcfg, extra_variables=extra)
    handles = [
        eng_a.submit(p, max_new_tokens=max_new,
                     params=_journal_params_for(i))
        for i, (_, p) in enumerate(requests)
    ]
    finish_target = max(1, n_requests // 3)
    steps = 0
    while eng_a.has_work():
        eng_a.step()
        steps += 1
        done = sum(1 for h in handles if h.done)
        if kill_step is not None:
            if steps >= kill_step:
                break
        elif done >= finish_target and done < n_requests:
            break
    finished_before = sum(1 for h in handles if h.done)
    live_at_kill = n_requests - finished_before
    # ABANDON eng_a (the in-process stand-in for a SIGKILL: no close,
    # no drain — only what the journal already flushed survives; the
    # CI crash-recovery smoke does the real SIGKILL through cli serve)
    del eng_a

    t0 = time.monotonic()
    eng_b = ServeEngine(model, params, kcfg, extra_variables=extra)
    resumed = eng_b.recover()
    eng_b.run()
    recovery_wall_s = time.monotonic() - t0
    assert all(r.done for r in resumed), "recovery drained unfinished"
    by_rid = {r.trace_id: r for r in resumed}
    exact = True
    for h, r in zip(handles, ref_handles):
        stream = (by_rid[h.trace_id].tokens if h.trace_id in by_rid
                  else h.tokens)
        if stream != r.tokens:
            exact = False
            break
    leak_fields = _zero_leak_fields(eng_b)

    if status_hold_s > 0 and probe_eng is not None:
        time.sleep(status_hold_s)
    if probe_eng is not None:
        probe_eng.close()
    return {
        "metric": "serve_journal_recovered_requests",
        "value": len(resumed),
        "unit": (f"in-flight requests recovered token-exactly after a "
                 f"mid-decode kill ({live_at_kill} live at kill)"),
        "vs_baseline": round(len(resumed) / live_at_kill, 4)
        if live_at_kill else 1.0,
        "detail": {
            "config": config,
            "workload": "journal",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "journal_overhead_pct": round(
                (1.0 - on_rps / off_rps) * 100.0, 2),
            "journal_on_requests_per_sec": round(on_rps, 2),
            "journal_off_requests_per_sec": round(off_rps, 2),
            "journal_records": jstats["records"],
            "journal_bytes": jstats["bytes_written"],
            "journal_fsyncs": jstats["fsyncs"],
            "journal_fsync_s": jstats["fsync_s"],
            "journal_rotations": jstats["rotations"],
            "kill_after_steps": steps,
            "finished_before_kill": finished_before,
            "live_at_kill": live_at_kill,
            "recovered_requests": len(resumed),
            "recovery_wall_s": round(recovery_wall_s, 4),
            "recovered_token_exact": exact,
            **leak_fields,
            **_kv_entry_fields(ref_eng),
            **probe_fields,
        },
    }


def run_replay_bench(
    config: str = "gpt_tiny_long",
    n_requests: int = 24,
    n_slots: int = 8,
    max_new: int = 48,
    decode_block: int = 8,
    prompt_lens=(16, 32, 48, 64),
    train_steps: int = 200,
    seed: int = 0,
    page_size: int = 16,
    kv_quant_block: int = 16,
    cut_stride: int = 8,
    journal_dir: str | None = None,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
) -> dict:
    """`cli serve-bench --replay`: the replay observatory's own gate.

    Journals a seeded workload (greedy + two seeded stochastic shapes,
    `_journal_params_for` — every stream byte-replayable), then replays
    it through `serve.replay.ReplayHarness` three ways:

    1. IDENTICAL config, lane pool — `byte_exact_rate` must be 1.0
       (same params, same seed chains, same pool: any flip is a replay
       or determinism bug). `replay_byte_exact` folds this arm AND the
       paged arm into the never-flip bool CI asserts.
    2. IDENTICAL config, paged pool — the same journal-record-replay
       discipline on the paged engine's own journal.
    3. INT8-KV candidate from the lane journal — the config-canary
       direction: byte exactness is EXPECTED to break (that is what
       the canary detects, `quant_byte_exact_rate` discloses how
       fast via `replay_first_divergence_p50`) while the teacher-
       forced GREEDY `replay_agreement_rate` grades per-step quality
       and is held to the same >= 0.99 band as `run_quant_bench`'s
       `greedy_agreement_rate` gate. Seeded cuts re-draw through the
       pinned seed chain, where int8 perturbation flips sampled
       tokens far more readily (the quant bench's
       `rollout_agreement_rate` analogue, ~0.95-0.98 on this family)
       — disclosed as `replay_agreement_rate_seeded`, never gated.

    Trained model for the same reason as the quant bench: agreement
    under perturbation on random init measures argmax tie-breaking
    over near-uniform logits, not replay quality (`train_steps`
    discloses it; 0 = random init)."""
    from solvingpapers_tpu.data.synthetic import synthetic_text
    from solvingpapers_tpu.serve.replay import ReplayHarness

    model, params, extra, vocab = build_serve_model(config)
    text = synthetic_text(n_chars=80000, seed=seed)
    ids = np.frombuffer(text.encode("ascii", "replace"),
                        np.uint8).astype(np.int32) % vocab
    if train_steps > 0:
        params = _train_bench_model(model, ids, train_steps, seed=seed)
    # corpus-slice prompts, all submitted upfront: replay exactness is
    # per-request and independent of arrival timing (the paced mode is
    # exercised by the latency-delta surface, not this gate)
    rng = np.random.default_rng(seed)
    requests = []
    for i in range(n_requests):
        length = prompt_lens[i % len(prompt_lens)]
        start = int(rng.integers(0, ids.size - length))
        requests.append((0.0, ids[start:start + length]))
    max_prompt = max(len(p) for _, p in requests)
    grain = math.lcm(page_size, kv_quant_block)
    max_len = -(-(max_prompt + max_new) // grain) * grain
    limit = getattr(model, "max_positions", None)
    if limit is not None and max_len > limit:
        max_len = limit // grain * grain
    jdir = journal_dir or tempfile.mkdtemp(prefix="serve_replay_bench_")
    base = dict(
        n_slots=n_slots, max_len=max_len, decode_block=decode_block,
        bucket=min(32, max_prompt), max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests), seed=seed,
    )
    lane_rec_cfg = ServeConfig(
        **base, journal_path=os.path.join(jdir, "lane.jsonl"))
    paged_rec_cfg = ServeConfig(
        **base, paged=True, page_size=page_size,
        journal_path=os.path.join(jdir, "paged.jsonl"))

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, ServeConfig(**base), max_new,
        status_port=status_port,
    )

    # ---- record: journaled lane + paged runs --------------------------
    lane_eng, _, _ = _run_engine_arm(
        model, params, extra, requests, lane_rec_cfg, max_new,
        params_for=_journal_params_for)
    lane_eng.journal.sync()
    jstats = lane_eng.journal.stats()
    leak_fields = _zero_leak_fields(lane_eng)
    kv_fields = _kv_entry_fields(lane_eng)
    lane_eng.close()
    paged_eng, _, _ = _run_engine_arm(
        model, params, extra, requests, paged_rec_cfg, max_new,
        params_for=_journal_params_for)
    paged_eng.journal.sync()
    paged_eng.close()

    # ---- replay: identical lane, identical paged, int8 candidate -----
    harness = ReplayHarness(model, params, extra_variables=extra)
    lane_entries = harness.load(lane_rec_cfg.journal_path)
    paged_entries = harness.load(paged_rec_cfg.journal_path)
    t0 = time.monotonic()
    lane_report = harness.run(
        lane_entries, ServeConfig(**base), cut_stride=cut_stride,
        journal_path=lane_rec_cfg.journal_path)
    paged_report = harness.run(
        paged_entries, ServeConfig(**base, paged=True,
                                   page_size=page_size),
        cut_stride=0,  # agreement is the lane arms' story; this one
        # pins byte exactness on the second pool layout
        journal_path=paged_rec_cfg.journal_path)
    quant_report = harness.run(
        lane_entries,
        ServeConfig(**base, kv_quant="int8",
                    kv_quant_block=kv_quant_block),
        cut_stride=cut_stride,
        journal_path=lane_rec_cfg.journal_path)
    replay_wall_s = time.monotonic() - t0

    byte_exact = (lane_report["byte_exact_rate"] == 1.0
                  and paged_report["byte_exact_rate"] == 1.0)
    agreement = quant_report["agreement_rate_greedy"]

    if status_hold_s > 0 and probe_eng is not None:
        time.sleep(status_hold_s)
    if probe_eng is not None:
        probe_eng.close()
    return {
        "metric": "serve_replay_agreement_rate",
        "value": round(float(agreement), 4),
        "unit": ("teacher-forced greedy agreement of the int8-kv "
                 "candidate replayed from the lane journal (identical-"
                 "config replays must be byte-exact on both pools)"),
        "vs_baseline": round(float(agreement) / 0.99, 4),
        "detail": {
            "config": config,
            "workload": "replay",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "train_steps": train_steps,
            "cut_stride": cut_stride,
            "replay_byte_exact": byte_exact,
            "replay_byte_exact_rate_lane": lane_report["byte_exact_rate"],
            "replay_byte_exact_rate_paged":
                paged_report["byte_exact_rate"],
            "replay_agreement_rate": round(float(agreement), 4),
            "replay_agreement_rate_seeded":
                quant_report["agreement_rate_seeded"],
            "replay_agreement_rate_all": quant_report["agreement_rate"],
            "identical_agreement_rate": lane_report["agreement_rate"],
            "quant_byte_exact_rate": quant_report["byte_exact_rate"],
            "replay_first_divergence_p50":
                quant_report["first_divergence_p50"],
            "replay_streams_compared": lane_report["streams_compared"],
            "replay_streams_skipped": len(lane_report["skipped"]),
            "replay_cut_positions": quant_report["cut_positions"],
            "replay_wall_s": round(replay_wall_s, 4),
            "journal_records": jstats["records"],
            "journal_bytes": jstats["bytes_written"],
            "journal_rotations": jstats["rotations"],
            **leak_fields,
            **kv_fields,
            **probe_fields,
        },
    }


def _run_fleet_arm(model, params, extra, requests, serve_cfg, max_new,
                   n_replicas, params_for=None, journal_dir=None):
    """The Poisson trace through a manually-stepped `FleetRouter`:
    submissions route through `router.submit` (the full ranking —
    health gate, burn gate, prefix probe under each replica's lock,
    least-loaded sort), steps run inline under each replica's loop
    lock. Manual stepping keeps the arm single-threaded like
    `_run_engine_arm`, so a fleet-vs-bare pairing isolates the ROUTER
    tax (ranking + lock traffic), not thread-scheduler noise. Returns
    ``(router, handles, makespan)``."""
    from solvingpapers_tpu.serve.fleet import FleetRouter

    engines = []
    for i in range(n_replicas):
        cfg = serve_cfg
        if journal_dir is not None:
            cfg = dataclasses.replace(
                serve_cfg,
                journal_path=os.path.join(journal_dir, f"r{i}.jsonl"),
            )
        engines.append(
            ServeEngine(model, params, cfg, extra_variables=extra))
    router = FleetRouter(engines, start=False)
    pending = sorted(requests, key=lambda r: r[0])
    handles = []
    t0 = time.monotonic()
    i = 0
    while i < len(pending) or any(
            r.engine.has_work() for r in router.replicas):
        elapsed = time.monotonic() - t0
        while i < len(pending) and pending[i][0] <= elapsed:
            _, req = router.submit(
                pending[i][1], max_new_tokens=max_new,
                params=params_for(i) if params_for is not None else None,
            )
            assert req is not None and req.state != "rejected", \
                "fleet arm sized to admit everything"
            handles.append(req)
            i += 1
        stepped = False
        for r in router.replicas:
            if r.engine.has_work():
                with r.loop.lock:
                    r.engine.step()
                stepped = True
        if not stepped and i < len(pending):
            time.sleep(max(0.0, pending[i][0] - (time.monotonic() - t0)))
    makespan = (time.monotonic() - t0) - pending[0][0]
    return router, handles, makespan


def _migrated_trail_fields(handles, successors) -> dict:
    """Check the fleet trail invariant on every migrated stream: phase
    walls re-derived from the request stamps — route + queue + prefill
    + decode on the drained replica, the migration hop, then peer
    queue/prefill/decode — each clamped non-negative exactly like the
    API's `/v1/requests/<id>` assembler, must PARTITION the
    route-start -> peer-finish e2e wall. With the router and every
    engine stamping from the same `metrics.now` clock no clamp ever
    fires and the error is zero; cross-replica clock skew or misordered
    migration stamps surface here as nonzero ``trail_partition_err_pct``
    (the acceptance budget is 5, matching the CI smoke's HTTP-side
    check)."""
    worst = 0.0
    n = 0
    for h in handles:
        succ = successors.get(h.trace_id)
        if succ is None:
            continue
        n += 1
        route_s = max(getattr(h, "fleet_route_s", 0.0) or 0.0, 0.0)
        phases = [route_s]
        for r in (h, succ):
            admit = (r.admit_time if r.admit_time is not None
                     else r.submit_time)
            first = (r.first_token_time if r.first_token_time is not None
                     else r.finish_time)
            phases.append(max(admit - r.submit_time, 0.0))
            phases.append(max(first - admit, 0.0))
            phases.append(max(r.finish_time - first, 0.0))
        phases.append(max(succ.submit_time - h.finish_time, 0.0))
        e2e = max(succ.finish_time - h.submit_time + route_s, 1e-9)
        worst = max(worst, abs(sum(phases) - e2e) / e2e * 100.0)
    return {
        "trail_partition_ok": n > 0 and worst <= 5.0,
        "trail_partition_err_pct": round(worst, 3),
        "trail_partition_streams": n,
    }


def run_fleet_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    n_replicas: int = 2,
    seed: int = 0,
    reps: int = 4,
    journal_dir: str | None = None,
    status_port: int | None = None,
    status_hold_s: float = 0.0,
    trace_out: str | None = None,
) -> dict:
    """`cli serve-bench --fleet`: the fleet-serving workload.

    Three arms, one entry:

    * router overhead — ABBA-paired req/s of the Poisson trace through
      a ONE-replica `FleetRouter` (manually stepped, no journal) vs the
      bare `_run_engine_arm` driver on an identical engine: the pure
      routing tax (candidate ranking, the locked prefix probe, owner
      bookkeeping, per-step lock traffic) with the engine workload held
      exactly like-for-like (`router_overhead_pct`; budget <= 5).
    * fleet trace overhead — the same pairing with a ONE-replica fleet
      on BOTH sides, tracing on vs off: the whole fabric's tax (router
      recorder + route-decision spans + per-engine recorders) with the
      routing work held like-for-like (`fleet_trace_overhead_pct`;
      budget <= 2, same as the single-engine flight recorder's).
    * drain migration — every request submitted up front through an
      `n_replicas`-way JOURNALED fleet (greedy + seeded stochastic
      sampling mix); after a third of the requests finish, replica r0
      is drained MID-DECODE: its live streams snapshot out of its
      journal, force-finish ``"migrated"`` (r0 reclaims to zero leaks),
      and peers adopt them through the recover() preemption-resume
      path. The fleet then drains to completion.
      ``migrated_token_exact`` pins every migrated stream's FULL token
      sequence (pre-drain prefix + post-adoption suffix) byte-identical
      to an uninterrupted single-engine reference;
      ``fleet_token_exact`` extends that to EVERY stream in the fleet
      (routed anywhere, migrated or not); ``migration_wall_s`` is the
      admission-gate close -> last adoption wall; ``zero_leak`` holds
      on BOTH the drained replica and the adopter after the drain.
      The drain fleet runs TRACED, so with `trace_out` set the stitched
      fleet trace (router + every replica, one Perfetto process each)
      is exported for `cli trace-summary --fleet`; and every migrated
      stream's trail is re-derived from its request stamps and checked
      against the fleet trail invariant — phase walls partition the
      route-start -> peer-finish e2e wall (``trail_partition_ok``,
      worst ``trail_partition_err_pct`` <= 5).
    """
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    max_len = -(-(max_prompt + max_new) // 16) * 16
    jdir = journal_dir or tempfile.mkdtemp(prefix="serve_fleet_bench_")
    base_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_len,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        max_prefills_per_step=n_slots,
        max_waiting=max(256, n_requests),
        seed=seed,
    )

    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    probe_fields, probe_eng = _obs_probe(
        model, params, extra, warm, base_cfg, max_new,
        status_port=status_port,
    )

    # reference arm FIRST: the uninterrupted single-engine token oracle
    # for BOTH exactness claims, and the plain-path jit warmup (greedy
    # and both seeded sampling shapes trace here, so neither paired arm
    # eats a cold compile). All requests up front: per-stream decode is
    # batch-composition-independent, so the oracle is arrival-agnostic.
    upfront = [(0.0, p) for _, p in requests]
    ref_eng, ref_handles, _ = _run_engine_arm(
        model, params, extra, upfront, base_cfg, max_new,
        params_for=_journal_params_for,
    )

    # ---- router overhead arm: 1-replica fleet vs bare driver, ABBA +
    # mean (the `_paired_makespans` discipline; fresh engines per run)
    mk_fleet: list = []
    mk_bare: list = []
    for rep_i in range(reps):
        order = ("fleet", "bare") if rep_i % 2 == 0 else ("bare", "fleet")
        for arm in order:
            if arm == "fleet":
                _, _, mk = _run_fleet_arm(
                    model, params, extra, requests, base_cfg, max_new,
                    n_replicas=1,
                )
                mk_fleet.append(mk)
            else:
                _, _, mk = _run_engine_arm(
                    model, params, extra, requests, base_cfg, max_new,
                )
                mk_bare.append(mk)
    fleet_rps = n_requests / (sum(mk_fleet) / len(mk_fleet))
    bare_rps = n_requests / (sum(mk_bare) / len(mk_bare))

    # ---- fleet trace overhead arm: traced vs untraced 1-replica fleet,
    # same ABBA discipline — both sides pay the router, so the delta is
    # the fabric alone (router recorder + route spans + engine recorders)
    tcfg = dataclasses.replace(base_cfg, trace=True)
    mk_traced: list = []
    mk_plain: list = []
    for rep_i in range(reps):
        order = (("traced", "plain") if rep_i % 2 == 0
                 else ("plain", "traced"))
        for arm in order:
            _, _, mk = _run_fleet_arm(
                model, params, extra, requests,
                tcfg if arm == "traced" else base_cfg, max_new,
                n_replicas=1,
            )
            (mk_traced if arm == "traced" else mk_plain).append(mk)
    traced_rps = n_requests / (sum(mk_traced) / len(mk_traced))
    plain_rps = n_requests / (sum(mk_plain) / len(mk_plain))

    # ---- drain-migration arm: journaled n_replicas-way fleet, TRACED
    # (the stitched-export + trail-invariant surface under test)
    from solvingpapers_tpu.serve.fleet import FleetRouter

    engines = [
        ServeEngine(
            model, params,
            dataclasses.replace(
                base_cfg, trace=True,
                journal_path=os.path.join(jdir, f"migrate_r{i}.jsonl")),
            extra_variables=extra,
        )
        for i in range(max(2, n_replicas))
    ]
    router = FleetRouter(engines, start=False)
    handles = []
    for i, (_, p) in enumerate(requests):
        _, req = router.submit(p, max_new_tokens=max_new,
                               params=_journal_params_for(i))
        assert req is not None and req.state != "rejected"
        handles.append(req)

    def _step_all():
        worked = False
        for r in router.replicas:
            if r.engine.has_work():
                with r.loop.lock:
                    r.engine.step()
                worked = True
        return worked

    finish_target = max(1, n_requests // 3)
    while _step_all():
        done = sum(1 for h in handles if h.done)
        if done >= finish_target and done < n_requests:
            break
    report = router.drain("r0")
    while _step_all():
        pass
    assert all(r.done for r in report.migrated), \
        "drain left adopted streams unfinished"

    ref_by_idx = {h.trace_id: r.tokens
                  for h, r in zip(handles, ref_handles)}
    successors = {
        old: router.replica(peer).engine._recovered[new]
        for old, (peer, new) in report.targets.items()
    }
    fleet_exact = True
    migrated_exact = True
    for h in handles:
        oracle = ref_by_idx[h.trace_id]
        if h.trace_id in successors:
            stream = successors[h.trace_id].tokens
            if stream != oracle:
                migrated_exact = False
        else:
            stream = h.tokens
        if stream != oracle:
            fleet_exact = False
    leak0 = _zero_leak_fields(router.replica("r0").engine)
    leak_peers = [_zero_leak_fields(r.engine)
                  for r in router.replicas if r.rid != "r0"]
    trail_fields = _migrated_trail_fields(handles, successors)
    trace_fields = {}
    if trace_out:
        router.export_chrome_fleet(trace_out)
        trace_fields["fleet_trace_out"] = trace_out

    if status_hold_s > 0 and probe_eng is not None:
        time.sleep(status_hold_s)
    if probe_eng is not None:
        probe_eng.close()
    live_at_drain = report.entries
    return {
        "metric": "serve_fleet_migrated_streams",
        "value": len(report.migrated),
        "unit": (f"live streams migrated token-exactly by a mid-decode "
                 f"drain ({live_at_drain} live at drain, "
                 f"{len(router.replicas)} replicas)"),
        "vs_baseline": round(len(report.migrated) / live_at_drain, 4)
        if live_at_drain else 1.0,
        "detail": {
            "config": config,
            "workload": "fleet",
            "n_requests": n_requests,
            "n_slots": n_slots,
            "n_replicas": len(router.replicas),
            "max_new_tokens": max_new,
            "decode_block": decode_block,
            "prompt_lens": list(prompt_lens),
            "mean_interarrival_s": mean_interarrival_s,
            "reps": reps,
            "router_overhead_pct": round(
                (1.0 - fleet_rps / bare_rps) * 100.0, 2),
            "fleet_requests_per_sec": round(fleet_rps, 2),
            "bare_requests_per_sec": round(bare_rps, 2),
            "fleet_trace_overhead_pct": round(
                (1.0 - traced_rps / plain_rps) * 100.0, 2),
            "fleet_traced_requests_per_sec": round(traced_rps, 2),
            "fleet_untraced_requests_per_sec": round(plain_rps, 2),
            "live_at_drain": live_at_drain,
            "migrated_streams": len(report.migrated),
            "migration_errors": len(report.errors),
            "migration_wall_s": round(report.wall_s, 4),
            "migrated_token_exact": migrated_exact,
            "fleet_token_exact": fleet_exact,
            "zero_leak_drained": leak0["zero_leak"],
            "zero_leak_peers": all(f["zero_leak"] for f in leak_peers),
            "zero_leak": (leak0["zero_leak"]
                          and all(f["zero_leak"] for f in leak_peers)),
            "routing": {k: v for k, v in router.stats.items()},
            **trail_fields,
            **trace_fields,
            **_kv_entry_fields(ref_eng),
            **probe_fields,
        },
    }
