"""Serving benchmark: continuous batching vs sequential one-shot generate.

Synthetic open-loop workload: request arrivals are a Poisson process
(exponential interarrivals, seeded), prompts are slices of the
deterministic synthetic corpus (`data/synthetic.synthetic_text`) encoded
to model token ids. Two arms replay the SAME arrival offsets:

* engine — one `ServeEngine`; the driver submits each request when the
  wall clock passes its arrival offset and keeps calling `step()`.
* sequential — the status quo ante: per-request one-shot
  `infer.generate` (batch 1), each request starting at
  ``max(previous finish, its arrival)``.

Both arms are warmed first (every compiled shape traced before timing)
so the comparison is steady-state serving throughput, not tracing time.
Requests/s = n_requests / (last finish - first arrival).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from solvingpapers_tpu import ops
from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine

_DECODER_FAMILIES = ("gpt", "llama3", "gemma", "deepseekv3")


def build_serve_model(config_name: str):
    """(model, params, extra_variables, vocab_size) for a registered
    decoder config — the serve-side analogue of `cli.cmd_sample`'s setup,
    minus data/tokenizer plumbing (the bench feeds raw token ids)."""
    import dataclasses

    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_model

    cfg = get_config(config_name)
    if cfg.model_family not in _DECODER_FAMILIES:
        raise ValueError(
            f"config {config_name!r} is family {cfg.model_family!r}; "
            f"serve-bench needs a decoder family {_DECODER_FAMILIES}"
        )
    if cfg.train.pipeline_parallel:
        raise ValueError(
            "pipeline-parallel configs have stage-stacked params; export "
            "to the dense family before serving"
        )
    if getattr(cfg.model, "context_parallel", False):
        # params are replicated at rest: serve through the dense twin,
        # exactly like cmd_sample's single-chip path
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, context_parallel=False)
        )
    model = build_model(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.key(0)}, toks)
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    return model, params, extra or None, cfg.model.vocab_size


def synthetic_requests(
    n: int,
    vocab_size: int,
    prompt_lens=(8, 16, 24, 32),
    mean_interarrival_s: float = 0.002,
    seed: int = 0,
):
    """[(arrival_offset_s, prompt ids)] — Poisson arrivals, corpus prompts.

    Prompt lengths cycle through a small fixed set so both arms compile a
    bounded number of shapes (the sequential arm retraces `generate` per
    distinct prompt length).
    """
    from solvingpapers_tpu.data.synthetic import synthetic_text

    rng = np.random.default_rng(seed)
    text = synthetic_text(n_chars=max(4096, n * max(prompt_lens) * 2),
                          seed=seed)
    corpus = np.frombuffer(text.encode("ascii", "replace"), np.uint8)
    ids = corpus.astype(np.int32) % vocab_size
    arrivals = np.cumsum(rng.exponential(mean_interarrival_s, size=n))
    out = []
    for i in range(n):
        length = prompt_lens[i % len(prompt_lens)]
        start = int(rng.integers(0, ids.size - length))
        out.append((float(arrivals[i]), ids[start:start + length]))
    return out


def _run_engine_arm(model, params, extra, requests, serve_cfg, max_new):
    eng = ServeEngine(model, params, serve_cfg, extra_variables=extra)
    pending = sorted(requests, key=lambda r: r[0])
    handles = []
    t0 = time.monotonic()
    i = 0
    while i < len(pending) or eng.has_work():
        elapsed = time.monotonic() - t0
        while i < len(pending) and pending[i][0] <= elapsed:
            handles.append(eng.submit(pending[i][1], max_new_tokens=max_new))
            i += 1
        if eng.has_work():
            eng.step()
        elif i < len(pending):
            # engine idle before the next arrival: busy-wait is pointless
            # on a bench box, sleep the remaining gap
            time.sleep(max(0.0, pending[i][0] - (time.monotonic() - t0)))
    makespan = (time.monotonic() - t0) - pending[0][0]
    assert all(h.done for h in handles), "engine drained with unfinished work"
    return eng, handles, makespan


def _run_sequential_arm(model, params, extra, requests, max_new):
    """Per-request one-shot generate at the same arrival offsets."""
    from solvingpapers_tpu.infer import generate

    rng = jax.random.key(0)
    ttfts = []
    cursor = None
    for arrival, prompt in sorted(requests, key=lambda r: r[0]):
        start = arrival if cursor is None else max(cursor, arrival)
        t0 = time.monotonic()
        out = generate(
            model, params, jnp.asarray(prompt)[None, :], rng,
            max_new_tokens=max_new, sampler=ops.sample_greedy,
            extra_variables=extra,
        )
        jax.block_until_ready(out)
        dur = time.monotonic() - t0
        cursor = start + dur
        # one-shot generate emits nothing until the whole batch finishes:
        # first-token latency == completion latency
        ttfts.append(cursor - arrival)
    makespan = cursor - min(a for a, _ in requests)
    return makespan, float(np.mean(ttfts))


def run_serve_bench(
    config: str = "llama3_shakespeare",
    n_requests: int = 32,
    n_slots: int = 8,
    max_new: int = 64,
    decode_block: int = 16,
    prompt_lens=(16, 32, 48, 64),
    mean_interarrival_s: float = 0.001,
    seed: int = 0,
    skip_sequential: bool = False,
) -> dict:
    """Run both arms, return the BENCH-shaped result dict."""
    model, params, extra, vocab = build_serve_model(config)
    requests = synthetic_requests(
        n_requests, vocab, prompt_lens=prompt_lens,
        mean_interarrival_s=mean_interarrival_s, seed=seed,
    )
    max_prompt = max(len(p) for _, p in requests)
    serve_cfg = ServeConfig(
        n_slots=n_slots,
        max_len=max_prompt + max_new,
        decode_block=decode_block,
        bucket=min(32, max_prompt),
        # throughput-oriented: refill the whole pool in one iteration
        # (the default 1-prefill/step decode-priority protects ITL, but
        # under a drain-the-queue workload it leaves slots idle)
        max_prefills_per_step=n_slots,
        # open-loop arrivals can queue every request at once; the bench
        # must never shed load or the drained-handles assert trips
        max_waiting=max(256, n_requests),
        seed=seed,
    )

    # warm both arms: trace every compiled shape outside the timed window
    # (one request per distinct prompt length covers every prefill bucket
    # and every sequential-arm generate trace; decode is one shape)
    by_len: dict = {}
    for _, p in requests:
        by_len.setdefault(len(p), p)
    warm = [(0.0, p) for p in by_len.values()]
    _run_engine_arm(model, params, extra, warm, serve_cfg, max_new)
    if not skip_sequential:
        _run_sequential_arm(model, params, extra, warm, max_new)

    eng, handles, makespan = _run_engine_arm(
        model, params, extra, requests, serve_cfg, max_new
    )
    snap = eng.metrics.snapshot()
    rps = n_requests / makespan
    detail = {
        "config": config,
        "n_requests": n_requests,
        "n_slots": n_slots,
        "max_new_tokens": max_new,
        "decode_block": decode_block,
        "prompt_lens": list(prompt_lens),
        "mean_interarrival_s": mean_interarrival_s,
        "engine_requests_per_sec": round(rps, 2),
        "engine_tokens_per_sec": round(snap.get("serve/tokens_per_sec", 0.0), 1),
        "mean_ttft_s": round(snap.get("serve/ttft_s_mean", float("nan")), 4),
        "ttft_p95_s": round(snap.get("serve/ttft_s_p95", float("nan")), 4),
        "itl_p95_s": round(snap.get("serve/itl_s_p95", float("nan")), 5),
        "slot_occupancy": round(snap.get("serve/slot_occupancy", 0.0), 3),
    }
    result = {
        "metric": "serve_requests_per_sec",
        "value": round(rps, 2),
        "unit": "req/s",
        "detail": detail,
    }
    if not skip_sequential:
        seq_makespan, seq_ttft = _run_sequential_arm(
            model, params, extra, requests, max_new
        )
        seq_rps = n_requests / seq_makespan
        detail["sequential_requests_per_sec"] = round(seq_rps, 2)
        detail["sequential_mean_ttft_s"] = round(seq_ttft, 4)
        result["vs_baseline"] = round(rps / seq_rps, 2)
    return result
