"""Grammar-constrained decoding: a host-side incremental JSON stepper.

`response_format: {"type": "json_object"}` requests must emit text that
`json.loads` accepts — guaranteed by construction, not by prompting. The
engine asks this module, once per emitted token, which token ids are
legal next (`JsonStepper.allowed`), packs them into the `(S, sample_cap)`
allow-list that rides the jitted programs' existing packed control
transfers, and `serve.sampling.fused_sample` restricts that slot's draw
(or argmax) to the listed ids — the mask is a TRACED operand, so
constrained and unconstrained slots share the one compiled decode
program (tests/test_grammar.py pins the jit cache size).

The stepper is a character-level pushdown automaton over the JSON
grammar (RFC 8259: objects, arrays, strings with escapes, numbers,
literals), simulated token-by-token: a token id is legal iff feeding its
decoded characters one at a time never leaves the grammar. That makes it
tokenizer-agnostic — single-char vocabs step one grammar transition per
token, merge-y BPE tokens are vetted by simulating their whole string
(a token that completes the document mid-way and then keeps writing is
illegal). Vocab-awareness prevents dead ends: a construct is only ever
OPENED if the vocabulary can CLOSE it (no `[` without `]`, no key
string unless `:` and some value are expressible, no `\\` escape
without a legal continuation), so the allowed set is never empty before
the document completes.

Budget-aware closing: `allowed(budget)` additionally drops any token
whose resulting state could not reach a complete document within the
request's remaining token budget (`min_close`, the pushdown's shortest
completion in characters, is a conservative bound on tokens). As the
budget runs out the mask narrows to closing tokens — `"` then `}`/`]`
— so a constrained greedy stream parses even when the model would
happily keep generating. The document completes at or before the
budget; it is never truncated mid-string.

EOS is never in the allowed set: the only legal end of a constrained
stream is a complete document (`done`), where the engine finishes the
request itself (finish reason "stop"). `submit` rejects a grammar
request that also carries an `eos_id` for exactly this reason.
"""

from __future__ import annotations

import numpy as np

_WS = " \t\n\r"
_DIGITS = "0123456789"
_HEX = "0123456789abcdefABCDEF"
# \u escapes are offered only with full hex coverage; everything a string
# needs is expressible without them, so partial-hex vocabs just skip them
_ESC = '"\\/bfnrt'

# container close cost is 1 char each; these per-mode constants are the
# extra chars to finish the CURRENT token before those closers can run
# (computed in min_close below)


class JsonStepper:
    """Incremental JSON validator + legal-next-token oracle.

    `token_strs` maps token id -> decoded string (`None`/empty entries
    are never legal). `top_object=True` (the json_object contract) pins
    the top-level value to an object. `cache` shares the allowed-set
    memo across steppers built over the SAME token table (the front
    door passes one dict per server): entries are keyed by grammar
    state, so every request after the first reads hot states instead of
    re-simulating the vocabulary. Raises ValueError when the vocabulary
    cannot express even the minimal document ``{}``.
    """

    def __init__(self, token_strs, top_object: bool = True, cache=None):
        self.tokens = [t if t else None for t in token_strs]
        self.top_object = top_object
        avail: set[str] = set()
        for t in self.tokens:
            if t:
                avail.update(t)
        self.avail = avail
        if "{" not in avail or "}" not in avail:
            raise ValueError(
                "tokenizer cannot express a JSON object: no token decodes "
                "to '{' / '}' — json_object mode needs a vocabulary that "
                "covers the JSON structural characters"
            )
        self.has_digit = any(d in avail for d in _DIGITS)
        self.has_str = '"' in avail
        self.has_arr = "[" in avail and "]" in avail
        self.lits = tuple(
            w for w in ("true", "false", "null") if set(w) <= avail
        )
        self.has_esc = any(c in avail for c in _ESC)
        self.has_hex = set("0123456789abcdef") <= {c.lower() for c in avail}
        # minimal complete VALUE in chars: one digit beats '""' / '{}'
        self.min_value = 1 if self.has_digit else 2
        # a key/value pair needs ':' plus an expressible value
        self.has_pair = ":" in avail and (
            self.has_digit or self.has_str or self.has_arr or self.lits
        )
        # mutable state ------------------------------------------------
        self.stack: list[str] = []  # 'obj' / 'arr'
        self.mode = "value"
        self.key = False       # current string is an object key
        self.lit_word = ""
        self.lit_pos = 0
        self.num = ""          # 'sign int0 int dot frac e esign exp'
        self.hex_left = 0
        self._allow_cache: dict = {} if cache is None else cache

    # ---------------------------------------------------------- cloning

    def clone(self) -> "JsonStepper":
        c = object.__new__(JsonStepper)
        c.__dict__.update(self.__dict__)
        c.stack = list(self.stack)
        c._allow_cache = {}  # never share: clones mutate state freely
        return c

    # ------------------------------------------------------------ state

    @property
    def done(self) -> bool:
        return self.mode == "done"

    def _sig(self):
        return (self.mode, self.key, self.lit_word, self.lit_pos,
                self.num, self.hex_left, tuple(self.stack))

    @property
    def min_close(self) -> int:
        """Shortest character count to a complete document from here —
        the pushdown's distance-to-accept, used for budget-aware
        closing (one token emits >= 1 char, so this also bounds the
        TOKEN count conservatively)."""
        cl = len(self.stack)  # one closing char per open container
        m = self.mode
        if m == "done":
            return 0
        if m == "value":
            if self.top_object and not self.stack:
                return 2  # the document must be an object: '{' '}'
            return self.min_value + cl
        if m in ("arr_first", "obj_first", "obj_next", "arr_next"):
            return cl  # the container's own closer is already counted
        if m in ("str", "esc", "str_u"):
            tail = 1 + self.min_value if self.key else 0  # ':' + value
            if m == "str":
                return 1 + tail + cl
            if m == "esc":
                return 1 + 1 + tail + cl
            return self.hex_left + 1 + tail + cl
        if m == "obj_key":
            return 2 + 1 + self.min_value + cl  # '""' ':' value
        if m == "colon":
            return 1 + self.min_value + cl
        if m == "lit":
            return len(self.lit_word) - self.lit_pos + cl
        if m == "num":
            return cl if self._num_complete() else 1 + cl
        raise AssertionError(f"unknown mode {m!r}")

    def _num_complete(self) -> bool:
        return self.num in ("int0", "int", "frac", "exp")

    # ----------------------------------------------------- legal chars

    def _value_starts(self) -> str:
        out = ""
        if self.has_str:
            out += '"'
        out += "{"  # always closable (ctor guarantees '}')
        if self.has_arr:
            out += "["
        if self.has_digit:
            out += _DIGITS
            if "-" in self.avail:
                out += "-"
        out += "".join(w[0] for w in self.lits)
        return out

    def _legal(self) -> str:
        """Every character legal next (before budget filtering)."""
        m = self.mode
        if m == "done":
            return ""
        if m == "value":
            if self.top_object and not self.stack:
                return "{"  # json_object: the document IS an object
            return _WS + self._value_starts()
        if m == "arr_first":
            return _WS + self._value_starts() + "]"
        if m == "obj_first":
            return _WS + ('"}' if self.has_pair else "}")
        if m == "obj_key":
            return _WS + '"'
        if m == "colon":
            return _WS + ":"
        if m == "obj_next":
            return _WS + (',}' if self.has_pair else "}")
        if m == "arr_next":
            return _WS + ",]"
        if m == "str":
            out = '"'
            if self.has_esc:
                out += "\\"
            # any non-control char except the two specials is content
            content = "".join(
                c for c in self.avail
                if ord(c) >= 0x20 and c not in '"\\'
            )
            return out + content
        if m == "esc":
            return _ESC + ("u" if self.has_hex else "")
        if m == "str_u":
            return _HEX
        if m == "lit":
            return self.lit_word[self.lit_pos]
        if m == "num":
            n = self.num
            delims = _WS + (",}" if self.stack and self.stack[-1] == "obj"
                            else ",]" if self.stack else "")
            if n == "sign":
                return _DIGITS
            if n == "int0":
                return ".eE" + delims
            if n == "int":
                return _DIGITS + ".eE" + delims
            if n == "dot":
                return _DIGITS
            if n == "frac":
                return _DIGITS + "eE" + delims
            if n == "e":
                return "+-" + _DIGITS
            if n == "esign":
                return _DIGITS
            if n == "exp":
                return _DIGITS + delims
        raise AssertionError(f"unknown mode {m!r}")

    # ---------------------------------------------------------- feeding

    def _end_value(self) -> None:
        """A value just completed: return to the enclosing container's
        separator state, or accept at the top level."""
        if not self.stack:
            self.mode = "done"
        elif self.stack[-1] == "obj":
            self.mode = "obj_next"
        else:
            self.mode = "arr_next"

    def feed(self, ch: str) -> None:
        """Advance by one character; ValueError if `ch` is not legal."""
        if ch not in self._legal():
            raise ValueError(
                f"char {ch!r} is not legal in grammar state "
                f"{self.mode!r} (stack {self.stack})"
            )
        m = self.mode
        if m == "num" and ch in _WS + ",}]":
            # a complete number ends implicitly at its delimiter: close
            # the value, then re-dispatch the delimiter (whitespace is
            # just a separator — consumed, nothing to re-dispatch)
            self._end_value()
            if ch not in _WS:
                self.feed(ch)
            return
        if ch in _WS and m != "str" and m != "esc" and m != "str_u" \
                and m != "lit" and m != "num":
            return  # inter-token whitespace: no state change
        if m in ("value", "arr_first"):
            if m == "arr_first" and ch == "]":
                self.stack.pop()
                self._end_value()
                return
            if m == "arr_first":
                self.mode = "value"  # fall through to value dispatch
            self._start_value(ch)
            return
        if m == "obj_first":
            if ch == "}":
                self.stack.pop()
                self._end_value()
            else:  # '"'
                self.mode = "str"
                self.key = True
            return
        if m == "obj_key":
            self.mode = "str"
            self.key = True
            return
        if m == "colon":
            self.mode = "value"
            return
        if m == "obj_next":
            if ch == "}":
                self.stack.pop()
                self._end_value()
            else:
                self.mode = "obj_key"
            return
        if m == "arr_next":
            if ch == "]":
                self.stack.pop()
                self._end_value()
            else:
                self.mode = "value"
            return
        if m == "str":
            if ch == '"':
                if self.key:
                    self.key = False
                    self.mode = "colon"
                else:
                    self._end_value()
            elif ch == "\\":
                self.mode = "esc"
            return
        if m == "esc":
            if ch == "u":
                self.mode = "str_u"
                self.hex_left = 4
            else:
                self.mode = "str"
            return
        if m == "str_u":
            self.hex_left -= 1
            if self.hex_left == 0:
                self.mode = "str"
            return
        if m == "lit":
            self.lit_pos += 1
            if self.lit_pos == len(self.lit_word):
                self._end_value()
            return
        if m == "num":
            self._feed_num(ch)
            return
        raise AssertionError(f"unreachable mode {m!r}")

    def _start_value(self, ch: str) -> None:
        if ch == "{":
            self.stack.append("obj")
            self.mode = "obj_first"
        elif ch == "[":
            self.stack.append("arr")
            self.mode = "arr_first"
        elif ch == '"':
            self.mode = "str"
            self.key = False
        elif ch == "-":
            self.mode = "num"
            self.num = "sign"
        elif ch in _DIGITS:
            self.mode = "num"
            self.num = "int0" if ch == "0" else "int"
        else:  # literal start (t/f/n) — uniqueness by first char
            self.mode = "lit"
            self.lit_word = next(w for w in self.lits if w[0] == ch)
            self.lit_pos = 1

    def _feed_num(self, ch: str) -> None:
        n = self.num
        if n == "sign":
            self.num = "int0" if ch == "0" else "int"
        elif n in ("int0", "int"):
            if ch == ".":
                self.num = "dot"
            elif ch in "eE":
                self.num = "e"
            else:  # digit; int0 never offers digits so this is int
                self.num = "int"
        elif n == "dot":
            self.num = "frac"
        elif n == "frac":
            self.num = "e" if ch in "eE" else "frac"
        elif n == "e":
            self.num = "esign" if ch in "+-" else "exp"
        elif n in ("esign", "exp"):
            self.num = "exp"

    # --------------------------------------------------------- tokens

    def _token_ok(self, s: str, budget: int | None):
        """(legal, min_close_after) — simulate the whole token string."""
        sim = self.clone()
        try:
            for ch in s:
                sim.feed(ch)
        except ValueError:
            return False, 0
        after = sim.min_close
        if budget is not None and after > budget - 1:
            return False, after
        return True, after

    def allowed(self, budget: int | None = None) -> list[int]:
        """Token ids legal next, most-closing first.

        `budget` is the request's remaining TOKEN budget including the
        next draw; a token is dropped when the state it leads to cannot
        complete the document within the rest (`min_close` chars <=
        budget - 1 tokens — conservative, every token is >= 1 char).
        Ordered by (distance-to-accept after the token, id): when the
        engine truncates the list to `sample_cap`, closing/structural
        tokens survive, so a truncated mask still always completes.
        Deterministic, memoized per grammar state."""
        if self.done:
            return []
        mc = self.min_close
        if budget is not None and budget <= mc:
            # too tight to spend this token on anything but the shortest
            # closing path; mc == budget still works (1 char per token)
            budget = mc
        # the filter depends only on (state, budget - mc): min_close
        # deltas are stack-depth-independent, so collapse the key
        key = (self._sig(),
               None if budget is None else min(budget - mc, 1 << 12))
        hit = self._allow_cache.get(key)
        if hit is not None:
            return hit
        scored = []
        for tid, s in enumerate(self.tokens):
            if not s:
                continue
            ok, after = self._token_ok(s, budget)
            if ok:
                scored.append((after, tid))
        scored.sort()
        out = [tid for _, tid in scored]
        self._allow_cache[key] = out
        return out

    def advance(self, token_id: int) -> None:
        """Consume an emitted token (ValueError if it was never legal —
        the engine only ever feeds ids from `allowed`)."""
        s = self.tokens[token_id]
        if not s:
            raise ValueError(f"token {token_id} decodes to nothing")
        for ch in s:
            self.feed(ch)


def encode_allow(ids, cap: int) -> np.ndarray:
    """Pack an allowed-id list into the engine's fixed-width (cap,)
    int32 allow row (-1 padded; over-long lists keep the head, which
    `JsonStepper.allowed`'s most-closing-first order makes safe)."""
    row = np.full(cap, -1, np.int32)
    n = min(len(ids), cap)
    row[:n] = ids[:n]
    return row
