"""Kernel microbench harness: the serving stack's hot inner ops, timed
in isolation (`cli kernel-bench` / tools/bench_kernels.py ->
BENCH_kernels.json).

The serve-bench workloads measure END-TO-END throughput: the paged
pool's 15-38% tax, the int8 pool's ~10% overhead. Those numbers bound
the problem but cannot attribute it — ROADMAP item 1's fused
paged-attention kernel needs to know how much of a decode step the
`gather_lanes` page view costs BY ITSELF, at the bench's exact shapes,
before and after the kernel lands. This module benches each hot op as
its own fenced program, min-of-reps (the `probe_stage_costs`
discipline: the op's cost gates a lockstep step, so the minimum is the
signal and scheduling noise is not):

    gather          the pool -> logical (S, max_len, ...) lane view.
                    paged: `gather_lanes` (f32) / `quant_gather_lanes`
                    (int8 pages + per-page scales dequantized on read);
                    lane: the contiguous pool IS the view and the
                    decode program reads it in place — f32 benches a
                    per-leaf reduction (every byte read, nothing
                    materialized: the honest floor the paged gather's
                    read-AND-materialize is measured against), int8
                    benches `quant_lanes_view` (dequant-on-read).
    scatter         ONE write-back per slot. paged: a single
                    `scatter_written_pages` window (the decode program
                    pays (decode_block-1)//page_size + 2 of these per
                    call — its post-scan write-back loop); lane:
                    a vmapped per-slot one-token `dynamic_update_slice`
                    (`quant_store_written` span=1 on int8 pools).
    quant_roundtrip `quantize_tree` + `dequantize_tree` of the full
                    lane view — the isolated cost of int8 storage
                    (benched on f32 rows too: what the exact pool WOULD
                    pay, the before/after of a kv_quant flip).
    splice          prefix-cache segment traffic. lane: the
                    splice/extract device copies (`_splice_program` /
                    `_extract_program`, quantized twins on int8); paged:
                    `gather_lane` + `scatter_lane_pages` — the per-slot
                    page-window ops a prefix MISS pays (a paged HIT is a
                    host-side refcount append, zero device programs —
                    pinned by splice_programs_dispatched == 0 in the
                    paged bench, so there is nothing to time).
    sample          `fused_sample` on a mixed half-greedy/half-
                    stochastic batch at the engine's (S, vocab) logits
                    shape and sample_cap support.
    spec_verify     the speculative 1+k verify window (`spec_verify`)
                    over (S, k+1, vocab) logits — drafts, rejection
                    sampling, commit counts.

Every op family is benched over the FULL (pool layout x kv_quant) grid
— including combinations the default engine would not pick — because
the decomposition question is comparative: the int8 gather moves a
quarter of the f32 bytes, the lane pool's gather is free, and only the
grid shows both. One BENCH_kernels.json entry per grid cell, JSON-lines
with `bench_provenance`, gated by tools/bench_check.py exactly like
BENCH_serve.json.

`paged_decode_decomposition` is the join the serve benches record: the
microbenched gather/dequant/scatter walls against a MEASURED decode
program wall (the compile registry's fenced run seconds per call),
yielding `gather_share_pct` / `dequant_share_pct` / `scatter_share_pct`
/ `attention_share_pct` — the last is the remainder (model forward:
attention + MLP + sampling), i.e. the compute a fused kernel must keep.
These fields are the honest before-numbers ROADMAP item 1's exit
criteria diff against.
"""

from __future__ import annotations

import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from solvingpapers_tpu.ops.quant import dequantize_tree, quantize_tree
from solvingpapers_tpu.serve.kv_pool import (
    _extract_program,
    _quant_extract_program,
    _quant_splice_program,
    _splice_program,
    gather_lane,
    gather_lanes,
    make_quant_store,
    quant_gather_lane,
    quant_gather_lanes,
    quant_lanes_view,
    quant_scatter_lane_pages,
    quant_scatter_written_pages,
    quant_store_written,
    scatter_lane_pages,
    scatter_written_pages,
)
from solvingpapers_tpu.serve.sampling import (
    PackedSampling,
    fused_sample,
    slot_keys,
)
from solvingpapers_tpu.serve.spec import spec_verify

OP_FAMILIES = ("gather", "scatter", "quant_roundtrip", "splice",
               "sample", "spec_verify")

POOL_LAYOUTS = ("lane", "paged")
KV_QUANTS = (None, "int8")


def _pytree_bytes(tree) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def fenced_wall_s(fn, args, *, reps: int = 5, static_argnums=(),
                  clock=time.monotonic) -> float:
    """Min-of-reps fenced wall seconds of ``jit(fn)(*args)``: compile +
    one warmup outside the timing, then `reps` fenced runs. Min, not
    mean — an isolated op's cost is its floor; the serve benches' ABBA
    pairing handles drift where a MEAN is the right estimator (see
    bench.py `_paired_makespans`), but a microbench wants the op, not
    the box."""
    jitted = jax.jit(fn, static_argnums=static_argnums)
    jax.block_until_ready(jitted(*args))  # compile + warm
    best = math.inf
    for _ in range(max(reps, 1)):
        t0 = clock()
        jax.block_until_ready(jitted(*args))
        best = min(best, clock() - t0)
    return best


def _mixed_packed(n_slots: int) -> PackedSampling:
    """Half-greedy / half-stochastic per-slot knobs — the mixed batch
    the engine's fused sampler actually serves (all-greedy would ride
    the argmax fast path and measure nothing)."""
    half = np.arange(n_slots) % 2 == 0
    return PackedSampling(
        temperature=jnp.where(half, 0.0, 0.8).astype(jnp.float32),
        top_p=jnp.full((n_slots,), 0.95, jnp.float32),
        min_p=jnp.zeros((n_slots,), jnp.float32),
        top_k=jnp.where(half, 0, 40).astype(jnp.int32),
        need_lp=jnp.zeros((n_slots,), jnp.int32),
    )


def _lane_token_write(caches, lanes, pos):
    """The lane pool's decode write site in isolation: each slot writes
    ONE token (its lane-view column at `pos[s]`) back into the
    contiguous pool — a vmapped batch-1 `dynamic_update_slice`, the
    lane counterpart of one `scatter_written_pages` write-back window."""

    def one(cleaf, laneleaf):
        val = jax.vmap(
            lambda lane, p: jax.lax.dynamic_slice_in_dim(lane, p, 1, axis=0)
        )(laneleaf, pos)
        return jax.vmap(
            lambda c, v, p: jax.lax.dynamic_update_slice_in_dim(
                c, v, p, axis=0)
        )(cleaf, val, pos)

    return jax.tree_util.tree_map(one, caches, lanes)


def _write_positions(rng, n_slots: int, max_len: int, page_size: int):
    """Seeded per-slot decode write positions: past the first page when
    the lane is long enough (the steady-state regime), but NEVER an
    empty numpy range — a one-page lane (max_len == page_size) draws
    from [0, max_len - 1) instead of crashing inside rng.integers."""
    lo = min(page_size, max(max_len - 2, 0))
    return jnp.asarray(
        rng.integers(lo, max(max_len - 1, lo + 1), size=n_slots,
                     dtype=np.int32)
    )


def _paged_pool_ops(model, *, n_slots: int, max_len: int, page_size: int,
                    kv_quant: str | None, decode_block: int = 16,
                    seed: int = 0):
    """The paged grid cell's arrays + gather / write-back-window
    scatter / splice closures — ONE construction shared by `build_kernel_ops`
    and `paged_decode_decomposition`, so the BENCH_kernels.json walls
    and the `*_share_pct` decomposition are measured on IDENTICAL op
    shapes (steady-state contiguous page tables, seeded positions).
    `decode_block` bounds the int8 scatter's merge window exactly as
    the engine passes it. Returns ``(ops, pool_tree, lane_view)``."""
    rng = np.random.default_rng(seed)
    ppl = max_len // page_size
    n_pages = n_slots * ppl + 1  # lane-equivalent budget + trash page
    lane_view = model.init_caches(n_slots, max_len)
    # contiguous page-table rows (slot s owns pages [1 + s*ppl, ...)):
    # the steady-state layout after in-order allocation
    table = jnp.asarray(
        1 + np.arange(n_slots * ppl, dtype=np.int32).reshape(n_slots, ppl)
    )
    pos = _write_positions(rng, n_slots, max_len, page_size)
    eidx_row = jnp.zeros((n_slots,), jnp.int32)
    row = table[0]
    if kv_quant is not None:
        store = make_quant_store(model, n_pages, page_size, page_size)
        # the engine's decode write-back ALWAYS bounds the requantized
        # window (engine.py: lo=pos0, hi=pos0+block) — on lossy compute
        # dtypes that selects the old-code merge branch, which is the op
        # the program actually runs; omitting lo/hi here would time the
        # cheaper merge-free variant and understate the scatter wall
        hi = pos + decode_block
        ops = {
            "gather": (
                lambda s, t: quant_gather_lanes(s, t, eidx_row),
                (store, table), (),
            ),
            "scatter": (
                lambda s, ln, t, p: quant_scatter_written_pages(
                    s, ln, t, p, lo=pos, hi=hi),
                (store, lane_view, table, pos), (),
            ),
            "splice": (
                lambda s, r: quant_scatter_lane_pages(
                    s, quant_gather_lane(s, r, 0), r, 0, 0),
                (store, row), (),
            ),
        }
        return ops, store, lane_view
    phys = model.init_caches(n_pages, page_size)
    ops = {
        "gather": (gather_lanes, (phys, table), ()),
        "scatter": (scatter_written_pages,
                    (phys, lane_view, table, pos), ()),
        "splice": (
            lambda ph, r: scatter_lane_pages(ph, gather_lane(ph, r), r, 0),
            (phys, row), (),
        ),
    }
    return ops, phys, lane_view


def build_kernel_ops(model, *, pool: str, kv_quant: str | None,
                     n_slots: int, max_len: int, page_size: int,
                     quant_block: int, vocab: int, sample_cap: int = 64,
                     spec_k: int = 4, decode_block: int = 16,
                     seed: int = 0) -> dict:
    """Build the six op-family closures for one (pool, kv_quant) grid
    cell: {family: (fn, args, static_argnums)}. All inputs are seeded
    device arrays at the cell's exact serving shapes; nothing here runs
    or times anything."""
    if pool not in POOL_LAYOUTS:
        raise ValueError(f"pool must be one of {POOL_LAYOUTS}, got {pool!r}")
    if max_len % page_size or max_len % quant_block:
        raise ValueError(
            f"max_len {max_len} must be a multiple of page_size "
            f"{page_size} and quant_block {quant_block}"
        )
    quant = kv_quant is not None
    rng = np.random.default_rng(seed)
    key = jax.random.key(seed)
    ops: dict = {}

    roundtrip_block = page_size if pool == "paged" else quant_block
    if pool == "paged":
        # `lane_view` (the logical compute-dtype view the decode
        # programs carry) and the seeded positions come from the ONE
        # shared cell construction — nothing re-derived here
        paged_ops, pool_tree, lane_view = _paged_pool_ops(
            model, n_slots=n_slots, max_len=max_len, page_size=page_size,
            kv_quant=kv_quant, decode_block=decode_block, seed=seed,
        )
        ops.update(paged_ops)
    else:
        lane_view = model.init_caches(n_slots, max_len)
        pos = _write_positions(rng, n_slots, max_len, page_size)
        eidx_row = jnp.zeros((n_slots,), jnp.int32)
        if quant:
            store = make_quant_store(model, n_slots, max_len, quant_block)
            ops["gather"] = (
                lambda s: quant_lanes_view(s, eidx_row), (store,), (),
            )
            ops["scatter"] = (
                lambda s, ln, p: quant_store_written(s, ln, p, 1, eidx_row),
                (store, lane_view, pos), (),
            )
            seg_len = max(quant_block,
                          max_len // 2 // quant_block * quant_block)
            ctl = jnp.asarray([0, 0], jnp.int32)
            seg = _quant_extract_program(store, ctl, seg_len)
            ops["splice"] = (
                lambda s, sg, c: _quant_extract_program.__wrapped__(
                    _quant_splice_program.__wrapped__(s, sg, c), c, seg_len),
                (store, seg, ctl), (),
            )
            pool_tree = store
        else:
            caches = model.init_caches(n_slots, max_len)

            # the contiguous pool IS the logical view: the lane decode
            # program reads it IN PLACE, so its "gather" cost is a pure
            # read — benched as a per-leaf reduction (touches every
            # byte, materializes nothing; a jitted identity would
            # measure a full pool COPY the real program never pays)
            def _read_all(c):
                return sum(
                    jnp.sum(leaf.astype(jnp.float32))
                    for leaf in jax.tree_util.tree_leaves(c)
                )

            ops["gather"] = (_read_all, (caches,), ())
            ops["scatter"] = (_lane_token_write,
                              (caches, lane_view, pos), ())
            seg_len = max(page_size, max_len // 2 // page_size * page_size)
            ctl = jnp.asarray([0, 0], jnp.int32)
            seg = _extract_program(caches, ctl, seg_len)
            ops["splice"] = (
                lambda c, sg, t: _extract_program.__wrapped__(
                    _splice_program.__wrapped__(c, sg, t), t, seg_len),
                (caches, seg, ctl), (),
            )
            pool_tree = caches

    view_dtype = jax.tree_util.tree_leaves(lane_view)[0].dtype
    ops["quant_roundtrip"] = (
        lambda v: dequantize_tree(
            *quantize_tree(v, roundtrip_block), view_dtype),
        (lane_view,), (),
    )

    cap = min(sample_cap, vocab)
    packed = _mixed_packed(n_slots)
    logits = jax.random.normal(key, (n_slots, vocab), jnp.float32) * 4.0
    rngs = slot_keys(key, 0, jnp.arange(n_slots, dtype=jnp.int32),
                     jnp.zeros(n_slots, jnp.int32))
    ops["sample"] = (
        lambda lg, pk, rg: fused_sample(lg, pk, rg, cap=cap),
        (logits, packed, rngs), (),
    )

    big_l = spec_k + 1
    spec_logits = jax.random.normal(
        jax.random.fold_in(key, 1), (n_slots, big_l, vocab), jnp.float32
    ) * 4.0
    drafts = jnp.asarray(
        rng.integers(0, vocab, size=(n_slots, spec_k), dtype=np.int32))
    avail = jnp.full((n_slots,), spec_k, jnp.int32)
    keys = jax.random.split(
        jax.random.fold_in(key, 2), n_slots * big_l
    ).reshape(n_slots, big_l)
    ops["spec_verify"] = (
        lambda lg, dr, av, pk, ks: spec_verify(lg, dr, av, pk, ks, cap=cap),
        (spec_logits, drafts, avail, packed, keys), (),
    )

    assert set(ops) == set(OP_FAMILIES), sorted(ops)
    ops["_view_bytes"] = _pytree_bytes(lane_view)
    ops["_pool_bytes"] = _pytree_bytes(pool_tree)
    # the pool's TRUE storage dtype: the unquantized grid rows are
    # labeled "f32" for trajectory-key stability, but a bf16-compute
    # model's exact pool stores bf16 — the entry must say so
    ops["_kv_dtype"] = kv_quant or str(view_dtype)
    return ops


def bench_kernel_cell(model, *, pool: str, kv_quant: str | None,
                      n_slots: int, max_len: int, page_size: int,
                      quant_block: int, vocab: int, sample_cap: int = 64,
                      spec_k: int = 4, decode_block: int = 16,
                      reps: int = 5, seed: int = 0) -> dict:
    """Time every op family for one grid cell: {family: wall seconds}
    plus the view/pool byte facts the entry records."""
    ops = build_kernel_ops(
        model, pool=pool, kv_quant=kv_quant, n_slots=n_slots,
        max_len=max_len, page_size=page_size, quant_block=quant_block,
        vocab=vocab, sample_cap=sample_cap, spec_k=spec_k,
        decode_block=decode_block, seed=seed,
    )
    out = {"_view_bytes": ops.pop("_view_bytes"),
           "_pool_bytes": ops.pop("_pool_bytes"),
           "_kv_dtype": ops.pop("_kv_dtype")}
    for family in OP_FAMILIES:
        fn, args, static = ops[family]
        out[family] = fenced_wall_s(fn, args, reps=reps,
                                    static_argnums=static)
    return out


def run_kernel_bench(
    config: str = "gpt_shakespeare",
    n_slots: int = 8,
    max_len: int = 256,
    page_size: int = 16,
    quant_block: int = 16,
    sample_cap: int = 64,
    spec_k: int = 4,
    decode_block: int = 16,
    reps: int = 5,
    seed: int = 0,
) -> list[dict]:
    """The full grid: one BENCH_kernels.json entry per (pool layout x
    kv_quant) cell, every op family timed at the cell's serving shapes.

    Entry headline (`value`) is the gather bandwidth in GB/s — logical
    lane-view bytes over the gather wall, HIGHER IS BETTER so the
    bench_check trajectory gate points the right way — with every
    family's wall microseconds as `<family>_wall_us` detail fields
    (lower-better, gated at matching scale). `detail.config` encodes the
    shape knobs so bench_check's scale matching never compares two
    different geometries."""
    from solvingpapers_tpu.serve.bench import build_serve_model

    model, _, _, vocab = build_serve_model(config)
    grain = math.lcm(page_size, quant_block)
    max_len = max_len // grain * grain
    limit = getattr(model, "max_positions", None)
    if limit is not None and max_len > limit:
        max_len = limit // grain * grain
    if max_len < grain:
        raise ValueError(
            f"max_len {max_len} cannot fit one page/quant-block grain "
            f"{grain} under the model's position budget"
        )
    shape_tag = (f"{config}@s{n_slots}l{max_len}p{page_size}"
                 f"b{quant_block}c{sample_cap}k{spec_k}")
    entries = []
    for pool in POOL_LAYOUTS:
        for kv_quant in KV_QUANTS:
            cell = bench_kernel_cell(
                model, pool=pool, kv_quant=kv_quant, n_slots=n_slots,
                max_len=max_len, page_size=page_size,
                quant_block=quant_block, vocab=vocab,
                sample_cap=sample_cap, spec_k=spec_k,
                decode_block=decode_block, reps=reps, seed=seed,
            )
            dtype = kv_quant or "f32"
            view_bytes = cell.pop("_view_bytes")
            pool_bytes = cell.pop("_pool_bytes")
            detail = {
                "workload": f"kernels-{pool}-{dtype}",
                "config": shape_tag,
                "pool": pool,
                "kv_quant": kv_quant,
                # the pool's true storage dtype (a bf16-compute model's
                # exact row stores bf16; the "f32" in the workload key
                # is the grid label, not a dtype claim)
                "kv_dtype": cell.pop("_kv_dtype"),
                "n_slots": n_slots,
                "max_len": max_len,
                "page_size": page_size,
                "quant_block": quant_block,
                "sample_cap": sample_cap,
                "spec_k": spec_k,
                "decode_block": decode_block,
                "reps": reps,
                "lane_view_bytes": view_bytes,
                "pool_bytes": pool_bytes,
            }
            for family in OP_FAMILIES:
                detail[f"{family}_wall_us"] = round(cell[family] * 1e6, 2)
            gather_gbps = view_bytes / cell["gather"] / 1e9
            detail["gather_gbps"] = round(gather_gbps, 3)
            # no `vs_baseline`: bench_check treats that key as a
            # higher-better relative metric, and no ratio of two op
            # walls points one way — the per-family _wall_us fields
            # carry the gated trajectory instead
            entries.append({
                "metric": "kernel_gather_bandwidth",
                "value": round(gather_gbps, 3),
                "unit": (f"GB/s logical-lane-view gather "
                         f"({pool} pool, {dtype})"),
                "detail": detail,
            })
    return entries


def paged_decode_decomposition(
    model, *,
    n_slots: int,
    max_len: int,
    page_size: int,
    decode_block: int,
    step_wall_s: float,
    kv_quant: str | None = None,
    reps: int = 5,
    seed: int = 0,
) -> dict:
    """Decompose a MEASURED paged decode-program wall into its paged-
    pool op shares: isolate-bench the gather / (dequant) / one-token
    scatter at the program's exact shapes and express each as a
    percentage of `step_wall_s` (the compile registry's fenced run
    seconds per `decode_block` call).

    Fields (all clamped to [0, 100]):

        gather_share_pct     the page-table gather (int8: net of the
                             dequant below — pure translation cost)
        dequant_share_pct    dequantizing the gathered view (0.0 on f32
                             pools — an honest zero, not an absence:
                             the f32 entry's decomposition must say
                             "no dequant" explicitly)
        scatter_share_pct    the written-page scatter, x the program's
                             (decode_block-1)//page_size + 2 write-back
                             windows per call
        attention_share_pct  the remainder — model forward (attention +
                             MLP) + sampling, the compute a fused
                             paged-attention kernel must KEEP while it
                             kills the three above

    The remainder is named "attention" because at serving shapes the
    forward is attention-dominated and the ledger's dot category pins
    the split; the microbenched ops are measured, the remainder is
    arithmetic — stated so the before-numbers cannot overclaim.
    """
    if step_wall_s <= 0:
        raise ValueError(f"step_wall_s must be > 0, got {step_wall_s}")
    quant = kv_quant is not None
    # the SAME cell construction the BENCH_kernels.json grid benches —
    # the decomposition and the microbench cannot drift onto different
    # op shapes
    ops, _, lane_view = _paged_pool_ops(
        model, n_slots=n_slots, max_len=max_len, page_size=page_size,
        kv_quant=kv_quant, decode_block=decode_block, seed=seed,
    )
    view_dtype = jax.tree_util.tree_leaves(lane_view)[0].dtype
    gather_fn, gather_args, _ = ops["gather"]
    scatter_fn, scatter_args, _ = ops["scatter"]
    t_gather = fenced_wall_s(gather_fn, gather_args, reps=reps)
    t_scatter1 = fenced_wall_s(scatter_fn, scatter_args, reps=reps)
    t_dequant = 0.0
    if quant:
        # the dequant cost in isolation: int8 payload + scales at the
        # gathered view's shape, multiplied back to compute dtype
        lane_store = make_quant_store(model, n_slots, max_len, page_size)
        t_dequant = fenced_wall_s(
            lambda q, s: dequantize_tree(q, s, view_dtype),
            (lane_store.q, lane_store.scale), reps=reps,
        )
    # the paged decode program scatters back WINDOWS, not tokens: the
    # write-back loop after the scan runs (block-1)//page + 2 clipped
    # scatter_written_pages calls per decode_block call (engine.py
    # `_paged_decode_program` — positions [p, p+block) touch at most
    # that many pages), NOT one scatter per committed token
    n_scatters = (decode_block - 1) // page_size + 2
    t_scatter = t_scatter1 * n_scatters
    dequant = min(t_dequant, t_gather)
    gather = max(t_gather - dequant, 0.0) if quant else t_gather
    g = 100.0 * gather / step_wall_s
    d = 100.0 * dequant / step_wall_s if quant else 0.0
    sc = 100.0 * t_scatter / step_wall_s
    # the shares PARTITION the step by construction: the isolated
    # microbench walls and the step wall come from different runs, so
    # on a noisy host their raw sum can exceed 100 — rescale the
    # measured components proportionally (disclosed, never silent)
    # instead of letting a required CI assert fail on box noise
    total = g + d + sc
    clamped = total > 100.0
    if clamped:
        scale = 100.0 / total
        g, d, sc = g * scale, d * scale, sc * scale
    att = max(100.0 - g - d - sc, 0.0)
    out = {
        "decode_step_wall_s": round(step_wall_s, 6),
        "gather_wall_s": round(t_gather, 6),
        "dequant_wall_s": round(t_dequant, 6),
        "scatter_wall_s": round(t_scatter, 6),
        "gather_share_pct": round(g, 2),
        "dequant_share_pct": round(d, 2) if quant else 0.0,
        "scatter_share_pct": round(sc, 2),
        "attention_share_pct": round(att, 2),
    }
    if clamped:
        # present iff it happened (the serve/preemptions discipline)
        out["decomposition_clamped"] = True
    return out
