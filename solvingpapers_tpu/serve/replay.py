"""Journal-backed request replay: the serving stack's correctness
observatory (ROADMAP item 5's regression-testing endpoint).

PR 14's write-ahead journal records everything needed to re-serve a
request exactly — prompt token ids, the full `SamplingParams` (incl.
seed and SLO class), budget/eos, arrival offset, and the committed
token stream. This module turns that durability artifact into a
shadow-traffic harness: `ReplayHarness` loads a journal (live file or
rotated snapshot, via `journal.read_entries`), reconstructs each
finished request, re-serves the corpus against a CANDIDATE
`ServeConfig` on a fresh engine, and diffs the replayed streams
against the recorded ones. The question it answers is the one every
kernel/pool/quant change needs answered before landing: *does the
candidate config serve yesterday's real traffic identically?*

Two comparison modes, applied per stream by replayability class:

* **byte diff** — greedy streams (temperature 0) and SEEDED stochastic
  streams fold only ``(seed, sample_index)`` into their sampling
  chains, so an identical-config replay must reproduce the recorded
  stream byte-for-byte (`byte_exact`), and any mismatch carries its
  `first_divergence` token offset. Unseeded stochastic streams fold
  the engine step counter (serve/sampling.py) — they are re-served for
  load realism but excluded from byte accounting.
* **teacher-forced agreement** — the quant bench's cut-replay
  machinery (PR 10) generalized to arbitrary recorded streams: each
  byte-comparable stream is cut every `cut_stride` positions and the
  prefix re-served through the candidate for exactly ONE token.
  Greedy cuts submit the prefix as a plain prompt (PR 10's cut
  verbatim — the measurement `run_quant_bench`'s >= 0.99
  `greedy_agreement_rate` band is calibrated on; argmax needs no seed
  pinning). Seeded cuts ride `ServeEngine.replay_submit`'s
  committed-prefix path, which pins the recorded seed chain
  (admission re-prefills prompt + committed[:-1], discards the
  resampled token, and the next draw lands at sample index
  ``len(committed)`` — the preemption-resume argument); the compared
  token there comes from a decode step reading the candidate's pool,
  so a lossy candidate (kv_quant int8) flips seeded cuts far more
  readily. Hence the split: `agreement_rate_greedy` is the gated
  graded score, `agreement_rate_seeded` discloses per-step seed-chain
  sensitivity, `agreement_rate` folds both. An identical config must
  score 1.0 on all three.

Entries the candidate cannot replay token-exactly — grammar requests
(host stepper state), stop strings without a detokenizer, kv_exact
without sidecar lanes, prompts beyond the candidate's capacity, or
streams with no committed tokens — land in the report as ``skipped``
with reasons, never as divergences. The aggregate report also carries
the replayed run's own `ServeMetrics` latency/throughput summary and,
when a baseline config is supplied, paired deltas against a second
re-serve of the same corpus.

Exposure (wired elsewhere, all riding this module's report dict):
`cli replay` (exit 2 past the divergence threshold — the CI canary
gate), `POST /v1/replay` + `GET /v1/replay/<id>` on the HTTP front
door (serve/api.py), and the `replay/*` gauges via `report_gauges`
through the standard gauge-provider mechanism.

Zero cost when unused: nothing here is imported by the engine, no
gauges exist until a replay has run, and `replay_submit` reuses the
existing submit/resume machinery — no new traced programs on a
replay-less engine (pinned in tests/test_replay.py).
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np

from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine
from solvingpapers_tpu.serve.journal import JournalEntry, read_entries
from solvingpapers_tpu.serve.sampling import SamplingParams

__all__ = [
    "ReplayHarness",
    "apply_overrides",
    "report_gauges",
    "sanitize_config",
]

# finish reasons whose committed stream is a faithful prefix of what an
# uninterrupted run would produce (cancel/timeout truncate the stream
# but never alter produced tokens, so the prefix still byte-compares)
_REPLAYABLE_REASONS = ("eos", "length", "stop", "cancelled", "timeout")


def sanitize_config(cfg: ServeConfig, n_requests: int = 0) -> ServeConfig:
    """A candidate config made safe for a shadow re-serve: no WAL of its
    own (shadow traffic must not write journal records), no listening
    ports, no fault injection, no tracing/time-series overhead — the
    replay engine is a measurement instrument, not a server. The queue
    bound is widened to hold the whole corpus (replay submits up
    front; admission order, not queue capacity, is under test)."""
    return dataclasses.replace(
        cfg,
        journal_path=None,
        journal_strict=False,
        api_port=None,
        status_port=None,
        fault_plan=None,
        trace=False,
        timeseries=False,
        max_waiting=max(cfg.max_waiting, n_requests + 1),
    )


def apply_overrides(cfg: ServeConfig, overrides: dict) -> ServeConfig:
    """Apply ``key=value`` candidate overrides to a ServeConfig. Values
    arrive as strings from the CLI / JSON from the HTTP body; strings
    coerce via json.loads first (ints, floats, ``true``/``false``,
    ``null``, lists), falling back to the raw string (``kv_quant=int8``).
    Unknown keys raise ValueError — a typo'd knob must not silently
    gate nothing."""
    fields = {f.name for f in dataclasses.fields(ServeConfig)}
    parsed = {}
    for key, val in overrides.items():
        if key not in fields:
            raise ValueError(
                f"unknown ServeConfig field {key!r} in config overrides "
                f"(known: {sorted(fields)})"
            )
        if isinstance(val, str):
            try:
                val = json.loads(val)
            except json.JSONDecodeError:
                pass  # a bare string value, e.g. kv_quant=int8
        parsed[key] = val
    return dataclasses.replace(cfg, **parsed)


def _entry_params(e: JournalEntry) -> SamplingParams:
    """The recorded SamplingParams, re-materialized exactly like
    `ServeEngine._entry_request` does (tuple-normalized stop fields);
    raises TypeError/ValueError for an unparseable record."""
    p = dict(e.params)
    p["stop_token_ids"] = tuple(p.get("stop_token_ids") or ())
    p["stop"] = tuple(p.get("stop") or ())
    return SamplingParams(**p)


def _stream_kind(params: SamplingParams) -> str:
    """Replayability class: ``greedy`` and ``seeded`` streams are
    byte-comparable (their sampling chains fold only (seed, sample
    index)); ``stochastic`` (unseeded, temperature > 0) streams fold
    the engine step counter and are replayed for load only."""
    if params.greedy:
        return "greedy"
    if params.seed is not None:
        return "seeded"
    return "stochastic"


def _first_divergence(recorded: list, replayed: list) -> int | None:
    """Token offset of the first mismatch (length differences diverge
    at the shorter stream's end), None when byte-identical."""
    for i, (a, b) in enumerate(zip(recorded, replayed)):
        if int(a) != int(b):
            return i
    if len(recorded) != len(replayed):
        return min(len(recorded), len(replayed))
    return None


def _metrics_summary(eng: ServeEngine) -> dict:
    """The replayed run's own latency/throughput view, flat and
    rounded — the paired-delta source."""
    snap = eng.metrics.snapshot()
    out = {}
    for key, name in (
        ("serve/ttft_s_mean", "ttft_s_mean"),
        ("serve/ttft_s_p99", "ttft_s_p99"),
        ("serve/itl_s_mean", "itl_s_mean"),
        ("serve/e2e_s_mean", "e2e_s_mean"),
        ("serve/tokens_per_sec", "tokens_per_sec"),
        ("serve/requests_per_sec", "requests_per_sec"),
    ):
        if key in snap:
            out[name] = round(float(snap[key]), 6)
    return out


def report_gauges(report: dict | None) -> dict[str, float]:
    """The `replay/*` gauge family from a finished report — the
    standard gauge-provider payload (serve/metrics.py): registered by
    whoever owns a report (the HTTP front door's replay registry),
    absent entirely until a replay has run (the present-iff-enabled
    key-surface contract). None-valued aggregates (no byte-comparable
    streams, no divergences) are omitted, not zero-filled."""
    if not report:
        return {}
    out = {
        "replay/streams_compared": float(report["streams_compared"]),
        "replay/streams_replayed": float(report["streams_replayed"]),
        "replay/streams_skipped": float(len(report["skipped"])),
        "replay/wall_s": float(report["replay_wall_s"]),
    }
    for src, name in (("byte_exact_rate", "replay/byte_exact_rate"),
                      ("agreement_rate", "replay/agreement_rate"),
                      ("agreement_rate_greedy",
                       "replay/agreement_rate_greedy"),
                      ("first_divergence_p50",
                       "replay/first_divergence_p50")):
        if report.get(src) is not None:
            out[name] = float(report[src])
    return out


class ReplayHarness:
    """Re-serve a journal's recorded traffic against a candidate
    `ServeConfig` and produce the divergence report.

    Holds the model half of an engine (model / params / extra
    variables / detokenize) so one harness can drive several candidate
    configs over one loaded corpus. Construct directly or borrow a
    live engine's weights with `from_engine` (the HTTP front door's
    path — the replay engine is always a FRESH engine; the live one is
    never touched)."""

    def __init__(self, model, params, *, extra_variables=None,
                 detokenize=None):
        self.model = model
        self.params = params
        self.extra_variables = extra_variables
        self.detokenize = detokenize

    @classmethod
    def from_engine(cls, engine: ServeEngine) -> "ReplayHarness":
        extra = {k: v for k, v in engine.variables.items()
                 if k != "params"}
        return cls(engine.model, engine.variables["params"],
                   extra_variables=extra or None,
                   detokenize=engine.detokenize)

    # ------------------------------------------------------------- load

    @staticmethod
    def load(path: str, *, retries: int = 1) -> list[JournalEntry]:
        """Snapshot-load a journal file (live or rotated) — delegates
        to `journal.read_entries`: torn-tail tolerant, ENOENT around a
        concurrent compaction swap retried once."""
        return read_entries(path, retries=retries)

    # -------------------------------------------------------- selection

    def _screen(self, e: JournalEntry, cfg: ServeConfig,
                quant: bool) -> tuple[SamplingParams | None, str | None]:
        """(params, None) for a replayable finished entry, (None,
        reason) otherwise — `ServeEngine._entry_request`'s validation
        order, extended with the corpus-level conditions (unfinished /
        tokenless / non-prefix outcomes). Skips are report rows, never
        divergences."""
        if not e.finished:
            return None, "still live at capture"
        if e.grammar:
            return None, "grammar stepper state is not journaled"
        if not e.tokens:
            return None, "no committed tokens to compare"
        if e.finish_reason not in _REPLAYABLE_REASONS:
            return None, (f"finish {e.finish_reason!r} is not a "
                          "token-faithful outcome")
        try:
            params = _entry_params(e)
        except (TypeError, ValueError) as exc:
            return None, f"unreplayable params: {exc}"
        limit = getattr(self.model, "max_positions", None)
        cap = min(cfg.max_len, limit or cfg.max_len)
        if len(e.prompt) < 1 or len(e.prompt) + len(e.tokens) > cap:
            return None, f"beyond the candidate's capacity {cap}"
        if params.stop and self.detokenize is None:
            return None, "stop strings need a detokenize callable"
        if params.kv_exact and quant and not cfg.kv_exact_lanes:
            return None, "kv_exact needs exact sidecar lanes"
        if params.top_k > cfg.sample_cap:
            return None, (f"top_k {params.top_k} exceeds the candidate's "
                          f"sample_cap {cfg.sample_cap}")
        return params, None

    # -------------------------------------------------------------- run

    def _drain(self, eng: ServeEngine) -> None:
        while eng.has_work():
            eng.step()

    def _serve_corpus(self, corpus, cfg: ServeConfig, pace: bool):
        """One full re-serve of the screened corpus on a fresh engine:
        submit in arrival order (paced at the recorded offsets when
        `pace`, up front otherwise — exactness is arrival-independent,
        latency realism is not), drain, return (engine, handles,
        wall_s)."""
        eng = ServeEngine(self.model, self.params, cfg,
                          extra_variables=self.extra_variables,
                          detokenize=self.detokenize)
        handles = []
        t0 = time.monotonic()
        if pace:
            base = min(e.arrival for e, _ in corpus)
            pending = sorted(
                ((e.arrival - base, e, p) for e, p in corpus),
                key=lambda r: r[0])
            i = 0
            while i < len(pending) or eng.has_work():
                elapsed = time.monotonic() - t0
                while i < len(pending) and pending[i][0] <= elapsed:
                    _, e, params = pending[i]
                    handles.append(eng.replay_submit(
                        np.asarray(e.prompt, np.int32),
                        max_new_tokens=len(e.tokens),
                        eos_id=e.eos_id, params=params))
                    i += 1
                if eng.has_work():
                    eng.step()
                elif i < len(pending):
                    time.sleep(max(0.0, pending[i][0]
                                   - (time.monotonic() - t0)))
        else:
            for e, params in corpus:
                handles.append(eng.replay_submit(
                    np.asarray(e.prompt, np.int32),
                    max_new_tokens=len(e.tokens),
                    eos_id=e.eos_id, params=params))
            self._drain(eng)
        wall = time.monotonic() - t0
        assert all(h.done for h in handles), \
            "replay engine drained with unfinished work"
        return eng, handles, wall

    def run(self, entries, candidate: ServeConfig, *,
            baseline: ServeConfig | None = None,
            cut_stride: int = 8, max_cuts: int = 512,
            max_requests: int | None = None, pace: bool = False,
            journal_path: str | None = None,
            progress=None) -> dict:
        """Re-serve `entries` against `candidate` and return the
        divergence report (see the module docstring for semantics).

        `cut_stride` spaces the teacher-forced agreement cuts (0
        disables the agreement pass); `max_cuts` bounds their total —
        cut coverage is disclosed in the report, never silently
        truncated. `baseline` re-serves the same corpus a second time
        for paired latency/throughput deltas. `progress(done, total)`
        is called from the replay thread as streams finish phases —
        the HTTP front door's progress surface."""
        t_start = time.monotonic()
        entries = list(entries)
        if max_requests is not None:
            entries = entries[:max_requests]
        quant = bool(candidate.kv_quant)
        corpus, skipped = [], []
        for e in entries:
            params, reason = self._screen(e, candidate, quant)
            if reason is not None:
                skipped.append({"rid": e.rid, "reason": reason})
            else:
                corpus.append((e, params))
        report = {
            "streams_total": len(entries),
            "streams_replayed": len(corpus),
            "streams_compared": 0,
            "skipped": skipped,
            "candidate": {
                "n_slots": candidate.n_slots,
                "max_len": candidate.max_len,
                "decode_block": candidate.decode_block,
                "paged": candidate.paged,
                "kv_quant": candidate.kv_quant,
                "speculative": candidate.speculative,
                "prefix_cache": candidate.prefix_cache,
            },
        }
        if journal_path is not None:
            report["journal"] = journal_path
        if not corpus:
            report.update(byte_exact_rate=None, agreement_rate=None,
                          agreement_rate_greedy=None,
                          agreement_rate_seeded=None,
                          first_divergence_p50=None, diverged=[],
                          streams=[], cut_positions=0,
                          replay_metrics={},
                          replay_wall_s=round(
                              time.monotonic() - t_start, 4))
            return report
        run_cfg = sanitize_config(candidate, len(corpus))

        total_phases = 2 + (1 if cut_stride else 0) + \
            (1 if baseline is not None else 0)
        done_phases = 0

        def _tick():
            nonlocal done_phases
            done_phases += 1
            if progress is not None:
                progress(done_phases, total_phases)

        _tick()  # corpus screened
        eng, handles, serve_wall = self._serve_corpus(
            corpus, run_cfg, pace)
        _tick()

        streams, diverged = [], []
        exact = compared = 0
        for (e, params), h in zip(corpus, handles):
            kind = _stream_kind(params)
            recorded = [int(t) for t in e.tokens]
            replayed = [int(t) for t in h.tokens]
            row = {
                "rid": e.rid, "kind": kind,
                "recorded_tokens": len(recorded),
                "replayed_tokens": len(replayed),
                "finish_recorded": e.finish_reason,
                "finish_replayed": h.finish_reason,
            }
            if kind in ("greedy", "seeded"):
                compared += 1
                offset = _first_divergence(recorded, replayed)
                row["byte_exact"] = offset is None
                row["first_divergence"] = offset
                if offset is None:
                    exact += 1
                else:
                    diverged.append({
                        "rid": e.rid, "kind": kind,
                        "first_divergence": offset,
                        "recorded_tokens": len(recorded),
                        "replayed_tokens": len(replayed),
                    })
            else:
                row["byte_exact"] = None
                row["first_divergence"] = None
            streams.append(row)

        # teacher-forced agreement cuts over the byte-comparable
        # streams, seed chains pinned via the committed-prefix path
        agreement = None
        cut_total = cut_matches = 0
        cuts_dropped = 0
        # per-kind split: greedy cuts are the kv-quant family's gated
        # number (argmax agreement is robust to small logit error);
        # seeded cuts re-draw through the pinned seed chain, where a
        # lossy candidate flips tokens far more readily — disclosed
        # separately so the graded score stays comparable to the
        # --kv-quant bench's greedy_agreement_rate precedent
        by_kind = {"greedy": [0, 0], "seeded": [0, 0]}  # [total, match]
        if cut_stride:
            cuts = []  # (expected token, entry, params, offset, kind)
            for (e, params), row in zip(corpus, streams):
                if row["kind"] not in ("greedy", "seeded"):
                    continue
                for j in range(0, len(e.tokens), cut_stride):
                    cuts.append(
                        (int(e.tokens[j]), e, params, j, row["kind"]))
            if len(cuts) > max_cuts:
                cuts_dropped = len(cuts) - max_cuts
                cuts = cuts[:max_cuts]
            cut_params = {}
            cut_handles = []
            for expected, e, params, j, kind in cuts:
                key = id(params)
                if key not in cut_params:
                    # pure continuation comparison: the recorded stop
                    # conditions and budget must not cut the cut
                    cut_params[key] = dataclasses.replace(
                        params, stop=(), stop_token_ids=(),
                        max_tokens=None)
                try:
                    if kind == "greedy":
                        # PR 10's plain-prompt cut verbatim — the
                        # measurement run_quant_bench's >= 0.99
                        # greedy_agreement_rate band is calibrated on:
                        # the teacher-forced prefix rides the prefill
                        # path and argmax needs no seed pinning
                        h = eng.replay_submit(
                            np.concatenate([
                                np.asarray(e.prompt, np.int32),
                                np.asarray(e.tokens[:j], np.int32),
                            ]),
                            max_new_tokens=1, eos_id=None,
                            params=cut_params[key])
                        out_idx = 0
                    else:
                        # seeded streams need the committed-prefix
                        # resume path: it is what lands the next draw
                        # at the recorded sample index
                        h = eng.replay_submit(
                            np.asarray(e.prompt, np.int32),
                            max_new_tokens=j + 1, eos_id=None,
                            params=cut_params[key],
                            committed=e.tokens[:j])
                        out_idx = j
                except ValueError:
                    cuts_dropped += 1
                    continue
                cut_handles.append((h, expected, out_idx, kind))
            self._drain(eng)
            for h, expected, out_idx, kind in cut_handles:
                cut_total += 1
                by_kind[kind][0] += 1
                if (len(h.tokens) > out_idx
                        and int(h.tokens[out_idx]) == expected):
                    cut_matches += 1
                    by_kind[kind][1] += 1
            if cut_total:
                agreement = cut_matches / cut_total
            _tick()

        fdivs = sorted(d["first_divergence"] for d in diverged)
        report.update(
            streams_compared=compared,
            byte_exact=exact,
            byte_exact_rate=(exact / compared) if compared else None,
            diverged=diverged,
            first_divergence_p50=(
                float(fdivs[len(fdivs) // 2]) if fdivs else None),
            agreement_rate=(
                round(agreement, 6) if agreement is not None else None),
            agreement_rate_greedy=(
                round(by_kind["greedy"][1] / by_kind["greedy"][0], 6)
                if by_kind["greedy"][0] else None),
            agreement_rate_seeded=(
                round(by_kind["seeded"][1] / by_kind["seeded"][0], 6)
                if by_kind["seeded"][0] else None),
            cut_positions=cut_total,
            cuts_dropped=cuts_dropped,
            cut_stride=cut_stride,
            streams=streams,
            replay_metrics=_metrics_summary(eng),
            serve_wall_s=round(serve_wall, 4),
        )
        eng.close()

        if baseline is not None:
            base_cfg = sanitize_config(baseline, len(corpus))
            beng, _, _ = self._serve_corpus(corpus, base_cfg, pace)
            base_metrics = _metrics_summary(beng)
            beng.close()
            report["baseline_metrics"] = base_metrics
            deltas = {}
            cand = report["replay_metrics"]
            for name, base_val in base_metrics.items():
                if name in cand and base_val:
                    deltas[f"{name}_delta_pct"] = round(
                        (cand[name] / base_val - 1.0) * 100.0, 2)
            report["deltas"] = deltas
            _tick()

        report["replay_wall_s"] = round(time.monotonic() - t_start, 4)
        return report
