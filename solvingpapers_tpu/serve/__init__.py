"""Continuous-batching serving: slot/paged KV pools + FIFO scheduler +
mixed prefill/decode engine + radix-tree prefix cache (zero-copy
refcounted page sharing on the paged pool) + per-request sampling
(SamplingParams / fused_sample) + speculative decoding (serve/spec.py:
n-gram/MTP draft-and-verify with lossless rejection sampling) +
grammar-constrained JSON decoding (JsonStepper) + OpenAI-compatible
HTTP front door (ApiServer) + latency metrics + fault tolerance
(serve/faults.py: seeded fault injection, supervised step loop with
per-request blast-radius isolation, SLO-driven degradation ladder) +
durable serving (serve/journal.py: request write-ahead journal,
crash-safe warm restart via ServeEngine.recover, SSE stream
resumption over Last-Event-ID) + fleet serving (serve/fleet.py:
multi-replica FleetRouter with prefix-affinity + SLO-aware routing,
merged fleet metrics, journal-backed zero-drop stream migration via
FleetRouter.drain) + replay observatory (serve/replay.py: journal-
backed shadow-traffic replay against a candidate config, byte-level
stream diffing + teacher-forced agreement scoring, the config-canary
divergence gate)."""

from solvingpapers_tpu.serve.api import ApiServer, EngineLoop, serve_api
from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine
from solvingpapers_tpu.serve.fleet import (
    FleetRouter,
    MigrationReport,
    Replica,
)
from solvingpapers_tpu.serve.faults import (
    DegradationLadder,
    FaultPlan,
    FaultSpec,
    InjectedFault,
)
from solvingpapers_tpu.serve.grammar import JsonStepper
from solvingpapers_tpu.serve.journal import (
    Journal,
    JournalEntry,
    JournalError,
    read_entries,
)
from solvingpapers_tpu.serve.kv_pool import (
    KVSlotPool,
    PagedKVPool,
    extract_lane,
    store_lane,
)
from solvingpapers_tpu.serve.metrics import ServeMetrics
from solvingpapers_tpu.serve.prefix_cache import PrefixCache, PrefixMatch
from solvingpapers_tpu.serve.replay import ReplayHarness
from solvingpapers_tpu.serve.sampling import SamplingParams, fused_sample
from solvingpapers_tpu.serve.scheduler import FIFOScheduler, Request
from solvingpapers_tpu.serve.slo import DEFAULT_SLO_TARGETS, SloTracker
from solvingpapers_tpu.serve.spec import SpecController

__all__ = [
    "ApiServer",
    "DegradationLadder",
    "EngineLoop",
    "FaultPlan",
    "FaultSpec",
    "FleetRouter",
    "InjectedFault",
    "MigrationReport",
    "Replica",
    "JsonStepper",
    "Journal",
    "JournalEntry",
    "JournalError",
    "read_entries",
    "ReplayHarness",
    "serve_api",
    "ServeConfig",
    "ServeEngine",
    "KVSlotPool",
    "PagedKVPool",
    "extract_lane",
    "store_lane",
    "ServeMetrics",
    "PrefixCache",
    "PrefixMatch",
    "SamplingParams",
    "fused_sample",
    "FIFOScheduler",
    "Request",
    "DEFAULT_SLO_TARGETS",
    "SloTracker",
    "SpecController",
]
