"""Continuous-batching serving: slot pool + FIFO scheduler + mixed
prefill/decode engine + radix-tree prefix cache + latency metrics."""

from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine
from solvingpapers_tpu.serve.kv_pool import KVSlotPool, extract_lane, store_lane
from solvingpapers_tpu.serve.metrics import ServeMetrics
from solvingpapers_tpu.serve.prefix_cache import PrefixCache, PrefixMatch
from solvingpapers_tpu.serve.scheduler import FIFOScheduler, Request
