"""Speculative decoding for the serving engine: per-slot draft-and-verify
with lossless rejection sampling.

The serve engine's plain decode block advances every slot ONE token per
scan iteration — each iteration is a full vmapped model forward whose
cost, on the dispatch-bound serving path, is dominated by per-step
overhead rather than by the single token it yields. Speculative decoding
(Leviathan et al., "Fast Inference from Transformers via Speculative
Decoding"; Chen et al., "Accelerating LLM Decoding with Speculative
Sampling") turns each iteration into a DRAFT-AND-VERIFY round: a cheap
drafter proposes up to `k` next tokens per slot, one chunked forward
computes the model's distributions at all `1 + k` positions at once, and
the drafts are verified against those distributions — committing between
1 and ``k + 1`` tokens per forward with the OUTPUT DISTRIBUTION provably
unchanged:

* greedy slots verify by exact argmax match — the committed stream is
  token-identical to non-speculative greedy decode by construction
  (every committed token IS the model's argmax given its prefix);
* stochastic slots use modified rejection sampling against the SAME
  truncated per-request distribution `fused_sample` draws from: a draft
  `d` (a deterministic proposal, q = delta_d) is accepted with
  probability ``p(d)``; on rejection the token is redrawn from the
  residual ``p`` with `d` removed and renormalized, and when every draft
  survives a bonus token is drawn from the chunk's last row. Summing the
  two branches gives exactly ``p`` per committed position — lossless
  (`tests/test_spec.py` pins greedy byte-exactness and the stochastic
  empirical distribution).

Two drafters share the verify machinery (`ServeConfig.speculative`):

* ``"ngram"`` — a model-free prompt-lookup self-drafter (`ngram_drafts`):
  find the most recent earlier occurrence of the stream's trailing
  n-gram in its own history (prompt + committed tokens) and propose the
  tokens that followed it. Zero extra parameters, works for every
  decoder family, and runs INSIDE the jitted decode program over a
  history buffer that rides the engine's packed control transfer — so
  one program call runs `spec_rounds` draft-verify rounds back to back,
  amortizing host dispatch exactly like the plain block's scan.
* ``"mtp"`` — the DeepSeek-V3 multi-token-prediction heads
  (`infer/speculative.py` mechanics, vmapped over the slot axis): each
  round's chunk forward returns hidden states, the MTP head(s) advance
  their own per-slot latent-cache lanes and draft the next round's
  tokens in-program. deepseekv3 family, lane pool.

Draft length `k` is traced PER-SLOT (`avail`): a slot whose lookup found
nothing, a grammar-constrained slot (stale-mask contract: one token per
block), and a free lane all ride the same compiled program with zero
drafts — mixed speculative/non-speculative batches share ONE decode
program, which tests pin via the jit cache.

`SpecController` is the host-side adaptive policy: speculation helps
exactly when drafts get accepted, and the chunked forward is not free
(the model runs ``1 + k`` positions per round), so a workload whose
drafts keep rejecting — adversarial random-token traffic — would pay the
chunk width for nothing. The controller tracks an acceptance EMA per
engine and drops the engine back to the plain block program while the
EMA is below `spec_min_rate`, probing speculation again every
`spec_probe_every` steps — bounding the zero-acceptance overhead to the
occasional probe (the `serve-bench --speculative` adversarial arm
measures it against a <= 10% budget).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from solvingpapers_tpu import ops
from solvingpapers_tpu.serve.sampling import (
    PackedSampling,
    capped_support,
    request_key,
)

DRAFTERS = ("ngram", "mtp")


# ---------------------------------------------------------------- drafting


def ngram_drafts(hist, length, *, k: int, nmax: int = 3):
    """Prompt-lookup drafts for ONE slot (traced; vmap over the slot axis).

    `hist` is the slot's (H,) token history — prompt plus every committed
    token, garbage beyond `length` — and `length` the live token count.
    Tries tail n-grams from `nmax` down to 1: the first n whose trailing
    n-gram ``hist[length-n:length]`` recurs earlier in the history wins,
    and the proposal is the (up to) `k` tokens that FOLLOWED the most
    recent earlier occurrence. Returns ``(drafts (k,) i32, avail)`` with
    ``avail`` the usable draft count (0 = nothing to propose — the slot
    runs the round draft-free, committing exactly one token).

    Matches must end strictly before the final n-gram (``j + n <=
    length - 1``), so the trivial self-match never proposes, and drafts
    are clipped to committed history (a proposal never reads garbage).
    """
    h = jnp.asarray(hist)
    big = h.shape[0]
    idx = jnp.arange(big)
    best_start = jnp.int32(0)
    best_n = jnp.int32(0)
    found = jnp.bool_(False)
    # longest-n-gram-first fallback chain: a hit at larger n is a more
    # specific context and predicts the continuation better; ties at the
    # same n break toward the MOST RECENT occurrence (locality)
    for n in range(nmax, 0, -1):
        # rolling equality: window j matches iff h[j + t] == key[t] for
        # every t, with key = h[length - n : length]
        match = jnp.ones(big, bool)
        for t in range(n):
            key_t = h[jnp.clip(length - n + t, 0, big - 1)]
            match = match & (jnp.roll(h, -t) == key_t)
        match = match & (idx + n <= length - 1)
        j = jnp.max(jnp.where(match, idx, -1))
        hit = (j >= 0) & (length > n)
        take = hit & ~found
        best_start = jnp.where(take, j + n, best_start)
        best_n = jnp.where(take, n, best_n)
        found = found | hit
    start = jnp.clip(best_start, 0, big - 1)
    # gather k tokens from `start`; clip per-index so the slice never
    # wraps or reads past the buffer (avail masks the short tail anyway)
    drafts = h[jnp.clip(start + jnp.arange(k), 0, big - 1)]
    avail = jnp.where(found, jnp.clip(length - start, 0, k), 0)
    return drafts.astype(jnp.int32), avail.astype(jnp.int32)


# ------------------------------------------------------------ verification


def _fold_all(keys, tag):
    """fold_in over an arbitrary-rank array of typed keys."""
    flat = keys.reshape(-1)
    folded = jax.vmap(lambda kk: jax.random.fold_in(kk, tag))(flat)
    return folded.reshape(keys.shape)


def spec_verify(logits, drafts, avail, packed: PackedSampling, keys, *,
                cap: int, allow=None):
    """Verify one round of drafts and emit the committed-token matrix.

    ``logits`` is (S, L, V) with ``L = k + 1`` — row i is the model's
    distribution for the i-th position of the commit window (row j
    verifies draft j; row ``a`` supplies the correction/bonus draw).
    ``drafts`` (S, k) and ``avail`` (S,) come from the drafter (avail 0
    = non-speculative slot); ``keys`` (S, L) are the per-position
    sampling keys (chain: (seed, committed index) — ONE index per
    committed token, same contract as the plain path). Returns
    ``(out (S, L) i32, commits (S,) i32, logprobs (S, L) f32)``: the
    host keeps ``out[s, :commits[s]]``.

    Greedy slots: draft j accepted iff it equals row j's argmax; every
    committed token is a row argmax — byte-identical to non-speculative
    greedy decode. Stochastic slots: draft j accepted with probability
    ``p_j(d_j)`` under the request's truncated distribution (the same
    `capped_support` pipeline `fused_sample` uses); the cut position
    redraws from the residual (draft removed, renormalized) on a
    rejection or from the full row when every draft survived. Both
    branches compose to exactly ``p_j`` per committed position —
    lossless by the Leviathan/Chen argument specialized to a
    deterministic proposal (q = delta_draft: accept prob
    ``min(1, p/q) = p``, residual ``norm(max(0, p - q)) = p`` minus the
    draft).

    `allow` (S, cap) constrains ROW 0 ONLY of constrained slots (the
    grammar mask is stale after one draw; such slots ride with
    avail = 0, so row 0 is their single commit).
    """
    s_n, big_l, vocab = logits.shape
    k = big_l - 1
    cap = min(cap, vocab)
    logits32 = logits.astype(jnp.float32)
    greedy = packed.temperature <= 0.0
    within = jnp.arange(k)[None, :] < avail[:, None]
    greedy_tok = jnp.argmax(logits32, axis=-1).astype(jnp.int32)  # (S, L)
    if allow is not None:
        if allow.shape[-1] > cap:
            allow = allow[:, :cap]
        elif allow.shape[-1] < cap:
            allow = jnp.pad(allow, ((0, 0), (0, cap - allow.shape[-1])),
                            constant_values=-1)
        constrained = allow[:, 0] >= 0

    def _exact():
        """All-greedy, unconstrained: argmax rows + exact-match verify —
        no top_k, no masking, no rng (the cost of the plain greedy
        sampler, which is what keeps all-greedy serving fast)."""
        acc = (greedy_tok[:, :k] == drafts) & within
        commits = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(1) + 1
        return greedy_tok, commits

    def _mixed():
        flat = logits32.reshape(s_n * big_l, vocab)
        knobs = PackedSampling(
            temperature=jnp.repeat(packed.temperature, big_l),
            top_p=jnp.repeat(packed.top_p, big_l),
            min_p=jnp.repeat(packed.min_p, big_l),
            top_k=jnp.repeat(packed.top_k, big_l),
            need_lp=jnp.repeat(packed.need_lp, big_l),
        )
        allow_rows = None
        if allow is not None:
            # the grammar mask constrains row 0 only: rows >= 1 of a
            # constrained slot are discarded overshoot (avail = 0)
            allow_rows = jnp.full((s_n, big_l, cap), -1, jnp.int32)
            allow_rows = allow_rows.at[:, 0, :].set(allow)
            allow_rows = allow_rows.reshape(s_n * big_l, cap)
        masked, top_idx = capped_support(flat, knobs, cap=cap,
                                         allow=allow_rows)
        masked = masked.reshape(s_n, big_l, cap)
        top_idx = top_idx.reshape(s_n, big_l, cap)
        g_tok = greedy_tok
        if allow is not None:
            # greedy under a constraint = argmax over the allowed domain
            dom = jnp.take_along_axis(
                top_idx[:, 0], jnp.argmax(masked[:, 0], -1)[:, None], axis=-1
            )[:, 0]
            g_tok = g_tok.at[:, 0].set(
                jnp.where(constrained, dom, g_tok[:, 0]))
        probs = jax.nn.softmax(masked, axis=-1)  # -inf rows -> 0 mass
        d_hit = top_idx[:, :k, :] == drafts[:, :, None]
        d_prob = jnp.sum(jnp.where(d_hit, probs[:, :k], 0.0), axis=-1)
        u = jax.vmap(jax.vmap(jax.random.uniform))(
            _fold_all(keys[:, :k], 1))
        acc_st = u < d_prob
        acc_gr = g_tok[:, :k] == drafts
        acc = jnp.where(greedy[:, None], acc_gr, acc_st) & within
        a = jnp.cumprod(acc.astype(jnp.int32), axis=1).sum(1)  # (S,)
        commits = a + 1
        # cut-row draws: residual (draft zeroed, renormalized) after a
        # rejection, the full row after a clean sweep. Computed for every
        # row, selected at the cut — rows past the cut are discarded.
        resid = jnp.where(d_hit, -jnp.inf, masked[:, :k])
        cat_keys = _fold_all(keys, 2)
        cat = jax.vmap(jax.vmap(
            lambda row, kk: jax.random.categorical(kk, row)
        ))
        full_sel = cat(masked, cat_keys)                       # (S, L)
        resid_sel = cat(resid, cat_keys[:, :k])                # (S, k)
        full_tok = jnp.take_along_axis(top_idx, full_sel[..., None],
                                       axis=-1)[..., 0]
        resid_tok = jnp.take_along_axis(top_idx[:, :k],
                                        resid_sel[..., None], axis=-1)[..., 0]
        resid_tok = jnp.concatenate(
            [resid_tok, full_tok[:, -1:]], axis=1)             # row k: full
        rows = jnp.arange(big_l)[None, :]
        at_cut = rows == a[:, None]
        rejected = at_cut & (a < avail)[:, None]
        drafts_l = jnp.concatenate(
            [drafts, jnp.zeros((s_n, 1), drafts.dtype)], axis=1)
        stoch = jnp.where(rows < a[:, None], drafts_l,
                          jnp.where(rejected, resid_tok, full_tok))
        out = jnp.where(greedy[:, None], g_tok, stoch.astype(jnp.int32))
        return out, commits

    fast = jnp.all(greedy)
    if allow is not None:
        fast = fast & ~jnp.any(constrained)
    out, commits = jax.lax.cond(fast, _exact, _mixed)

    def _logprobs():
        chosen = jnp.take_along_axis(logits32, out[..., None],
                                     axis=-1)[..., 0]
        lse = jax.scipy.special.logsumexp(logits32, axis=-1)
        return chosen - lse

    logprobs = jax.lax.cond(
        jnp.any(packed.need_lp > 0), _logprobs,
        lambda: jnp.zeros(out.shape, jnp.float32),
    )
    return out, commits, logprobs


def round_keys(rng, step_tag, seeds, samp_cnt, big_l):
    """(S, L) per-position sampling keys for one draft-verify round:
    position i of slot s folds ``samp_cnt[s] + i`` — one sample index
    per COMMITTED token, so a seeded request's chain depends only on
    (seed, committed index), exactly like the non-speculative path."""
    s_n = seeds.shape[0]
    slots = jnp.arange(s_n, dtype=jnp.int32)

    def one(slot, seed, base):
        return jax.vmap(
            lambda i: request_key(rng, step_tag, slot, seed, base + i)
        )(jnp.arange(big_l, dtype=jnp.int32))

    return jax.vmap(one)(slots, seeds, samp_cnt)


# ------------------------------------------------------- adaptive control


class SpecController:
    """Host-side adaptive speculation policy (one per engine).

    Speculation pays for itself only while drafts get accepted: each
    round forwards ``1 + k`` positions to commit ``1 + accepted``, so a
    workload whose drafts keep rejecting must NOT pay the full chunked
    block every step. The controller runs a three-state loop:

    * ``probe`` (the cold-start state): the next spec step runs only a
      couple of draft-verify rounds — a cheap acceptance measurement,
      not a full block. Acceptance at or above `min_rate` (accepted
      drafts per round) promotes to ``full``; below it the engine
      drops to plain blocks for a hold.
    * ``full``: full `spec_rounds` blocks, with an EMA of per-round
      acceptance; the EMA sinking under `min_rate` demotes to a hold.
    * hold: plain block decoding for `probe_every` steps, DOUBLING on
      every failed probe (capped at ``probe_every x max_hold_mult``) —
      exponential backoff bounds the adversarial overhead to a few
      cheap probes over the whole run, while a workload that turns
      predictable again is picked up at the next probe.

    The acceptance EMA resets on demotion, so a probe is judged on its
    own evidence, not on the stale history that caused the hold.
    """

    def __init__(self, min_rate: float = 1.0, probe_every: int = 8,
                 decay: float = 0.7, max_hold_mult: int = 16):
        if probe_every < 1:
            raise ValueError(f"probe_every must be >= 1, got {probe_every}")
        self.min_rate = min_rate
        self.probe_every = probe_every
        self.decay = decay
        self.max_hold = probe_every * max_hold_mult
        self.ema: float | None = None
        self._mode = "probe"  # cold start: measure before committing
        self._hold = 0
        self._hold_len = probe_every
        self.fallback_steps = 0
        self.probes = 0

    def decide(self) -> str:
        """Called once per decode step: "full" = full spec block,
        "probe" = short measurement block, "off" = plain block."""
        if self._hold > 0:
            self._hold -= 1
            self.fallback_steps += 1
            return "off"
        if self._mode == "probe":
            self.probes += 1
            return "probe"
        return "full"

    def hold(self, steps: int) -> None:
        """External hold (the degradation ladder's rung 2): force plain
        blocks for at least `steps` upcoming decode steps WITHOUT
        touching the acceptance EMA or the backoff schedule — when the
        ladder steps back down, the controller resumes exactly the
        adaptive state it held before the squeeze."""
        if steps > 0:
            self._hold = max(self._hold, steps)

    def observe(self, accepted: int, rounds: int) -> None:
        """Feed one spec call's outcome (accepted drafts over `rounds`
        draft-verify rounds across the drafting slots)."""
        if rounds <= 0:
            return
        rate = accepted / rounds
        self.ema = rate if self.ema is None else (
            self.decay * self.ema + (1.0 - self.decay) * rate)
        if self.ema >= self.min_rate:
            self._mode = "full"
            self._hold_len = self.probe_every  # recovered: reset backoff
        else:
            self._mode = "probe"
            self._hold = self._hold_len
            self._hold_len = min(self._hold_len * 2, self.max_hold)
            self.ema = None  # the next probe is judged fresh

    def stats(self) -> dict:
        return {
            "acceptance_ema": (round(self.ema, 4)
                               if self.ema is not None else None),
            "mode": "hold" if self._hold > 0 else self._mode,
            "fallback_steps": self.fallback_steps,
            "probes": self.probes,
        }
