"""Request-scoped SLO accounting: attainment, error-budget burn, goodput.

Aggregate latency percentiles (serve/metrics.py) say how the engine is
doing on average; they say nothing about whether it is doing what each
CLASS of traffic was promised. An Orca-style iteration-level scheduler
can silently trade interactive TTFT for batch throughput under load —
the histograms keep looking healthy while every interactive user waits.
This module is the per-class accounting that makes the trade visible,
and the substrate the DistServe-style disaggregated phase (ROADMAP item
2's stretch goal) optimizes against:

* SLO classes — each request carries a `SamplingParams.slo` tier
  (untagged requests default to ``"standard"``); per-class latency
  targets live in `ServeConfig.slo_targets` (class -> targets dict,
  `DEFAULT_SLO_TARGETS` below is the reference three-tier shape).
* Attainment — a finished request ATTAINS its SLO when every configured
  target holds: TTFT (submit -> first token), mean ITL (decode wall /
  emitted gaps), and e2e (submit -> finish). Cancelled and engine-error
  finishes are excluded (the client or the host failed, not the latency
  contract); timeouts count as violations (that IS the latency contract
  failing).
* Error-budget burn rate — the SRE control signal: violation rate over
  the recent `burn_window` finishes divided by the class's error budget
  (``1 - objective``). 1.0 means violations arrive exactly at the rate
  the objective tolerates; sustained > 1 means the budget is burning
  and the scheduler/capacity needs attention.
* Goodput — tokens delivered by SLO-attained requests only, the metric
  serving papers (DistServe) optimize: raw tokens/sec can rise while
  goodput falls (the engine is busy finishing requests nobody is still
  waiting for). Exposed as `serve/goodput_tokens[_per_s]`.

Pure host-side bookkeeping on the finish path — no device work, no new
program shapes; the serve-bench ``--slo`` arm holds the whole observatory
(SLO tracking + histogram backend) to the PR-4/5 <= 2% paired budget.
"""

from __future__ import annotations

from collections import deque

__all__ = ["DEFAULT_SLO_TARGETS", "SLO_METRICS", "SloTracker",
           "request_latencies"]

# the latency dimensions a class may target (seconds); a class dict may
# set any non-empty subset plus an "objective" (attainment fraction the
# error budget is derived from)
SLO_METRICS = ("ttft_s", "itl_s", "e2e_s")

# reference three-tier shape: interactive chat, standard API traffic,
# and offline batch. Values are seconds and deliberately loose enough
# for CPU bench hardware; production deployments pass their own dict.
DEFAULT_SLO_TARGETS = {
    "interactive": {"ttft_s": 0.5, "itl_s": 0.05, "e2e_s": 10.0,
                    "objective": 0.99},
    "standard": {"ttft_s": 2.0, "itl_s": 0.2, "e2e_s": 60.0,
                 "objective": 0.95},
    "batch": {"ttft_s": 30.0, "itl_s": 1.0, "e2e_s": 600.0,
              "objective": 0.9},
}

DEFAULT_CLASS = "standard"

# finish reasons that never count against (or for) an SLO: the client
# walked away, the engine itself failed, or the stream moved to a peer
# replica mid-flight (fleet drain — the ADOPTING replica owns the
# latency outcome; the drained one force-finishing "migrated" must not
# burn its own budget on a stream it deliberately handed off)
_EXCLUDED_REASONS = ("cancelled", "error", "migrated")


def request_latencies(req, now: float) -> dict[str, float]:
    """The request's observable latency dimensions from its own
    lifecycle timestamps (the SAME clock readings the flight recorder's
    spans and the latency histograms use, so the three surfaces can
    never disagree). A request that timed out before its first token
    has no ttft/itl observation — the attainment check treats a missing
    observation for a configured target as a violation iff the request
    never got that far (it certainly did not meet the target)."""
    out = {"e2e_s": max(now - req.submit_time, 0.0)}
    if req.first_token_time is not None:
        out["ttft_s"] = max(req.first_token_time - req.submit_time, 0.0)
        n_gaps = len(req.tokens) - 1
        if n_gaps > 0 and req.finish_time is not None:
            out["itl_s"] = max(
                req.finish_time - req.first_token_time, 0.0
            ) / n_gaps
    return out


class SloTracker:
    """Per-class attainment / burn-rate / goodput accounting.

    One instance per engine (`ServeConfig.slo_targets`); `observe` runs
    once per finish on the host loop — O(#targets) with no allocation
    beyond the result dict the request keeps for its debug timeline.
    """

    def __init__(self, targets: dict, burn_window: int = 256):
        if not isinstance(targets, dict) or not targets:
            raise ValueError(
                "slo_targets must be a non-empty dict of "
                "{class: {ttft_s/itl_s/e2e_s/objective}}"
            )
        if DEFAULT_CLASS not in targets:
            raise ValueError(
                f"slo_targets must define the {DEFAULT_CLASS!r} class — "
                "untagged requests fall into it, and a config that "
                "silently untracked them would under-count every burn"
            )
        if burn_window < 1:
            raise ValueError(
                f"burn_window must be >= 1, got {burn_window}"
            )
        self.targets: dict[str, dict] = {}
        for cls, spec in targets.items():
            if not isinstance(spec, dict):
                raise ValueError(
                    f"slo_targets[{cls!r}] must be a dict, got "
                    f"{type(spec).__name__}"
                )
            unknown = set(spec) - set(SLO_METRICS) - {"objective"}
            if unknown:
                raise ValueError(
                    f"slo_targets[{cls!r}] has unknown keys {sorted(unknown)} "
                    f"(allowed: {SLO_METRICS + ('objective',)})"
                )
            if not any(m in spec for m in SLO_METRICS):
                raise ValueError(
                    f"slo_targets[{cls!r}] sets no latency target "
                    f"(need at least one of {SLO_METRICS})"
                )
            for m in SLO_METRICS:
                if m in spec and not spec[m] > 0:
                    raise ValueError(
                        f"slo_targets[{cls!r}][{m!r}] must be > 0, "
                        f"got {spec[m]}"
                    )
            obj = spec.get("objective", 0.99)
            if not 0.0 < obj < 1.0:
                raise ValueError(
                    f"slo_targets[{cls!r}]['objective'] must be in (0, 1), "
                    f"got {obj}"
                )
            self.targets[cls] = {**{m: spec[m] for m in SLO_METRICS
                                    if m in spec},
                                 "objective": obj}
        self._stats = {
            cls: {
                "finished": 0,
                "attained": 0,
                "violations": dict.fromkeys(SLO_METRICS, 0),
                "window": deque(maxlen=burn_window),
            }
            for cls in self.targets
        }
        self.goodput_tokens = 0
        self.excluded = 0

    def classify(self, req) -> str:
        return req.params.slo or DEFAULT_CLASS

    # ------------------------------------------------------------ record

    def observe(self, req, now: float) -> dict | None:
        """Account one finished request; returns the per-request verdict
        (class / attained / violated metrics / latencies) that the HTTP
        debug timeline carries, or None for excluded finishes."""
        if req.finish_reason in _EXCLUDED_REASONS:
            self.excluded += 1
            return None
        cls = self.classify(req)
        spec = self.targets[cls]
        lat = request_latencies(req, now)
        violated = []
        for m in SLO_METRICS:
            if m not in spec:
                continue
            seen = lat.get(m)
            if seen is None:
                # configured target the request never reached (e.g. a
                # queue timeout before its first token): a violation —
                # "no observation" must not read as "attained"
                violated.append(m)
            elif seen > spec[m]:
                violated.append(m)
        attained = not violated
        st = self._stats[cls]
        st["finished"] += 1
        st["window"].append(attained)
        if attained:
            st["attained"] += 1
            self.goodput_tokens += len(req.tokens)
        else:
            for m in violated:
                st["violations"][m] += 1
        return {
            "class": cls,
            "attained": attained,
            "violated": violated,
            "latencies": {k: round(v, 6) for k, v in lat.items()},
            "targets": {m: spec[m] for m in SLO_METRICS if m in spec},
        }

    # ----------------------------------------------------------- surface

    def burn_rate(self, cls: str) -> float:
        """Windowed violation rate / error budget. 0 with an empty
        window (no invented burn before traffic arrives)."""
        st = self._stats[cls]
        if not st["window"]:
            return 0.0
        viol = st["window"].count(False) / len(st["window"])
        budget = 1.0 - self.targets[cls]["objective"]
        return viol / budget

    def gauges(self, elapsed_s: float) -> dict[str, float]:
        """The slo/* + goodput gauge family (riding ServeMetrics
        snapshots via the engine's provider — present iff slo_targets
        is configured, per the conditional-key-surface discipline).
        Attainment/burn appear once a class has finishes; rate keys
        once the metrics window is open (same absent-beats-NaN rule as
        serve/tokens_per_sec)."""
        out: dict[str, float] = {}
        for cls, st in self._stats.items():
            out[f"slo/{cls}_finished"] = float(st["finished"])
            if st["finished"]:
                out[f"slo/{cls}_attainment"] = (
                    st["attained"] / st["finished"]
                )
                out[f"slo/{cls}_burn_rate"] = self.burn_rate(cls)
        out["serve/goodput_tokens"] = float(self.goodput_tokens)
        if elapsed_s > 0:
            out["serve/goodput_tokens_per_s"] = (
                self.goodput_tokens / elapsed_s
            )
        return out

    def statusz(self) -> dict:
        """The /statusz `slo` section: per-class accounting + targets."""
        classes = {}
        for cls, st in self._stats.items():
            spec = self.targets[cls]
            classes[cls] = {
                "targets": {m: spec[m] for m in SLO_METRICS if m in spec},
                "objective": spec["objective"],
                "finished": st["finished"],
                "attained": st["attained"],
                "attainment": round(st["attained"] / st["finished"], 4)
                if st["finished"] else None,
                "burn_rate": round(self.burn_rate(cls), 4)
                if st["window"] else None,
                "violations": {m: v for m, v in st["violations"].items()
                               if v},
            }
        return {
            "classes": classes,
            "goodput_tokens": self.goodput_tokens,
            "excluded_finishes": self.excluded,
        }
