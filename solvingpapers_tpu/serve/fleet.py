"""Fleet serving: N independent `ServeEngine` replicas behind one
router — the "millions of users" layer (ROADMAP item 2) between the
HTTP front door (serve/api.py) and the engines.

PRs 11–14 shipped the three prerequisites without cashing them in:
exactly-mergeable per-replica latency histograms (metrics/hist.py's
merge-of-shards == shard-of-merged contract), a readiness-aware
`/healthz` state machine built for a load balancer, and a write-ahead
journal that makes any stream resumable on any process that can read it
(serve/journal.py + `ServeEngine.recover`). `FleetRouter` composes
them: each replica is a full engine with its own `EngineLoop`, KV pool,
journal file, and metrics — no shared device state, so a replica's
fault blast radius stays its own (the vLLM-style replication shape, as
opposed to DistServe-style role splitting, which this layer does not
attempt).

Routing composes three signals, in order:

    health     a replica that is draining, whose loop thread died, or
               whose fault-plane health says "unhealthy" receives no
               new admissions — the same gate its own /healthz exposes
               to an external balancer, applied internally.
    SLO burn   the request's SLO class avoids replicas whose windowed
               error-budget burn rate for that class exceeds
               `burn_threshold` (serve/slo.py `SloTracker.burn_rate`),
               unless every candidate is burning — interactive traffic
               steers around a replica that is missing its latency
               targets while batch traffic keeps it busy.
    affinity   the replica whose prefix-cache radix tree covers the
               longest page-aligned prompt prefix wins (the host-side
               `PrefixCache.peek` via `ServeEngine._match_len`, taken
               under that replica's step lock — the tree mutates on its
               engine thread). A cache hit is a host-side page-table
               append instead of a device prefill, so affinity is the
               difference between O(prompt) and O(suffix) admission
               cost; least-loaded (free fraction of the scarcest
               resource: pages on a paged pool, slots otherwise, then
               queue room, then replica id) breaks ties and decides
               when no replica covers any prefix.

`submit` walks the ranked candidates: a replica whose waiting queue is
full rejects host-side and the router retries the next candidate
instead of bouncing the client — the fleet-wide fix for single-replica
503s (serve/api.py consults `FleetRouter.capacity_left`, the SUM of
admitting replicas' queue room, before burning a submission).

Observability rides the existing primitives: `prom_sets()` feeds
`PrometheusTextWriter.render_sets` one UNLABELED merged set (fleet
gauges + the exact `LogHistogram` merge of every replica's latency
histograms, taken under each replica's step lock — so
`histogram_quantile` over the merged series equals the quantile over
the union of observations) plus one ``replica="rN"``-labeled set per
replica; `statusz()` is the `/statusz` ``fleet`` section with
per-replica occupancy/health/rung and the routing counters.

The headline capability is journal-backed zero-drop stream migration:
`drain(replica)` generalizes PR 14's crash-restart to a LIVE rolling
upgrade. Under the drained replica's step lock, its journal is synced
and the live entries snapshotted, then every in-flight request is
force-finished host-side with reason ``"migrated"`` (slots, pages and
lanes reclaim through the ordinary finish paths — the drained replica
passes the zero-leak invariant). Each snapshotted entry is adopted by
the best admitting peer (`ServeEngine.adopt`: journaled into the peer,
requeued through the `recover()` preemption-resume path — token-exact
for greedy and seeded plain-decode streams). The SSE side: the front
door closes a ``"migrated"`` stream WITHOUT a terminal chunk, the
client reconnects with its Last-Event-ID cursor, and the cursor
resolves on the peer through the same recovered-set path a crash
restart uses — zero dropped streams, byte-identical transcripts
(pinned in tests/test_fleet.py; measured in BENCH_serve.json's
``serve_fleet_migrated_streams`` entry). ``"migrated"`` is excluded
from SLO accounting on the drained replica (serve/slo.py) — the
adopting replica owns the latency outcome.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import OrderedDict

import numpy as np

from solvingpapers_tpu.metrics.hist import LogHistogram
from solvingpapers_tpu.metrics.trace import (FlightRecorder,
                                             fleet_events_to_chrome)
from solvingpapers_tpu.serve import metrics as smetrics
from solvingpapers_tpu.serve.api import EngineLoop

__all__ = ["FleetRouter", "MigrationReport", "Replica"]


class Replica:
    """One engine + its driver loop under a fleet id ("r0", "r1", ...).

    Thin by design: the engine keeps owning its pool/journal/metrics
    and the loop keeps owning the step thread; the replica adds only
    the fleet-facing facts (id, draining flag, admission gate, the
    locked prefix probe)."""

    def __init__(self, rid: str, engine, loop=None, start: bool = True):
        self.rid = rid
        self.engine = engine
        self.loop = loop if loop is not None else EngineLoop(
            engine, start=start)
        # drain() sets this before touching the journal: the admission
        # gate must close FIRST so no new stream lands between the
        # snapshot and the force-drain (undrain() reopens it)
        self.draining = False

    @property
    def admitting(self) -> bool:
        """May this replica receive NEW admissions? Draining replicas,
        replicas whose loop thread died, and replicas whose fault-plane
        health machine says "unhealthy" are out — the same signals the
        replica's own /healthz would serve an external balancer."""
        return (not self.draining and self.loop.error is None
                and getattr(self.engine, "health", "healthy")
                != "unhealthy")

    def free_fraction(self) -> float:
        """Free fraction of the SCARCEST pool resource — pages on a
        paged pool (slots stop being the binding constraint there),
        slots otherwise. Host-mirror reads, safe without the lock."""
        pool = self.engine.pool
        budget = getattr(pool, "page_budget", 0)
        if budget:
            return pool.pages_free / budget
        return pool.n_free / max(pool.n_slots, 1)

    def probe(self, prompt: np.ndarray) -> int:
        """Cached-prefix match length for `prompt` on THIS replica,
        under its step lock (the radix tree mutates on the engine
        thread; `PrefixCache.peek` is read-only — no LRU touch, so
        routing probes cannot evict what they are looking for)."""
        eng = self.engine
        if getattr(eng, "prefix_cache", None) is None:
            return 0
        return self.loop._locked(lambda: eng._match_len(prompt))


@dataclasses.dataclass
class MigrationReport:
    """What one `FleetRouter.drain` did: which streams moved where.

    `targets` maps each migrated journal id to ``(peer_rid, new_rid)``
    — `new_rid` differs from the original only when the peer's journal
    already had a live entry under that id (the adopt re-key rule).
    `errors` holds ``(rid, reason)`` for entries no peer could adopt
    (they finished "migrated" on the drained replica and their journal
    record is the only trace — honest loss accounting, never silent)."""

    replica: str
    entries: int
    migrated: list
    targets: dict
    errors: list
    wall_s: float


class FleetRouter:
    """N `ServeEngine` replicas behind one submit surface (module
    docstring has the policy). Construct with the engines (each gets a
    `Replica` + started `EngineLoop`; pass ``start=False`` for
    manually-stepped benches/tests) and hand the router to `ApiServer`
    — the front door keeps its single-engine API surface and routes
    through here when a router is present."""

    # bounded like the front door's timelines registry: the owner map
    # only accelerates cancel/resume lookups — an evicted id falls back
    # to scanning the replicas' recovered sets and journals
    owner_cap = 4096

    def __init__(self, engines, *, replica_ids=None,
                 burn_threshold: float = 1.0, start: bool = True,
                 stale_shard_cutoff_s: float = 300.0):
        engines = list(engines)
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        paths = [getattr(e.config, "journal_path", None) for e in engines]
        dup = {p for p in paths if p is not None and paths.count(p) > 1}
        if dup:
            raise ValueError(
                f"replicas share a journal file ({sorted(dup)}): each "
                "replica needs its OWN journal — interleaved writers "
                "would corrupt recovery and drain migration"
            )
        ids = (list(replica_ids) if replica_ids is not None
               else [f"r{i}" for i in range(len(engines))])
        if len(ids) != len(engines) or len(set(ids)) != len(ids):
            raise ValueError(
                "replica_ids must be unique, one per engine")
        self.replicas = [Replica(rid, eng, start=start)
                         for rid, eng in zip(ids, engines)]
        self._by_id = {r.rid: r for r in self.replicas}
        # burn rate above which a replica stops receiving traffic of
        # the burning class (1.0 = the error budget is fully consumed
        # over the window); >= everything disables the gate
        self.burn_threshold = burn_threshold
        # a non-admitting replica whose metrics shard has seen no
        # traffic for longer than this is EXCLUDED from the /metrics
        # N-way histogram merge (its numbers describe a rotation it is
        # no longer part of); the labeled per-replica set still serves
        # the shard, tagged with serve/shard_age_s + serve/shard_stale
        self.stale_shard_cutoff_s = stale_shard_cutoff_s
        self._lock = threading.Lock()
        self._owners: OrderedDict[str, Replica] = OrderedDict()
        self.stats = {
            "routed": 0, "affinity_hits": 0, "burn_avoided": 0,
            "rerouted_full": 0, "drains": 0, "migrated_streams": 0,
            "migration_errors": 0,
        }
        # the router's own flight recorder: route-decision spans with
        # per-candidate scores, reroute attempts, drain/migration hops
        # — created iff any replica records (same opt-in as the
        # engines', on the SAME patchable clock, so the stitched fleet
        # export aligns router and replica timelines on one time base)
        self.trace: FlightRecorder | None = None
        traced = [e for e in engines
                  if getattr(e, "trace", None) is not None]
        if traced:
            self.trace = FlightRecorder(
                capacity=getattr(traced[0].config, "trace_capacity",
                                 65536),
                clock=smetrics.now,
            )

    # ------------------------------------------------------------ routing

    def replica(self, rid: str) -> Replica:
        try:
            return self._by_id[rid]
        except KeyError:
            raise KeyError(
                f"unknown replica {rid!r} (have "
                f"{sorted(self._by_id)})") from None

    def _rank(self, prompt: np.ndarray, slo: str | None
              ) -> tuple[list[Replica], list[dict]]:
        """Admitting replicas, best first: health gate -> per-class
        burn gate -> prefix affinity -> least-loaded (free fraction of
        the scarcest resource, then queue room, then replica id).
        Returns ``(ranked, scores)``: one score row per replica (the
        route-decision evidence the router's trace span records) —
        ranked candidates carry the signals the sort used, excluded
        replicas carry the gate that dropped them."""
        excluded: dict[str, str] = {
            r.rid: "not_admitting"
            for r in self.replicas if not r.admitting
        }
        cands = [r for r in self.replicas if r.admitting]
        if cands and slo is not None and len(cands) > 1:
            cool = [
                r for r in cands
                if r.engine._slo is None
                or slo not in r.engine._slo.targets
                or r.engine._slo.burn_rate(slo) <= self.burn_threshold
            ]
            if cool and len(cool) < len(cands):
                with self._lock:
                    self.stats["burn_avoided"] += 1
                for r in cands:
                    if r not in cool:
                        excluded[r.rid] = "burn"
                cands = cool
        matches = {r.rid: r.probe(prompt) for r in cands}
        best = max(matches.values(), default=0)
        if best > 0:
            with self._lock:
                self.stats["affinity_hits"] += 1

        def key(r: Replica):
            # longest cached prefix first; then emptiest, then roomiest
            # queue; replica id last so ranking is deterministic
            return (-matches[r.rid], -r.free_fraction(),
                    -r.engine.scheduler.capacity_left, r.rid)

        ranked = sorted(cands, key=key)
        scores = [
            {"replica": r.rid, "match": matches[r.rid],
             "free": round(r.free_fraction(), 4),
             "queue_room": r.engine.scheduler.capacity_left}
            for r in ranked
        ]
        scores += [{"replica": rid, "excluded": why}
                   for rid, why in sorted(excluded.items())]
        return ranked, scores

    def route(self, prompt, slo: str | None = None) -> Replica | None:
        """The admission replica for `prompt` (None when nothing
        admits); `submit` is the same ranking with full-queue retry."""
        ranked, _ = self._rank(
            np.asarray(prompt, np.int32).reshape(-1), slo)
        return ranked[0] if ranked else None

    def submit(self, prompt, *, max_new_tokens: int = 64, params=None,
               deadline_s=None, grammar=None, stream_cb=None,
               trace_id=None):
        """Route + submit through the chosen replica's loop. Returns
        ``(replica, request)``; ``(None, None)`` when no replica admits
        (the front door 503s with the fleet Retry-After). A replica
        that rejects host-side (queue full, shed, or a health flip that
        raced the ranking) does NOT bounce the client while a peer has
        room: the router retries down the ranked list and only surfaces
        the LAST rejection when every candidate refused — the
        fleet-wide 503 fix. ValueError (a malformed request) propagates
        immediately: it would fail identically everywhere.

        The accepted request carries the routing outcome as plain
        attributes — ``fleet_reroutes`` (how many ranked peers refused
        before this one took it; the ``X-Fleet-Reroutes`` header) and
        ``fleet_route_s`` (ranking + retry wall, the trail's "route"
        phase) — so the front door's request trail works with tracing
        OFF; with the router recorder on, the same decision lands as a
        ``route`` span (per-candidate scores in args) plus one
        ``reroute`` instant per refusing peer."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        slo = getattr(params, "slo", None) if params is not None else None
        t0 = smetrics.now()
        ranked, scores = self._rank(prompt, slo)
        if not ranked:
            return None, None
        last = None
        refusals: list[tuple[float, str]] = []  # (ts, rid) per refusal
        for i, rep in enumerate(ranked):
            try:
                req = rep.loop.submit(
                    prompt, max_new_tokens=max_new_tokens, params=params,
                    deadline_s=deadline_s, grammar=grammar,
                    stream_cb=stream_cb, trace_id=trace_id,
                )
            except RuntimeError:
                # the loop died between the ranking and the submit:
                # treat like any other per-replica refusal
                refusals.append((smetrics.now(), rep.rid))
                continue
            if req.state != "rejected":
                with self._lock:
                    self.stats["routed"] += 1
                    if i:
                        self.stats["rerouted_full"] += 1
                self._remember(req.trace_id, rep)
                dur = max(smetrics.now() - t0, 0.0)
                req.fleet_reroutes = i
                req.fleet_route_s = dur
                if self.trace is not None:
                    for ts, frm in refusals:
                        self.trace.instant(
                            "reroute", "fleet", "router", req=req.id,
                            ts=ts, rid=req.trace_id, rejected_by=frm)
                    self.trace.complete(
                        "route", "fleet", "router", ts=t0, dur=dur,
                        req=req.id, rid=req.trace_id, replica=rep.rid,
                        attempts=i + 1, scores=scores)
                return rep, req
            refusals.append((smetrics.now(), rep.rid))
            last = (rep, req)
        if self.trace is not None:
            self.trace.instant(
                "route_failed", "fleet", "router", ts=smetrics.now(),
                attempts=len(ranked), scores=scores)
        if last is None:
            return None, None
        return last

    def _remember(self, rid, rep: Replica) -> None:
        if rid is None:
            return
        with self._lock:
            self._owners[rid] = rep
            self._owners.move_to_end(rid)
            while len(self._owners) > self.owner_cap:
                self._owners.popitem(last=False)

    def owner(self, rid) -> Replica | None:
        """Which replica currently owns the stream `rid` — the routed
        (or post-migration adopting) replica; falls back to scanning
        the recovered sets when the bounded owner map evicted it."""
        if rid is None:
            return None
        with self._lock:
            rep = self._owners.get(rid)
        if rep is not None:
            return rep
        for r in self.replicas:
            if rid in getattr(r.engine, "_recovered", {}):
                return r
        return None

    def owner_loop(self, req) -> EngineLoop:
        """The loop that owns `req` (for cancel) — replica 0's loop
        when the owner is unknown (cancel on the wrong engine is a
        no-op: `engine.cancel` matches by identity)."""
        rep = self.owner(getattr(req, "trace_id", None))
        return rep.loop if rep is not None else self.replicas[0].loop

    # ------------------------------------------------------- fleet views

    @property
    def capacity_left(self) -> int:
        """Fleet-wide queue room (admitting replicas only) — the front
        door's backpressure probe, replacing the single-replica check
        that would 503 while a peer had room."""
        return sum(r.engine.scheduler.capacity_left
                   for r in self.replicas if r.admitting)

    @property
    def degradation_rung(self) -> int:
        """The fleet's Retry-After input: the LEAST degraded admitting
        replica (traffic routes toward it, so its rung is the honest
        backoff hint); the max over everyone when nothing admits."""
        rungs = [getattr(r.engine, "degradation_rung", 0)
                 for r in self.replicas if r.admitting]
        if rungs:
            return min(rungs)
        return max((getattr(r.engine, "degradation_rung", 0)
                    for r in self.replicas), default=0)

    @property
    def health(self) -> str:
        """/healthz for the fleet: healthy while ANY admitting replica
        is healthy (the router steers around the rest), degraded while
        only degraded replicas admit, unhealthy when nothing admits."""
        states = [r.engine.health for r in self.replicas if r.admitting]
        if any(s == "healthy" for s in states):
            return "healthy"
        if states:
            return "degraded"
        return "unhealthy"

    def prom_sets(self):
        """``[(step, labels, metrics), ...]`` for
        `PrometheusTextWriter.render_sets`: the UNLABELED merged set
        first (fleet gauges + the exact `LogHistogram` merge of every
        replica's latency histograms — `histogram_quantile` over the
        merged series equals the quantile over the union), then one
        ``replica="rN"``-labeled set per replica. Each replica's
        snapshot AND the merge of its live histograms happen under its
        step lock, so a histogram mid-`add` can never tear the merged
        series (the merge itself is also copy-safe — hist.merge_from).

        Staleness: a shard that stopped moving describes a rotation
        the replica is no longer part of — silently merging it skews
        the fleet quantiles toward history. Every labeled set carries
        ``serve/shard_age_s`` (seconds since the shard last recorded)
        and ``serve/shard_stale`` (1 when the replica is NOT admitting
        and its age exceeds `stale_shard_cutoff_s`); stale shards are
        SKIPPED by the histogram merge (tagged, not silently merged —
        the labeled set still serves the frozen numbers) and counted
        in ``fleet/stale_shards``."""
        merged: dict[str, LogHistogram] = {}
        per = []
        max_step = 0
        stale_shards = 0
        for r in self.replicas:
            m = r.engine.metrics
            ref = m._t_last if m._t_last is not None else m._t_first
            age = (max(smetrics.now() - ref, 0.0)
                   if ref is not None else 0.0)
            stale = (not r.admitting
                     and age > self.stale_shard_cutoff_s)
            stale_shards += stale

            def grab(eng=r.engine, stale=stale):
                snap = eng.metrics.prom_snapshot()
                if not stale:
                    for k, v in snap.items():
                        if isinstance(v, LogHistogram):
                            acc = merged.get(k)
                            if acc is None:
                                merged[k] = acc = LogHistogram(
                                    *v.layout[:2],
                                    buckets_per_decade=v.layout[2])
                            acc.merge_from(v)
                return eng._step_idx, snap
            step, snap = r.loop._locked(grab)
            snap["serve/shard_age_s"] = round(age, 3)
            snap["serve/shard_stale"] = float(stale)
            max_step = max(max_step, step)
            per.append((step, {"replica": r.rid}, snap))
        fleet = {
            "fleet/replicas": float(len(self.replicas)),
            "fleet/admitting": float(
                sum(r.admitting for r in self.replicas)),
            "fleet/draining": float(
                sum(r.draining for r in self.replicas)),
            "fleet/capacity_left": float(self.capacity_left),
            "fleet/stale_shards": float(stale_shards),
        }
        with self._lock:
            for k, v in self.stats.items():
                fleet[f"fleet/{k}"] = float(v)
        fleet.update(merged)
        return [(max_step, None, fleet)] + per

    def statusz(self) -> dict:
        """The /statusz ``fleet`` section: per-replica admission facts
        (host-mirror reads — safe from request threads, same contract
        as `ServeEngine.statusz`) plus policy + routing counters."""
        reps = {}
        for r in self.replicas:
            eng = r.engine
            d = {
                "health": getattr(eng, "health", "healthy"),
                "draining": r.draining,
                "admitting": r.admitting,
                "rung": getattr(eng, "degradation_rung", 0),
                "loop_error": (None if r.loop.error is None else
                               f"{type(r.loop.error).__name__}: "
                               f"{r.loop.error}"),
                "step": eng._step_idx,
                "occupancy": round(eng.pool.occupancy, 4),
                "n_free": eng.pool.n_free,
                "queue_depth": len(eng.scheduler),
                "capacity_left": eng.scheduler.capacity_left,
                "recovered_requests": eng._recovered_total,
            }
            if getattr(eng.pool, "page_budget", 0):
                d["pages_free"] = eng.pool.pages_free
            reps[r.rid] = d
        with self._lock:
            routing = dict(self.stats)
        return {
            "replicas": reps,
            "policy": {"burn_threshold": self.burn_threshold},
            "routing": routing,
        }

    def timeseriesz(self) -> dict:
        """The fleet ``/timeseriesz`` body: one rolling-retrospective
        doc per replica that keeps one (`ServeConfig.timeseries`)."""
        out = {}
        for r in self.replicas:
            store = getattr(r.engine, "timeseries", None)
            if store is not None:
                out[r.rid] = store.doc()
        return {"replicas": out}

    # ----------------------------------------------------- stitched export

    def to_chrome_fleet(self) -> dict:
        """ONE Chrome trace for the whole fleet: the router recorder
        plus every replica recorder stitched process-per-replica
        (metrics/trace.fleet_events_to_chrome — all recorders share
        the engine clock, so one t0 aligns the sections; flows follow
        each request across reroutes and migrations via the rid args
        the router spans and engine submit instants carry)."""
        sections = []
        if self.trace is not None:
            sections.append(("router", self.trace.events()))
        for r in self.replicas:
            rec = getattr(r.engine, "trace", None)
            if rec is not None:
                sections.append((r.rid, rec.events()))
        if not sections:
            raise ValueError(
                "no recorders to stitch: run the replicas with "
                "ServeConfig.trace=True")
        return fleet_events_to_chrome(sections)

    def export_chrome_fleet(self, path: str) -> str:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_fleet(), f)
        return path

    # ------------------------------------------------------------- drain

    def undrain(self, rid: str) -> None:
        """Reopen admissions to a drained replica (rolling upgrade done
        — the process came back; its journal starts empty of live
        entries, everything migrated out)."""
        self.replica(rid).draining = False

    def drain(self, rid: str, *, peer_slo_route: bool = True
              ) -> MigrationReport:
        """Stop admissions to `rid` and migrate every live stream to a
        peer — the journal-backed zero-drop rolling-upgrade drain.

        Protocol (the SSE half lives in serve/api.py):

        1. the replica's admission gate closes (`draining`), so the
           router sends it nothing new while the snapshot runs;
        2. under its step lock, in ONE critical section: the journal is
           synced, its live entries snapshotted (token lists copied —
           the entry objects keep mutating), and every in-flight
           request force-finished host-side with reason ``"migrated"``
           (`ServeEngine.force_drain`: slots/pages/lanes reclaim
           through the ordinary finish paths, so the drained replica
           passes `assert_no_leaks`; the finish lands in its journal).
           The single critical section is load-bearing: a token decoded
           AFTER the snapshot but BEFORE the stop would put the
           client's Last-Event-ID cursor past the peer's committed
           prefix — a 409 instead of a resume;
        3. each snapshotted entry is adopted by the best admitting peer
           (`ServeEngine.adopt` under the peer's lock: journaled into
           the peer, requeued through the recover() preemption-resume
           path — token-exact for greedy and seeded plain-decode
           streams), newest-first so the oldest ends at each peer's
           queue head (FIFO survives the migration). The owner map
           flips so reconnects and cancels follow the stream.

        The front door closes a ``"migrated"`` SSE stream WITHOUT a
        terminal chunk — the client's signal to reconnect with its
        Last-Event-ID cursor, which resolves on the peer through the
        recovered-set path. Entries no peer can adopt are reported in
        `MigrationReport.errors`, never silently dropped. The drained
        replica stays up (draining, zero streams) for its clients to
        finish reading; `undrain` reopens it.

        Raises KeyError for an unknown replica, ValueError when `rid`
        has no journal (migration IS journal replay), RuntimeError when
        no peer admits (the drain would drop streams — refused)."""
        rep = self.replica(rid)
        if rep.engine.journal is None:
            raise ValueError(
                f"drain({rid!r}) migrates via the write-ahead journal; "
                "the replica has no journal_path")
        if not any(r is not rep and r.admitting for r in self.replicas):
            raise RuntimeError(
                f"no admitting peer to drain {rid!r} into — refusing "
                "to drop its live streams")
        t0 = time.monotonic()
        t_d0 = smetrics.now()  # trace time base (patchable in tests)
        rep.draining = True

        def freeze(eng=rep.engine):
            eng.journal.sync()
            entries = [
                dataclasses.replace(e, tokens=list(e.tokens))
                for e in eng.journal.live_entries()
            ]
            eng.force_drain("migrated")
            return entries

        entries = rep.loop._locked(freeze)
        migrated, errors, targets = [], [], {}
        for e in reversed(entries):  # newest-first: see the docstring
            t_m0 = smetrics.now()
            slo = (e.params or {}).get("slo") if peer_slo_route else None
            target = self.route(np.asarray(e.prompt, np.int32), slo=slo)
            if target is None or target is rep:
                errors.append((e.rid, "no admitting peer"))
                continue
            try:
                req = target.loop._locked(
                    lambda eng=target.engine, e=e: eng.adopt(e))
            except ValueError as exc:
                errors.append((e.rid, str(exc)))
                continue
            target.loop._wake.set()
            self._remember(req.trace_id, target)
            targets[e.rid] = (target.rid, req.trace_id)
            migrated.append(req)
            if self.trace is not None:
                # the migration hop: freeze-to-adopt on the router's
                # lane, carrying the rid so the stitched flow follows
                # the stream from the drained replica to its peer
                self.trace.complete(
                    "migrate", "fleet", "router", ts=t_m0,
                    dur=max(smetrics.now() - t_m0, 0.0), req=req.id,
                    rid=req.trace_id, src=rid, dst=target.rid,
                    old_rid=e.rid)
        migrated.reverse()  # report in arrival order
        with self._lock:
            self.stats["drains"] += 1
            self.stats["migrated_streams"] += len(migrated)
            self.stats["migration_errors"] += len(errors)
        if self.trace is not None:
            self.trace.complete(
                "drain", "fleet", "router", ts=t_d0,
                dur=max(smetrics.now() - t_d0, 0.0), replica=rid,
                entries=len(entries), migrated=len(migrated),
                errors=len(errors))
        return MigrationReport(
            replica=rid, entries=len(entries), migrated=migrated,
            targets=targets, errors=errors,
            wall_s=time.monotonic() - t0,
        )

    # ------------------------------------------------------------- close

    def close(self, drain_timeout_s: float = 0.0) -> None:
        """Close every replica (loop then engine), sharing ONE drain
        budget across the fleet — the front door's close() deadline
        semantics, not per-replica multiplication."""
        deadline = time.monotonic() + max(drain_timeout_s, 0.0)
        for r in self.replicas:
            left = max(deadline - time.monotonic(), 0.0)
            r.loop.close(drain_timeout_s=left)
            r.engine.close()
