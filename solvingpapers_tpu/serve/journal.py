"""Request write-ahead journal: crash-safe durability for the serving
engine.

PR 12 made the engine survive faults INSIDE a live process (quarantine,
pool-rebuild retries, the degradation ladder), but the process boundary
stayed the single point of total loss: a crash, OOM-kill or SIGKILL
dropped every in-flight request with no trace, and a reconnecting SSE
client got nothing back. This module is the missing durability layer —
an append-only JSONL write-ahead journal (`ServeConfig.journal_path`;
None-pattern off, like the tracer and the fault plane) the engine
writes three event kinds into:

    submit   request identity + everything needed to replay it: journal
             id (the HTTP front door's X-Request-Id when one exists),
             prompt token ids, the full SamplingParams (incl. seed and
             SLO class), max_new_tokens / eos_id, arrival time
    commit   committed token ids — written once per DECODE-BLOCK
             boundary riding the existing host-mirror drain (never per
             token: the journal's granularity is the engine's, so the
             hot loop gains one buffered write per block, not per draw)
    finish   lifecycle outcome (reason) + usage

Durability contract: every record is ONE `write()` of one newline-
terminated JSON line under the journal lock (concurrent writers —
engine loop + HTTP handler threads — can interleave records but never
tear one), flushed to the OS immediately; `fsync` is BATCHED once per
engine step (`Journal.sync`), so a hard kill loses at most one step's
worth of records — the same boundary at which the engine commits
tokens to streams anyway. The loader tolerates a torn final line (a
crash mid-write) by ignoring it.

Bounded by compaction: finished requests' records are dead weight, so
once `rotate_bytes` of file or `rotate_finished` finish records
accumulate, the journal REWRITES itself to just the live set (one
submit record per unfinished request with its committed tokens folded
in) via atomic tmp + fsync + rename — the journal stays O(active
requests), never O(requests ever served). A bounded in-memory map of
recently finished entries survives rotation so `/v1/requests/<id>` and
SSE reconnects can replay completed streams past the front door's
1024-entry registry.

Recovery (`ServeEngine.recover`, `cli serve --journal`): unfinished
entries replay through the engine's EXISTING preemption-resume
machinery — prefill prompt + committed tokens, discard the resampled
token, continue decoding. Because cached KV depends only on token ids
and seeded sampling chains fold only ``(seed, sample_index)``, a
recovered stream is TOKEN-EXACT vs an uninterrupted run for greedy
requests (any configuration — speculation's verify is lossless for
greedy) and for seeded stochastic requests on the plain decode path
(pinned in tests/test_journal.py across both pools and kv_quant
on/off). Seeded stochastic streams under SPECULATION are
distribution-exact but not replay-exact across the resume point (the
committed value at a position depends on its draft-window alignment —
the same contract live paged preemption already has). Unseeded
stochastic streams keep their committed prefix and continue from
fresh entropy (no reproducibility contract to preserve).
Grammar-constrained requests are journaled but NOT resumed
(their stepper is host state the journal does not capture) — recovery
finishes them ``"error"`` honestly instead of silently dropping them.

Failure policy: journal I/O failures (disk full; injected via the
fault plane's ``journal_write`` site, kind ``io_error``) must not take
serving down with them — the engine degrades to journal-off with a
single warning and a ``serve/journal_degraded`` gauge, unless
`ServeConfig.journal_strict` is set (then the failure propagates: a
deployment that REQUIRES durability fails loudly instead of silently
serving without it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import OrderedDict

__all__ = ["Journal", "JournalEntry", "JournalError", "read_entries"]


class JournalError(RuntimeError):
    """A journal write/rotate failed (wraps the OSError); raised to the
    engine's journal boundary, which degrades to journal-off (or, under
    `journal_strict`, lets it escape)."""


@dataclasses.dataclass
class JournalEntry:
    """One request's journaled state, reconstructed by the loader and
    kept live in memory (the recovery set and the lookup surface)."""

    rid: str
    prompt: list
    max_new_tokens: int
    eos_id: int | None
    params: dict
    arrival: float
    grammar: bool = False
    # the request's ORIGINAL relative deadline budget in seconds (None =
    # no deadline); absolute deadlines cannot cross a process restart
    # (monotonic clocks reset), so recovery re-arms this budget fresh
    deadline_s: float | None = None
    tokens: list = dataclasses.field(default_factory=list)
    finished: bool = False
    finish_reason: str | None = None
    usage: dict | None = None


def read_entries(path: str, *, retries: int = 1,
                 retry_delay_s: float = 0.05) -> list[JournalEntry]:
    """Read-only snapshot of a journal file — the replay harness's
    loader (`serve/replay.py`), safe against a LIVE writer on the same
    path. Returns every reconstructible entry in arrival order, both
    finished (tokens, outcome, usage folded in) and still-live ones;
    the caller filters for its corpus.

    Concurrency contract: one whole-file read. Appends are single
    `write()` calls of newline-terminated lines, so the only partial
    line a snapshot can see is the final one — tolerated exactly like
    a crash-torn tail. Compaction (`Journal._rotate_locked`) swaps the
    file via atomic tmp + rename; an open descriptor keeps reading the
    pre-rotation inode, and the one observable race — the path briefly
    unresolvable around the swap on non-POSIX rename semantics — is
    absorbed by retrying ENOENT `retries` times before giving up.
    Mid-file corruption still raises `JournalError`: only the tail can
    legitimately be torn."""
    for attempt in range(retries + 1):
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().split("\n")
            break
        except FileNotFoundError:
            if attempt >= retries:
                raise
            time.sleep(retry_delay_s)
    entries: OrderedDict[str, JournalEntry] = OrderedDict()
    for i, line in enumerate(lines):
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i >= len(lines) - 2:
                break  # torn tail: a crash or an in-flight append
            raise JournalError(
                f"{path}:{i + 1}: malformed journal record before the "
                "final line — the file is corrupt, not merely torn"
            ) from None
        kind = rec.get("kind")
        if kind == "submit":
            e = JournalEntry(
                rid=rec["rid"], prompt=list(rec["prompt"]),
                max_new_tokens=int(rec["max_new_tokens"]),
                eos_id=rec.get("eos_id"), params=rec.get("params") or {},
                arrival=float(rec.get("arrival", 0.0)),
                grammar=bool(rec.get("grammar", False)),
                deadline_s=rec.get("deadline_s"),
                tokens=list(rec.get("tokens", ())),
            )
            # a reused rid (registry last-wins) replaces the old entry
            entries[e.rid] = e
        elif kind == "commit":
            e = entries.get(rec["rid"])
            if e is not None and not e.finished:
                e.tokens.extend(int(t) for t in rec["tokens"])
        elif kind == "finish":
            e = entries.get(rec["rid"])
            if e is not None and not e.finished:
                e.finished = True
                e.finish_reason = rec.get("reason")
                e.usage = rec.get("usage")
        # unknown kinds are skipped, the loader's forward-compat rule
    return list(entries.values())


class Journal:
    """Append-only JSONL write-ahead journal with live-set compaction.

    Opening an existing path LOADS it first (the recovery source — see
    `live_entries`) and then appends; records survive a crash up to the
    last `sync()` (fsync), lines up to the last append (flush). All
    appends serialize behind one lock, so records from the engine loop
    and HTTP handler threads interleave whole, never torn.
    """

    def __init__(self, path: str, *, rotate_bytes: int = 4 << 20,
                 rotate_finished: int = 256, finished_keep: int = 1024):
        if rotate_bytes < 1 or rotate_finished < 1:
            raise ValueError(
                "rotate_bytes and rotate_finished must be >= 1 (the "
                "journal must be allowed to compact)"
            )
        self.path = path
        self.rotate_bytes = rotate_bytes
        self.rotate_finished = rotate_finished
        self.finished_keep = finished_keep
        self._lock = threading.Lock()
        # arrival-ordered unfinished entries: the recovery set
        self.live: OrderedDict[str, JournalEntry] = OrderedDict()
        # recently finished entries, bounded (lookup surface for
        # /v1/requests/<id> and SSE replay past the registry)
        self.finished: OrderedDict[str, JournalEntry] = OrderedDict()
        # counters (the serve/journal_* gauges + /statusz section)
        self.records = 0
        self.bytes_written = 0
        self.fsyncs = 0
        self.fsync_s = 0.0
        self.rotations = 0
        self._finished_since_rotate = 0
        self._dirty = False
        self._load()
        self._f = open(self.path, "a", encoding="utf-8")  # noqa: SIM115

    # ------------------------------------------------------------- load

    def _load(self) -> None:
        """Rebuild the in-memory index from an existing journal file.
        Tolerates a torn FINAL line (crash mid-write); a malformed line
        anywhere else raises — that is corruption, not a crash tail."""
        if not os.path.exists(self.path):
            return
        with open(self.path, encoding="utf-8") as f:
            lines = f.read().split("\n")
        for i, line in enumerate(lines):
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                if i >= len(lines) - 2:
                    break  # torn tail: the crash interrupted this write
                raise JournalError(
                    f"{self.path}:{i + 1}: malformed journal record "
                    "before the final line — the file is corrupt, not "
                    "merely crash-torn"
                ) from None
            self._apply(rec)

    def _apply(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "submit":
            e = JournalEntry(
                rid=rec["rid"], prompt=list(rec["prompt"]),
                max_new_tokens=int(rec["max_new_tokens"]),
                eos_id=rec.get("eos_id"), params=rec.get("params") or {},
                arrival=float(rec.get("arrival", 0.0)),
                grammar=bool(rec.get("grammar", False)),
                deadline_s=rec.get("deadline_s"),
                tokens=list(rec.get("tokens", ())),
            )
            self.live[e.rid] = e
        elif kind == "commit":
            e = self.live.get(rec["rid"])
            if e is not None:
                e.tokens.extend(int(t) for t in rec["tokens"])
        elif kind == "finish":
            e = self.live.pop(rec["rid"], None)
            if e is not None:
                e.finished = True
                e.finish_reason = rec.get("reason")
                e.usage = rec.get("usage")
                self._remember_finished(e)
        # unknown kinds are skipped: a newer writer's record must not
        # brick an older reader's recovery

    def _remember_finished(self, e: JournalEntry) -> None:
        self.finished[e.rid] = e
        self.finished.move_to_end(e.rid)
        while len(self.finished) > self.finished_keep:
            self.finished.popitem(last=False)

    # ----------------------------------------------------------- append

    def _write(self, rec: dict) -> None:
        """One record = ONE write of one line (torn-record safety) +
        flush (line-visible to readers; fsync is batched in sync())."""
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        try:
            self._f.write(line)
            self._f.flush()
        except (OSError, ValueError) as exc:  # ValueError: closed file
            raise JournalError(
                f"journal write to {self.path} failed: {exc}"
            ) from exc
        self.records += 1
        self.bytes_written += len(line)
        self._dirty = True

    def is_live(self, rid: str) -> bool:
        """True while `rid` has an unfinished entry — the engine's
        duplicate-id guard (two live streams must never merge their
        commits into one record)."""
        with self._lock:
            return rid in self.live

    def append_submit(self, rid: str, prompt, max_new_tokens: int,
                      eos_id, params: dict, arrival: float,
                      grammar: bool = False,
                      deadline_s: float | None = None) -> None:
        with self._lock:
            e = JournalEntry(
                rid=rid, prompt=[int(t) for t in prompt],
                max_new_tokens=int(max_new_tokens),
                eos_id=None if eos_id is None else int(eos_id),
                params=params, arrival=float(arrival), grammar=grammar,
                deadline_s=deadline_s,
            )
            self._write({
                "kind": "submit", "rid": rid, "prompt": e.prompt,
                "max_new_tokens": e.max_new_tokens, "eos_id": e.eos_id,
                "params": params, "arrival": round(e.arrival, 6),
                "grammar": grammar, "deadline_s": deadline_s,
            })
            self.live[rid] = e

    def append_commit(self, rid: str, tokens) -> None:
        toks = [int(t) for t in tokens]
        if not toks:
            return
        with self._lock:
            self._write({"kind": "commit", "rid": rid, "tokens": toks})
            e = self.live.get(rid)
            if e is not None:
                e.tokens.extend(toks)

    def append_finish(self, rid: str, reason: str,
                      usage: dict | None = None) -> None:
        with self._lock:
            self._write({"kind": "finish", "rid": rid, "reason": reason,
                         "usage": usage or {}})
            e = self.live.pop(rid, None)
            if e is not None:
                e.finished = True
                e.finish_reason = reason
                e.usage = usage or {}
                self._remember_finished(e)
            self._finished_since_rotate += 1
            if (self._finished_since_rotate >= self.rotate_finished
                    or self._f.tell() >= self.rotate_bytes):
                self._rotate_locked()

    # ------------------------------------------------------ sync/rotate

    @property
    def dirty(self) -> bool:
        """Records written since the last `sync()` — the engine's
        per-step fsync gate (idle steps skip the lock and the fault-
        plane poke entirely)."""
        return self._dirty

    def sync(self) -> None:
        """Batched durability point: fsync once if anything was written
        since the last sync — the engine calls this once per step."""
        with self._lock:
            if not self._dirty:
                return
            t0 = time.monotonic()
            try:
                os.fsync(self._f.fileno())
            except OSError as exc:
                raise JournalError(
                    f"journal fsync of {self.path} failed: {exc}"
                ) from exc
            self.fsync_s += time.monotonic() - t0
            self.fsyncs += 1
            self._dirty = False

    def compact(self) -> None:
        """Force a compaction (recovery calls this after replaying the
        live set, so a freshly recovered journal starts O(active))."""
        with self._lock:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Rewrite the journal to the live set only: each unfinished
        request becomes one submit record with its committed tokens
        folded in (`"tokens"`, which the loader accepts). Atomic
        tmp + fsync + rename — a crash mid-rotation leaves either the
        old journal or the new one, never a hybrid."""
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for e in self.live.values():
                    f.write(json.dumps({
                        "kind": "submit", "rid": e.rid, "prompt": e.prompt,
                        "max_new_tokens": e.max_new_tokens,
                        "eos_id": e.eos_id, "params": e.params,
                        "arrival": round(e.arrival, 6),
                        "grammar": e.grammar, "deadline_s": e.deadline_s,
                        "tokens": e.tokens,
                    }, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._f.close()
            os.replace(tmp, self.path)
            self._f = open(self.path, "a", encoding="utf-8")  # noqa: SIM115
        except OSError as exc:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise JournalError(
                f"journal rotation of {self.path} failed: {exc}"
            ) from exc
        self.rotations += 1
        self._finished_since_rotate = 0
        self._dirty = False

    # ----------------------------------------------------------- lookup

    def live_entries(self) -> list[JournalEntry]:
        """Unfinished entries in arrival order — the recovery set."""
        with self._lock:
            return list(self.live.values())

    def lookup(self, rid: str) -> JournalEntry | None:
        """Live or recently finished entry for `rid` (None once a
        finished entry ages past `finished_keep`)."""
        with self._lock:
            return self.live.get(rid) or self.finished.get(rid)

    def stats(self) -> dict:
        """The /statusz `journal` section + gauge source."""
        with self._lock:
            return {
                "path": self.path,
                "records": self.records,
                "bytes_written": self.bytes_written,
                "fsyncs": self.fsyncs,
                "fsync_s": round(self.fsync_s, 6),
                "rotations": self.rotations,
                "live": len(self.live),
                "finished_kept": len(self.finished),
            }

    def close(self) -> None:
        """Flush + fsync + close (idempotent); further appends raise
        JournalError, which the engine's degrade boundary absorbs."""
        with self._lock:
            if self._f.closed:
                return
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
            except OSError:
                pass
            self._f.close()
