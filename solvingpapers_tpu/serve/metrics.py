"""Serving observability: TTFT, inter-token latency, throughput, occupancy.

Latency observations flow into mergeable log-bucketed histograms
(`metrics.hist.LogHistogram` — fixed bucket layout, O(1) record, exact
cross-replica merge; they replaced the bounded `Ring`, whose window was
a biased estimator under load and could not be aggregated) and summaries
flow out through the existing `MetricsWriter` sink interface — the same
channel train-loop metrics ride, so a serve process logs to
console/JSONL/TensorBoard/wandb with zero new plumbing. Flat sinks get
the scalar summary keys below (mean exact, percentiles bucket-resolution
estimates); histogram-capable sinks (`PrometheusTextWriter`, and the
live `/metrics` pull paths riding its `render`) additionally get the
histograms themselves via `prom_snapshot()`, exposed as native
Prometheus ``_bucket{le=...}/_sum/_count`` series. Metric names:

    serve/ttft_s_*           submit -> first token (includes queue wait)
    serve/itl_s_*            gap between consecutive token emissions
    serve/e2e_s_*            submit -> finish (whole-request wall)
    serve/queue_wait_s_*     submit -> slot admission
    serve/tokens_per_sec     generated tokens / elapsed wall time
    serve/requests_per_sec   finished requests / elapsed wall time
    serve/slot_occupancy     mean fraction of slots decoding, per iteration
    serve/tokens_prefilled   prompt tokens the engine actually prefilled
                             (excludes prefix-cache-spliced tokens)
    serve/finish_<reason>    finished requests by lifecycle outcome
                             (eos / length / stop / cancelled / timeout —
                             see serve/scheduler.py Request.finish_reason)

SLO gauges (serve/slo.py; present iff `ServeConfig.slo_targets` is set —
the engine registers a gauge provider, the same mechanism as every
conditional family below):

    slo/<class>_finished       finished requests in the class (cancelled/
                               error finishes excluded — client's fault,
                               not a latency outcome)
    slo/<class>_attainment     requests that met EVERY configured target
                               (TTFT / ITL / e2e) / finished
    slo/<class>_burn_rate      violation rate over the recent window /
                               the class's error budget (1 - objective);
                               > 1 means the budget is burning
    serve/goodput_tokens       tokens delivered by SLO-ATTAINED requests
    serve/goodput_tokens_per_s ... per elapsed second — the DistServe-
                               style goodput an iteration-level scheduler
                               can silently trade away under load

Paged-pool gauges (present iff `ServeConfig.paged`; the engine registers
a gauge provider, same mechanism as the observatory below):

    serve/pages_free           allocatable pages currently free
    serve/pages_active         pages referenced by slots or the tree
    serve/page_fragmentation   internal slack: 1 - live KV / allocated
                               page capacity (reservations + tail slack)
    serve/preemptions          requests evicted mid-stream on page
                               exhaustion (recomputed on re-admission);
                               present iff any occurred, with
    serve/recompute_tokens     the tokens re-prefilled by those resumes

Quantized-KV gauges (present iff `ServeConfig.kv_quant`; the engine
registers a gauge provider, same mechanism as the paged-pool gauges —
byte values are analytic shape sums, never device reads):

    serve/kv_bytes_per_token         resident KV bytes (int8 payload +
                                     scale sidecar) per bookable cache
                                     slot — the capacity price of one
                                     context token under this pool
    serve/kv_quant_scale_bytes       f32 absmax-scale sidecar bytes
    serve/kv_quant_bytes_saved       compute-dtype baseline minus the
                                     quantized payload — the ledger-
                                     visible capacity win
    serve/kv_quant_exact_lanes_free  full-precision sidecar lanes free /
    serve/kv_quant_exact_active      serving kv_exact requests (present
                                     iff kv_exact_lanes > 0)

Speculative-decoding gauges (serve/spec.py; present iff
`ServeConfig.speculative` — the engine registers a gauge provider, the
same mechanism as the paged-pool and observatory gauges):

    serve/spec_acceptance_rate   drafts accepted / drafts proposed
                                 (lifetime; 0 before any proposal)
    serve/spec_tokens_per_step   tokens committed per speculative decode
                                 step (1 per round = speculation idle;
                                 up to rounds x (1 + spec_k) per slot)
    serve/spec_drafts_rejected   drafts that failed verification
                                 (cumulative)

Prefix-cache counters (serve/prefix_cache.py; present when the engine's
prefix cache is on):

    serve/prefix_lookups           admission-time radix-tree matches
    serve/prefix_hits              lookups that matched >= 1 page
    serve/prefix_hit_rate          hits / lookups
    serve/prefix_cached_tokens     prompt tokens served by splicing
    serve/tokens_prefilled_saved   alias of the above: prefill compute
                                   avoided, the bench's headline saving
    serve/prefix_evictions         LRU leaf evictions so far
    serve/prefix_hbm_bytes         device bytes the radix tree holds now

HTTP front-door gauges (serve/api.py; present iff an `ApiServer` is
attached to the engine — it registers a gauge provider, the same
mechanism as the paged-pool and observatory gauges):

    serve/http_connections     streams currently open (SSE + blocking)
    serve/http_requests        completion requests received (cumulative)
    serve/http_streams         SSE streams started
    serve/http_disconnects     clients that dropped mid-stream (each one
                               maps to engine.cancel — pair with
                               serve/finish_cancelled)
    serve/http_rejected        503s (queue full / too many streams)
    serve/http_client_errors   400s (validation failures)

Compile & memory observatory gauges (metrics/xla_obs.py; present iff
`ServeConfig.xla_obs` is on, via `add_gauge_provider`):

    compile/*                      programs / compilations / cached /
                                   recompiles / storms / time_s
    mem/*                          per-pool live bytes (params, kv_pool,
                                   prefix_cache), program temp, projected
                                   peak, capacity + headroom where the
                                   backend reports a limit
    roofline/<program>_*           achieved FLOP/s, arithmetic intensity,
                                   MFU (only on chips with a known peak)
"""

from __future__ import annotations

import time
import warnings

from solvingpapers_tpu.metrics.hist import LogHistogram
from solvingpapers_tpu.metrics.writer import MetricsWriter, Ring

# one latency bucket layout for every serve histogram: merge across
# engines/replicas only works on identical layouts, and Prometheus
# cross-replica aggregation needs aligned `le` label sets
_LATENCY_LAYOUT = dict(lo=1e-4, hi=1e4, buckets_per_decade=16)


def latency_histogram() -> LogHistogram:
    """A serve-layout latency histogram (the shared layout every
    ServeMetrics instance and replica aggregator must use)."""
    return LogHistogram(**_LATENCY_LAYOUT)


class ServeMetrics:
    """Engine-side collector; one instance per `ServeEngine`."""

    def __init__(self, window: int = 4096):
        self.ttft = latency_histogram()
        self.itl = latency_histogram()
        self.queue_wait = latency_histogram()
        self.e2e = latency_histogram()
        self.occupancy = Ring(window)
        self.tokens_out = 0
        self.prefill_tokens = 0
        self.requests_finished = 0
        self.requests_rejected = 0
        self.finish_reasons: dict[str, int] = {}
        self.steps = 0
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_cached_tokens = 0
        self.prefix_evictions = 0
        self.prefix_bytes_held = 0
        self.preemptions = 0
        self.recompute_tokens = 0
        self.spec_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_tokens = 0
        self.faults_injected = 0
        self.quarantines = 0
        self.engine_retries = 0
        self.engine_unhealthy = 0
        self.watchdog_stalls = 0
        self.recoveries = 0
        self.recovery_s_last = 0.0
        self.degrade_transitions = 0
        self.sheds: dict[str, int] = {}
        self._t_first: float | None = None
        self._t_last: float | None = None
        # zero-arg dict providers merged into every snapshot — how the
        # compile & memory observatory (metrics/xla_obs.py) publishes its
        # compile/* + mem/* + roofline/* gauges through the same sinks
        # without ServeMetrics knowing the observatory exists. Registered
        # only when the engine enables it, so the key surface stays
        # "present iff the observatory is on".
        self._gauge_providers: list = []
        # providers that already raised once (warned; their keys are
        # skipped that snapshot, the provider stays registered so a
        # transient failure self-heals) — id()-keyed, ids stay valid
        # because the provider list holds strong refs
        self._provider_warned: set[int] = set()

    def add_gauge_provider(self, provider) -> None:
        """Attach a zero-arg callable returning {metric_name: float};
        its keys ride every `snapshot()` (last writer wins on clashes)."""
        self._gauge_providers.append(provider)

    def _touch(self, now: float) -> None:
        if self._t_first is None:
            self._t_first = now
        self._t_last = now

    @property
    def elapsed_s(self) -> float:
        if self._t_first is None or self._t_last is None:
            return 0.0
        return self._t_last - self._t_first

    def record_reject(self) -> None:
        self.requests_rejected += 1

    def record_admit(self, req, now: float) -> None:
        self._touch(now)
        self.queue_wait.add(now - req.submit_time)

    def record_first_token(self, req, now: float,
                           prefilled: int | None = None) -> None:
        """`prefilled` = prompt tokens the engine actually ran prefill
        over (the uncovered suffix when the prefix cache spliced the
        rest); defaults to the full prompt length."""
        self._touch(now)
        self.ttft.add(now - req.submit_time)
        self.tokens_out += 1
        self.prefill_tokens += (
            len(req.prompt) if prefilled is None else prefilled
        )

    def record_tokens(self, req, n: int, span_s: float, now: float) -> None:
        """`n` tokens emitted for `req` over `span_s` seconds (a decode
        block emits in bursts; the per-token gap is the amortized span)."""
        self._touch(now)
        self.tokens_out += n
        if n > 0:
            self.itl.add(span_s / n, n)

    def record_finish(self, req, now: float) -> None:
        self._touch(now)
        self.requests_finished += 1
        self.e2e.add(max(now - req.submit_time, 0.0))
        reason = req.finish_reason or "unknown"
        self.finish_reasons[reason] = self.finish_reasons.get(reason, 0) + 1

    def record_step(self, occupancy: float) -> None:
        self.steps += 1
        self.occupancy.add(occupancy)

    def record_prefix_lookup(self, matched_tokens: int) -> None:
        """One admission-time radix match; `matched_tokens` prompt tokens
        were served by splicing instead of prefill (0 = miss)."""
        self.prefix_lookups += 1
        if matched_tokens > 0:
            self.prefix_hits += 1
            self.prefix_cached_tokens += matched_tokens

    def record_prefix_state(self, bytes_held: int, evictions: int) -> None:
        """Latest radix-tree gauges (HBM held, cumulative evictions)."""
        self.prefix_bytes_held = bytes_held
        self.prefix_evictions = evictions

    def record_preemption(self) -> None:
        """A paged-pool request lost its slot to page exhaustion (it will
        recompute on re-admission)."""
        self.preemptions += 1

    def record_spec_step(self, proposed: int, accepted: int,
                         delivered: int) -> None:
        """One speculative decode step: `proposed` drafts went into the
        draft-verify rounds, `accepted` of them survived verification,
        and `delivered` tokens were committed to streams (the engine's
        gauge provider derives serve/spec_* from these — present iff
        speculation is enabled)."""
        self.spec_steps += 1
        self.spec_proposed += proposed
        self.spec_accepted += accepted
        self.spec_tokens += delivered

    def record_fault_injected(self) -> None:
        """The fault plan fired one spec at a hot-path site."""
        self.faults_injected += 1

    def record_quarantine(self) -> None:
        """A NaN/Inf-poisoned slot was contained (its request finished
        "error"; every other stream continued)."""
        self.quarantines += 1

    def record_engine_retry(self) -> None:
        """A systemic step failure consumed one pool-rebuild retry."""
        self.engine_retries += 1

    def record_engine_unhealthy(self) -> None:
        """Retries exhausted: the engine drained to `unhealthy`."""
        self.engine_unhealthy += 1

    def record_watchdog_stall(self, dur_s: float) -> None:
        """A step exceeded the absolute watchdog deadline (`dur_s` is
        carried by the trace instant / anomaly dump, not a gauge)."""
        self.watchdog_stalls += 1

    def record_recovery(self, dur_s: float) -> None:
        """First clean step after a failure episode: `dur_s` = first
        failure -> first clean step (the serve/fault_recovery_s gauge)."""
        self.recoveries += 1
        self.recovery_s_last = dur_s

    def record_degrade_transition(self) -> None:
        """The degradation ladder moved one rung (either direction)."""
        self.degrade_transitions += 1

    def record_shed(self, slo_class: str) -> None:
        """An admission was load-shed by SLO class (ladder rung >= 3)."""
        self.sheds[slo_class] = self.sheds.get(slo_class, 0) + 1

    def record_recompute_tokens(self, n: int) -> None:
        """Prompt+stream tokens re-prefilled by a preempted request's
        resume — the compute cost of preemption-by-recompute."""
        self.recompute_tokens += n
        self.prefill_tokens += n

    def snapshot(self) -> dict[str, float]:
        """Current aggregate view, flat keys ready for a MetricsWriter."""
        out = {
            "serve/tokens_out": float(self.tokens_out),
            "serve/tokens_prefilled": float(self.prefill_tokens),
            "serve/requests_finished": float(self.requests_finished),
            "serve/requests_rejected": float(self.requests_rejected),
            "serve/steps": float(self.steps),
        }
        for reason in sorted(self.finish_reasons):
            out[f"serve/finish_{reason}"] = float(self.finish_reasons[reason])
        if self.prefix_lookups:
            out["serve/prefix_lookups"] = float(self.prefix_lookups)
            out["serve/prefix_hits"] = float(self.prefix_hits)
            out["serve/prefix_hit_rate"] = (
                self.prefix_hits / self.prefix_lookups
            )
            out["serve/prefix_cached_tokens"] = float(self.prefix_cached_tokens)
            out["serve/tokens_prefilled_saved"] = float(
                self.prefix_cached_tokens
            )
            out["serve/prefix_evictions"] = float(self.prefix_evictions)
            out["serve/prefix_hbm_bytes"] = float(self.prefix_bytes_held)
        if self.preemptions:
            out["serve/preemptions"] = float(self.preemptions)
            out["serve/recompute_tokens"] = float(self.recompute_tokens)
        # fault-tolerance counters: present iff the event family ever
        # occurred (the serve/preemptions discipline — a fault-free run
        # keeps its key surface identical to the pre-fault engine's)
        if self.faults_injected:
            out["serve/fault_injected"] = float(self.faults_injected)
        if self.quarantines:
            out["serve/fault_quarantined"] = float(self.quarantines)
        if self.engine_retries:
            out["serve/fault_retries"] = float(self.engine_retries)
        if self.engine_unhealthy:
            out["serve/fault_unhealthy"] = float(self.engine_unhealthy)
        if self.watchdog_stalls:
            out["serve/watchdog_stalls"] = float(self.watchdog_stalls)
        if self.recoveries:
            out["serve/fault_recovery_s"] = float(self.recovery_s_last)
        if self.degrade_transitions:
            out["serve/degrade_transitions"] = float(
                self.degrade_transitions
            )
        for cls in sorted(self.sheds):
            out[f"serve/shed_{cls}"] = float(self.sheds[cls])
        elapsed = self.elapsed_s
        if elapsed > 0:
            out["serve/tokens_per_sec"] = self.tokens_out / elapsed
            out["serve/requests_per_sec"] = self.requests_finished / elapsed
        if len(self.occupancy):
            out["serve/slot_occupancy"] = self.occupancy.mean()
        for name, hist in self._latency_hists():
            if len(hist):
                out[f"serve/{name}_mean"] = hist.mean()
                for k, v in hist.percentiles().items():
                    out[f"serve/{name}_{k}"] = v
        for provider in self._gauge_providers:
            # one broken provider must not kill the whole scrape: every
            # /metrics pull, /statusz document and textfile write runs
            # through here, and the providers read live engine state
            # (pool gauges, registry locks) that can legitimately raise
            # mid-teardown. Warn ONCE per provider, skip its keys, keep
            # every healthy provider's gauges flowing.
            try:
                out.update(provider())
            except Exception as e:  # noqa: BLE001 — scrape isolation
                if id(provider) not in self._provider_warned:
                    self._provider_warned.add(id(provider))
                    name = getattr(provider, "__qualname__", None) or repr(
                        provider
                    )
                    warnings.warn(
                        f"gauge provider {name} raised "
                        f"{type(e).__name__}: {e} — its keys are skipped "
                        "(warning once; other providers keep reporting)",
                        stacklevel=2,
                    )
        return out

    def _latency_hists(self):
        return (
            ("ttft_s", self.ttft),
            ("itl_s", self.itl),
            ("e2e_s", self.e2e),
            ("queue_wait_s", self.queue_wait),
        )

    def prom_snapshot(self) -> dict:
        """`snapshot()` plus the latency histograms THEMSELVES (under
        their base names, e.g. ``serve/ttft_s``) — the metric set for
        histogram-capable sinks: `PrometheusTextWriter` and the live
        `/metrics` pull paths render them as native `_bucket/_sum/_count`
        series, which is what makes per-replica latency aggregation
        (`sum by (le)`) possible. Flat sinks keep getting `snapshot()`."""
        out = self.snapshot()
        for name, hist in self._latency_hists():
            if len(hist):
                out[f"serve/{name}"] = hist
        return out

    def emit(self, writer: MetricsWriter, step: int | None = None) -> None:
        snap = (self.prom_snapshot()
                if getattr(writer, "accepts_histograms", False)
                else self.snapshot())
        writer.write(self.steps if step is None else step, snap)


def now() -> float:
    """The engine's clock (monotonic; patchable in tests)."""
    return time.monotonic()
