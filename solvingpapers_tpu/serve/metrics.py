"""Serving observability: TTFT, inter-token latency, throughput, occupancy.

Latency observations flow into bounded rings (`metrics.writer.Ring`) and
summaries flow out through the existing `MetricsWriter` sink interface —
the same channel train-loop metrics ride, so a serve process logs to
console/JSONL/TensorBoard/wandb with zero new plumbing. Metric names:

    serve/ttft_s_*           submit -> first token (includes queue wait)
    serve/itl_s_*            gap between consecutive token emissions
    serve/queue_wait_s_*     submit -> slot admission
    serve/tokens_per_sec     generated tokens / elapsed wall time
    serve/requests_per_sec   finished requests / elapsed wall time
    serve/slot_occupancy     mean fraction of slots decoding, per iteration
"""

from __future__ import annotations

import time

from solvingpapers_tpu.metrics.writer import MetricsWriter, Ring


class ServeMetrics:
    """Engine-side collector; one instance per `ServeEngine`."""

    def __init__(self, window: int = 4096):
        self.ttft = Ring(window)
        self.itl = Ring(window)
        self.queue_wait = Ring(window)
        self.occupancy = Ring(window)
        self.tokens_out = 0
        self.prefill_tokens = 0
        self.requests_finished = 0
        self.requests_rejected = 0
        self.steps = 0
        self._t_first: float | None = None
        self._t_last: float | None = None

    def _touch(self, now: float) -> None:
        if self._t_first is None:
            self._t_first = now
        self._t_last = now

    @property
    def elapsed_s(self) -> float:
        if self._t_first is None or self._t_last is None:
            return 0.0
        return self._t_last - self._t_first

    def record_reject(self) -> None:
        self.requests_rejected += 1

    def record_admit(self, req, now: float) -> None:
        self._touch(now)
        self.queue_wait.add(now - req.submit_time)

    def record_first_token(self, req, now: float) -> None:
        self._touch(now)
        self.ttft.add(now - req.submit_time)
        self.tokens_out += 1
        self.prefill_tokens += len(req.prompt)

    def record_tokens(self, req, n: int, span_s: float, now: float) -> None:
        """`n` tokens emitted for `req` over `span_s` seconds (a decode
        block emits in bursts; the per-token gap is the amortized span)."""
        self._touch(now)
        self.tokens_out += n
        if n > 0:
            per_tok = span_s / n
            for _ in range(n):
                self.itl.add(per_tok)

    def record_finish(self, req, now: float) -> None:
        self._touch(now)
        self.requests_finished += 1

    def record_step(self, occupancy: float) -> None:
        self.steps += 1
        self.occupancy.add(occupancy)

    def snapshot(self) -> dict[str, float]:
        """Current aggregate view, flat keys ready for a MetricsWriter."""
        out = {
            "serve/tokens_out": float(self.tokens_out),
            "serve/requests_finished": float(self.requests_finished),
            "serve/requests_rejected": float(self.requests_rejected),
            "serve/steps": float(self.steps),
        }
        elapsed = self.elapsed_s
        if elapsed > 0:
            out["serve/tokens_per_sec"] = self.tokens_out / elapsed
            out["serve/requests_per_sec"] = self.requests_finished / elapsed
        if len(self.occupancy):
            out["serve/slot_occupancy"] = self.occupancy.mean()
        for name, ring in (
            ("ttft_s", self.ttft),
            ("itl_s", self.itl),
            ("queue_wait_s", self.queue_wait),
        ):
            if len(ring):
                out[f"serve/{name}_mean"] = ring.mean()
                for k, v in ring.percentiles().items():
                    out[f"serve/{name}_{k}"] = v
        return out

    def emit(self, writer: MetricsWriter, step: int | None = None) -> None:
        writer.write(self.steps if step is None else step, self.snapshot())


def now() -> float:
    """The engine's clock (monotonic; patchable in tests)."""
    return time.monotonic()
