"""Per-request sampling for the serving engine.

PRs 1–2 decoded every slot with one engine-wide static sampler
(`ops.sample_greedy` baked into the jitted programs as a static arg).
Real traffic wants vLLM-style `SamplingParams` attached to each request:
one user greedy, the next temperature-1.2/top-p-0.9, both inside the same
vmapped decode block. This module provides

* `SamplingParams` — the per-request knobs (validated at construction, so
  a bad request fails at `submit` instead of inside a traced program);
* `encode_params` — the host-side packing of one request's knobs into the
  engine's slot-major struct-of-arrays mirrors (float row triple + int
  top_k/seed pair), shipped to the device as packed control arrays
  exactly like the engine's existing decode-state block: one transfer per
  jitted call, never a static arg;
* `fused_sample` — one vectorized sampler applied to the whole slot axis
  inside `_prefill_program`/`_decode_program`: ONE `lax.top_k` gathers
  the `cap` most likely tokens per slot (static cap, a ServeConfig
  knob), then temperature scaling and the shared sort-based
  `ops.top_k_mask`/`top_p_mask`/`min_p_mask` truncations run in that
  (S, cap) domain (the SAME masking code the one-shot `ops.sample_top_p`
  etc. use — no duplicate logic), then per-slot categorical draws map
  back through the gathered indices. The cap bounds the per-step
  sampling cost at O(V) selection + O(cap log cap) masking instead of
  full-vocab sorts — on a 50k-vocab model inside the decode scan,
  XLA:CPU full-vocab sorts cost ~100x the whole forward pass, which is
  why bounded-support sampling is the only shape that keeps the mixed
  batch within the greedy arm's budget. Two runtime `lax.cond` fast
  paths keep the rest free: an all-greedy batch skips the selection and
  masking entirely, and the full-vocab log-softmax runs only when some
  active request asked for logprobs;
* `slot_keys` — per-slot rng derivation. Seeded requests fold
  ``(seed, sample_index)`` into the engine's base key: the chain depends
  only on the request, NOT on which slot it landed in or how many engine
  iterations ran first, so a fixed-seed stream is reproducible
  run-to-run under any interleaving. Unseeded requests fold the engine's
  step counter + slot instead (fresh entropy, no reproducibility
  contract).

Compiled-program inventory is unchanged from the static-sampler engine:
every knob enters as a traced array operand, so a greedy engine and a
mixed stochastic engine share the same compiled decode program
(tests/test_serve_sampling.py pins the jit cache size).

Determinism contract:
* temperature == 0.0 means greedy: the slot takes ``argmax(logits)`` and
  is token-exact with solo greedy `generate`, regardless of what the
  other slots in the batch are doing (the per-slot forward is batch-1
  under vmap, and masking/sampling are per-row).
* a request with ``seed=s`` draws from a chain keyed by
  ``(engine base key, s, sample index)`` only — two engine runs with the
  same `ServeConfig.seed` replay the same stream.
* stochastic draws land inside the top ``ServeConfig.sample_cap`` logits
  (bounded-support sampling; ``top_k`` must fit under the cap — submit
  rejects larger values). With cap >= vocab the support is exact; below
  it, top-p/min-p masses are computed over the capped support's
  renormalized distribution, a truncation that is negligible for
  trained LMs at practical caps and is the price of CPU-viable
  per-step sampling.
* `logprobs` reports the log-softmax of the model's RAW logits at the
  chosen token (the model's own distribution — independent of
  temperature/truncation, well-defined for greedy too).
"""

from __future__ import annotations

import dataclasses
import operator
from typing import NamedTuple

import jax
import jax.numpy as jnp

from solvingpapers_tpu import ops

# fold-in tags separating the seeded per-request rng chain from the
# engine-step chain (both start from the engine's base key). BOTH chains
# lead with their own constant tag: if only the seeded chain were tagged,
# the unseeded chain's leading fold would be the engine step counter,
# which EQUALS the tag after ~0x5EED engine iterations — at that point an
# unseeded slot s would replay the exact draw stream of a seed=s request.
_SEED_TAG = 0x5EED
_STEP_TAG = 0x57E9


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling + termination knobs (vLLM-style).

    temperature   0.0 = greedy argmax (the default; token-exact with solo
                  greedy `generate`); > 0 scales logits before sampling.
    top_k         keep only the k most likely tokens (0 = disabled).
    top_p         nucleus sampling: keep the smallest token set with
                  cumulative probability >= top_p (1.0 = disabled).
    min_p         drop tokens below ``min_p * max token probability``
                  (0.0 = disabled).
    seed          rng seed for a reproducible stream (None = engine
                  entropy, not reproducible run-to-run).
    max_tokens    generation budget; overrides `submit`'s
                  max_new_tokens when set.
    stop_token_ids  finishing token ids beyond the request's `eos_id`
                  (a multi-token EOS set); matched host-side, the
                  matching token is kept in the stream, finish reason
                  "stop".
    stop          stop strings, matched host-side against the decoded
                  generated text (the engine needs a `detokenize`
                  callable); the stream ends at the token that completes
                  the first match, finish reason "stop". A match may
                  span decode-block boundaries.
    logprobs      when True, the chosen token's log-softmax under the
                  model's raw logits is streamed into
                  `Request.logprobs`, one entry per generated token.
    kv_exact      escape hatch from `ServeConfig.kv_quant`: the request
                  serves from a full-precision sidecar lane inside the
                  quantized engine's compiled programs (its exact-lane
                  index rides the packed control rows), byte-identical
                  to unquantized serving; needs
                  `ServeConfig.kv_exact_lanes` >= 1 (submit validates)
                  and bypasses the quantized prefix cache. A no-op on
                  an unquantized engine (everything is exact there).
    slo           SLO class the request's latency is accounted under
                  (serve/slo.py: "interactive"/"standard"/"batch" in the
                  default tier set; any class `ServeConfig.slo_targets`
                  defines). None = the engine's default class when SLO
                  accounting is on. Pure host-side bookkeeping — it
                  never changes sampling or scheduling, only which
                  attainment/goodput bucket the request lands in; submit
                  validates the class exists (and that slo_targets is
                  configured at all).
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0
    seed: int | None = None
    max_tokens: int | None = None
    stop_token_ids: tuple[int, ...] = ()
    stop: tuple[str, ...] = ()
    logprobs: bool = False
    kv_exact: bool = False
    slo: str | None = None

    def __post_init__(self):
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{self.temperature}"
            )
        if not 0 < self.top_p <= 1:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if not 0 <= self.min_p <= 1:
            raise ValueError(f"min_p must be in [0, 1], got {self.min_p}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = off), got {self.top_k}")
        if self.seed is not None and not 0 <= self.seed < 2**31:
            # the seed rides the engine's int32 control mirrors: negative
            # values collide with the -1 "unseeded" sentinel and >= 2**31
            # would overflow the packed array (crashing the shared engine
            # loop under numpy 2.x, silently wrapping under 1.x)
            raise ValueError(
                f"seed must be None or in [0, 2**31), got {self.seed}"
            )
        if self.max_tokens is not None and self.max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {self.max_tokens}")
        if self.slo is not None and (
            not isinstance(self.slo, str) or not self.slo
        ):
            # class MEMBERSHIP is an engine property (ServeConfig.
            # slo_targets names the classes) — submit validates that;
            # here only the type, so the error blames the right knob
            raise ValueError(
                f"slo must be None or a non-empty class name, got "
                f"{self.slo!r}"
            )
        # normalize: a lone string is a single stop string, not chars
        stop = (self.stop,) if isinstance(self.stop, str) else tuple(self.stop)
        if any(not s for s in stop):
            raise ValueError("stop strings must be non-empty")
        object.__setattr__(self, "stop", stop)
        ids = self.stop_token_ids
        try:
            ids = (operator.index(ids),)  # a lone id, like a lone string
        except TypeError:
            pass
        try:
            # operator.index keeps the ValueError-at-construction contract:
            # int(50256.9) would silently stop on the WRONG token id
            ids = tuple(operator.index(t) for t in ids)
        except TypeError:
            raise ValueError(
                f"stop_token_ids must be integer token ids, got {ids!r}"
            ) from None
        object.__setattr__(self, "stop_token_ids", ids)

    @property
    def greedy(self) -> bool:
        return self.temperature == 0.0


GREEDY = SamplingParams()

# slot-major float mirror rows (engine's `_samp_f`): temperature, top_p,
# min_p — the greedy/disabled resting state of a free lane
GREEDY_ROW = (0.0, 1.0, 0.0)


def encode_params(p: SamplingParams):
    """One request's knobs -> ((temperature, top_p, min_p) float row,
    top_k, seed) for the engine's slot-major device mirrors; seed -1
    means unseeded (engine entropy)."""
    seed = -1 if p.seed is None else int(p.seed)
    return (p.temperature, p.top_p, p.min_p), int(p.top_k), seed


class PackedSampling(NamedTuple):
    """Slot-major struct-of-arrays view of every active request's params,
    built inside the jitted programs from the packed control operands
    (float rows + int rows) — all traced, never static."""

    temperature: jax.Array  # (S,) f32; 0 => greedy
    top_p: jax.Array        # (S,) f32; 1 => disabled
    min_p: jax.Array        # (S,) f32; 0 => disabled
    top_k: jax.Array        # (S,) i32; 0 => disabled
    need_lp: jax.Array      # (S,) i32; 1 => stream chosen-token logprobs


def request_key(base, step_tag, slot, seed, samp_idx):
    """Per-slot sampling key (traced; vmap-able over the slot axis).

    ``seed >= 0``: fold (seed tag, seed, sample index) into `base` — a
    chain that depends only on the request, reproducible across runs and
    slot assignments. ``seed < 0``: fold (step tag, engine step, slot,
    sample index) — decorrelated fresh entropy per emission. The two
    chains lead with DISTINCT constant tags so no engine-step value can
    alias the seeded domain (see the tag comment above).
    """
    seeded = jax.random.fold_in(
        jax.random.fold_in(jax.random.fold_in(base, _SEED_TAG), seed),
        samp_idx,
    )
    unseeded = jax.random.fold_in(
        jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(base, _STEP_TAG),
                               step_tag),
            slot,
        ),
        samp_idx,
    )
    return jax.random.wrap_key_data(
        jnp.where(
            seed >= 0,
            jax.random.key_data(seeded),
            jax.random.key_data(unseeded),
        )
    )


def slot_keys(base, step_tag, seeds, samp_idx):
    """(S,) sampling keys for one decode step: `request_key` vmapped over
    the slot axis (`seeds`/`samp_idx` are the packed (S,) i32 rows)."""
    slots = jnp.arange(seeds.shape[0], dtype=jnp.int32)
    return jax.vmap(
        lambda slot, seed, idx: request_key(base, step_tag, slot, seed, idx)
    )(slots, seeds, samp_idx)


def capped_support(logits32, packed: PackedSampling, *, cap: int,
                   allow=None):
    """The bounded-support truncation pipeline shared by `fused_sample`
    and the speculative verifier (`serve.spec.spec_verify`): one
    `lax.top_k` into the `cap`-token domain, the optional per-row grammar
    allow-swap (`ops.allowed_logits`; a row whose first entry is >= 0 is
    constrained), temperature scaling (greedy rows scale by 1 — their
    token comes from argmax, the scale only keeps the masked row finite),
    then the shared top-k/top-p/min-p masks. Returns ``(masked, top_idx)``
    — the -inf-masked scaled support values and their vocab ids. ONE
    implementation, so the speculative path's per-position distributions
    cannot drift from what the plain path samples."""
    top_vals, top_idx = jax.lax.top_k(logits32, cap)
    if allow is not None:
        constrained = allow[..., 0] >= 0
        a_vals, a_idx = ops.allowed_logits(logits32, allow)
        top_vals = jnp.where(constrained[..., None], a_vals, top_vals)
        top_idx = jnp.where(constrained[..., None], a_idx, top_idx)
    greedy = packed.temperature <= 0.0
    temp = jnp.where(greedy, 1.0, packed.temperature)[:, None]
    scaled = top_vals / temp
    masked = ops.top_k_mask(scaled, packed.top_k[:, None])
    masked = ops.top_p_mask(masked, packed.top_p[:, None])
    masked = ops.min_p_mask(masked, packed.min_p[:, None])
    return masked, top_idx


def fused_sample(logits, packed: PackedSampling, rngs, *, cap: int = 64,
                 allow=None):
    """Sample one token per slot under per-slot params; returns
    ``(tokens (S,) i32, logprobs (S,) f32)``.

    `logits` is (S, vocab); `rngs` is (S,) typed keys (from `slot_keys`);
    `cap` is the STATIC support bound (ServeConfig.sample_cap, clamped to
    the vocab). Greedy rows (temperature 0) take argmax of the raw
    logits. Stochastic rows draw from the top-`cap` logits: one
    `lax.top_k` selection, then temperature scaling and the shared
    `ops.top_k_mask`/`top_p_mask`/`min_p_mask` truncations (all cutoffs
    traced, per-row) in the small (S, cap) domain, then per-slot
    categorical draws mapped back through the gathered indices. The
    returned logprob is the log-softmax of the RAW full-vocab logits at
    the chosen token, or 0 where `need_lp` is unset.

    `allow` is the optional grammar constraint: (S, cap) int32 token ids
    with -1 padding (`serve/grammar.py`), a TRACED operand riding the
    engine's packed control transfers. A row whose first entry is >= 0
    is constrained: its candidate domain becomes the allowed ids
    themselves (`ops.allowed_logits` — the same (values, indices) shape
    `lax.top_k` yields, so every truncation mask and the categorical
    draw apply unchanged), and a greedy constrained row takes argmax
    over that domain instead of the raw vocab. All-(-1) rows (every
    unconstrained slot) are untouched — a mixed constrained/plain batch
    shares this one compiled program.

    Runtime `lax.cond` fast paths: an all-greedy batch with no
    constrained row runs argmax only (no selection, no masking — the
    cost of the old static greedy sampler), and the full-vocab
    log-softmax runs only when some slot wants logprobs. Full-vocab
    sorts would be correct but are ~100x the model forward on XLA:CPU
    inside the decode scan — the cap is what makes a mixed batch
    affordable (see the module docstring for the semantics of the
    truncation).
    """
    cap = min(cap, logits.shape[-1])
    greedy = packed.temperature <= 0.0
    # one f32 cast up front: selection/reduction ops over bf16 are
    # scalar-emulated on XLA:CPU (a bf16 top_k here measured ~27x the f32
    # one — slower than the whole model forward)
    logits32 = logits.astype(jnp.float32)
    if allow is not None:
        # reconcile widths: the engine packs ServeConfig.sample_cap
        # entries, the effective cap may have clamped to a smaller
        # vocab. Truncation is lossless — allowed ids are distinct and
        # < vocab, so past index `cap` only -1 padding can remain.
        if allow.shape[-1] > cap:
            allow = allow[:, :cap]
        elif allow.shape[-1] < cap:
            allow = jnp.pad(allow, ((0, 0), (0, cap - allow.shape[-1])),
                            constant_values=-1)
        constrained = allow[:, 0] >= 0

    def _all_greedy():
        return jnp.argmax(logits32, axis=-1).astype(jnp.int32)

    def _mixed():
        masked, top_idx = capped_support(logits32, packed, cap=cap,
                                         allow=allow)
        greedy_tok = _all_greedy()
        if allow is not None:
            # greedy under a constraint = argmax over the allowed domain
            # (the masks never drop a row's argmax, so the masked argmax
            # is the domain argmax)
            dom = jnp.take_along_axis(
                top_idx, jnp.argmax(masked, axis=-1)[:, None], axis=-1
            )[:, 0]
            greedy_tok = jnp.where(constrained, dom, greedy_tok)
        sel = jax.vmap(
            lambda row, key: jax.random.categorical(key, row)
        )(masked, rngs)
        drawn = jnp.take_along_axis(top_idx, sel[:, None], axis=-1)[:, 0]
        return jnp.where(greedy, greedy_tok, drawn.astype(jnp.int32))

    fast = jnp.all(greedy)
    if allow is not None:
        fast = fast & ~jnp.any(constrained)
    toks = jax.lax.cond(fast, _all_greedy, _mixed)

    def _logprobs():
        chosen = jnp.take_along_axis(logits32, toks[:, None], axis=-1)[:, 0]
        lse = jax.scipy.special.logsumexp(logits32, axis=-1)
        return chosen - lse

    logprobs = jax.lax.cond(
        jnp.any(packed.need_lp > 0), _logprobs,
        lambda: jnp.zeros(toks.shape, jnp.float32),
    )
    return toks, logprobs
