"""OpenAI wire protocol: request validation + response/SSE shapes.

Pure JSON-dict mapping, no HTTP and no engine — `serve/api.py` owns the
sockets and threads, this module owns the contract: what a
`/v1/completions` / `/v1/chat/completions` body means, how it maps onto
`Request` + `SamplingParams`, and what the response objects (full and
streamed chunk) look like. Keeping it transport-free makes every
validation rule unit-testable without opening a port.

Errors raise `ApiError`, which carries the HTTP status and renders the
OpenAI error envelope::

    {"error": {"message": ..., "type": ..., "param": ..., "code": ...}}

`submit`-side `ValueError`s (prompt too long, top_k over the cap, ...)
are wrapped into the same envelope by the front door, so every client
failure mode is a structured 400/503 — never a traceback over a socket.

Prompts may be a string (tokenized by the server's `encode`) or a list
of token ids (the raw-id path the bench and token-exactness tests use —
the OpenAI completions API allows token arrays too). Chat messages are
flattened by `chat_prompt` (a minimal ``role: content`` template — the
char-level bench models have no chat template to honor).

`response_format {"type": "json_object"}` attaches a
`serve.grammar.JsonStepper` built over the server's token table; a
vocabulary that cannot express JSON yields a structured 400.
"""

from __future__ import annotations

import time

from solvingpapers_tpu.serve.sampling import SamplingParams

# OpenAI finish_reason values; engine reasons outside the standard set
# ("timeout") pass through as extensions — a client that only switches
# on "stop"/"length" treats them as an unknown terminal state, which is
# exactly what they are. "error" maps EXPLICITLY (not by fallthrough):
# it is the engine's failure-isolation contract — a quarantined or
# engine-failed stream ends with finish_reason "error" plus a
# structured error event (see `error_event` and serve/api.py's SSE
# error protocol), never a silently dropped connection.
_FINISH_MAP = {"eos": "stop", "stop": "stop", "length": "length",
               "error": "error"}


class ApiError(Exception):
    """Structured client error -> OpenAI error envelope + HTTP status."""

    def __init__(self, message: str, status: int = 400,
                 err_type: str = "invalid_request_error",
                 param: str | None = None, code: str | None = None):
        super().__init__(message)
        self.status = status
        self.err_type = err_type
        self.param = param
        self.code = code

    def body(self) -> dict:
        return {
            "error": {
                "message": str(self),
                "type": self.err_type,
                "param": self.param,
                "code": self.code,
            }
        }


def finish_reason(engine_reason: str | None) -> str | None:
    if engine_reason is None:
        return None
    return _FINISH_MAP.get(engine_reason, engine_reason)


def error_event(message: str, err_type: str = "server_error",
                code: str | None = "engine_error") -> dict:
    """Mid-stream SSE error payload: the OpenAI error envelope as a
    `data:` event. Sent when a stream that already holds a 200 + SSE
    headers fails server-side (engine quarantine, engine-loop death, a
    rendering bug) — the client gets a STRUCTURED terminal error, then
    the finish chunk with ``finish_reason: "error"`` and ``[DONE]``,
    instead of a connection that just drops."""
    return {
        "error": {
            "message": message,
            "type": err_type,
            "param": None,
            "code": code,
        }
    }


def _field(body: dict, name: str, types, default, param=None):
    val = body.get(name, default)
    if val is default:
        return default
    if not isinstance(val, types) or isinstance(val, bool) and bool not in (
        types if isinstance(types, tuple) else (types,)
    ):
        raise ApiError(
            f"{name} must be {getattr(types, '__name__', types)}, got "
            f"{type(val).__name__}", param=param or name,
        )
    return val


def parse_sampling(body: dict, slo_classes=None
                   ) -> tuple[SamplingParams, int, float | None]:
    """The sampling-relevant fields of a completion/chat body ->
    (SamplingParams, max_tokens, timeout_s). OpenAI defaults:
    temperature 1.0 (pass 0 for greedy), top_p 1.0, max_tokens 16.
    `top_k` / `min_p` / `timeout_s` / `slo` are accepted extensions
    (vLLM serves the first three). `slo_classes` is the server's
    configured SLO class set (None = SLO accounting off)."""
    if _field(body, "n", int, 1) != 1:
        raise ApiError("only n=1 is supported", param="n")
    if _field(body, "best_of", int, 1) != 1:
        raise ApiError("only best_of=1 is supported", param="best_of")
    if body.get("echo"):
        raise ApiError("echo is not supported", param="echo")
    max_tokens = _field(body, "max_tokens", int, 16)
    lp = body.get("logprobs")
    if lp not in (None, False, True, 0, 1):
        raise ApiError(
            "only the chosen token's logprob is available (logprobs must "
            "be null, 0 or 1)", param="logprobs",
        )
    stop = body.get("stop")
    if stop is None:
        stop = ()
    elif isinstance(stop, str):
        stop = (stop,)
    elif isinstance(stop, list) and all(isinstance(s, str) for s in stop):
        stop = tuple(stop)
    else:
        raise ApiError("stop must be a string or a list of strings",
                       param="stop")
    if len(stop) > 4:
        raise ApiError("at most 4 stop sequences are supported",
                       param="stop")
    timeout_s = body.get("timeout_s")
    if timeout_s is not None and (
        not isinstance(timeout_s, (int, float)) or timeout_s <= 0
    ):
        raise ApiError("timeout_s must be a positive number",
                       param="timeout_s")
    seed = body.get("seed")
    if seed is not None and not isinstance(seed, int):
        raise ApiError("seed must be an integer", param="seed")
    # SLO class tag: the explicit "slo" extension is validated strictly
    # (a typo'd class must 400, at submit, not silently untrack), while
    # OpenAI's "service_tier" is honored as a BEST-EFFORT alias: it maps
    # only when it names one of the server's configured classes —
    # stock OpenAI values this server has no class for ("flex",
    # "priority", "scale", and "auto"/"default" meaning the default)
    # are ignored, never promoted into a 400 on an otherwise-valid
    # OpenAI request.
    slo = body.get("slo", None)
    if slo is None:
        tier = body.get("service_tier")
        if slo_classes and isinstance(tier, str) and tier in slo_classes:
            slo = tier
    if slo is not None and not (isinstance(slo, str) and slo):
        raise ApiError("slo must be a non-empty class name string",
                       param="slo")
    try:
        params = SamplingParams(
            temperature=float(_field(body, "temperature", (int, float), 1.0)),
            top_p=float(_field(body, "top_p", (int, float), 1.0)),
            top_k=_field(body, "top_k", int, 0),
            min_p=float(_field(body, "min_p", (int, float), 0.0)),
            seed=seed,
            max_tokens=max_tokens,
            stop=stop,
            logprobs=bool(lp),
            slo=slo,
        )
    except ValueError as e:
        raise ApiError(str(e)) from None
    return params, max_tokens, timeout_s


def wants_json(body: dict, json_mode_ok: bool) -> bool:
    """Interpret `response_format`; 400 on unknown types or when the
    server has json_mode disabled."""
    fmt = body.get("response_format")
    if fmt is None:
        return False
    if not isinstance(fmt, dict) or fmt.get("type") not in (
        "text", "json_object"
    ):
        raise ApiError(
            'response_format must be {"type": "text"} or '
            '{"type": "json_object"}', param="response_format",
        )
    if fmt["type"] == "text":
        return False
    if not json_mode_ok:
        raise ApiError(
            "json_object mode is disabled on this server "
            "(ServeConfig.json_mode)", param="response_format",
        )
    return True


def parse_prompt(body: dict, encode, vocab_size: int):
    """`prompt` -> 1-D int token id list. Strings go through the
    server's `encode`; token-id arrays pass through validated (the
    OpenAI completions API accepts both)."""
    prompt = body.get("prompt")
    if isinstance(prompt, str):
        if encode is None:
            raise ApiError(
                "this server has no tokenizer: send the prompt as a "
                "list of token ids", param="prompt",
            )
        try:
            return [int(t) for t in encode(prompt)]
        except KeyError as e:
            raise ApiError(
                f"prompt contains characters outside the model's "
                f"vocabulary: {e}", param="prompt",
            ) from None
    if isinstance(prompt, list) and prompt and all(
        isinstance(t, int) and not isinstance(t, bool) for t in prompt
    ):
        bad = [t for t in prompt if not 0 <= t < vocab_size]
        if bad:
            raise ApiError(
                f"prompt token ids out of range [0, {vocab_size}): "
                f"{bad[:5]}", param="prompt",
            )
        return prompt
    raise ApiError(
        "prompt must be a string or a non-empty list of token ids",
        param="prompt",
    )


def chat_prompt(body: dict) -> str:
    """Flatten chat `messages` to a prompt string: a minimal
    ``role: content`` template ending with the assistant cue — the
    char-level bench models have no trained chat format, so the
    template only needs to be deterministic and reversible by eye."""
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise ApiError("messages must be a non-empty list",
                       param="messages")
    parts = []
    for i, m in enumerate(messages):
        if not isinstance(m, dict) or not isinstance(m.get("role"), str) \
                or not isinstance(m.get("content"), str):
            raise ApiError(
                "each message needs string 'role' and 'content' fields",
                param=f"messages[{i}]",
            )
        parts.append(f"{m['role']}: {m['content']}\n")
    parts.append("assistant:")
    return "".join(parts)


def _base(kind: str, rid: str, model: str) -> dict:
    return {
        "id": rid,
        "object": kind,
        "created": int(time.time()),
        "model": model,
    }


def usage_block(req) -> dict:
    return {
        "prompt_tokens": int(req.prompt.size),
        "completion_tokens": len(req.tokens),
        "total_tokens": int(req.prompt.size) + len(req.tokens),
    }


def completion_chunk(rid: str, model: str, text: str,
                     reason: str | None = None,
                     usage: dict | None = None) -> dict:
    out = _base("text_completion", rid, model)
    out["choices"] = [
        {"index": 0, "text": text, "logprobs": None,
         "finish_reason": finish_reason(reason)}
    ]
    if usage is not None:
        out["usage"] = usage
    return out


def chat_chunk(rid: str, model: str, content: str | None,
               reason: str | None = None, role: bool = False,
               usage: dict | None = None) -> dict:
    delta: dict = {}
    if role:
        delta["role"] = "assistant"
    if content is not None:
        delta["content"] = content
    out = _base("chat.completion.chunk", rid, model)
    out["choices"] = [
        {"index": 0, "delta": delta, "finish_reason": finish_reason(reason)}
    ]
    if usage is not None:
        out["usage"] = usage
    return out


def completion_response(rid: str, model: str, req, text: str) -> dict:
    out = _base("text_completion", rid, model)
    out["choices"] = [{
        "index": 0,
        "text": text,
        "logprobs": (
            {"token_logprobs": [round(v, 6) for v in req.logprobs]}
            if req.params.logprobs else None
        ),
        "finish_reason": finish_reason(req.finish_reason),
    }]
    out["usage"] = usage_block(req)
    return out


def chat_response(rid: str, model: str, req, text: str) -> dict:
    out = _base("chat.completion", rid, model)
    out["choices"] = [{
        "index": 0,
        "message": {"role": "assistant", "content": text},
        "finish_reason": finish_reason(req.finish_reason),
    }]
    out["usage"] = usage_block(req)
    return out


# JSON structural characters, most essential first: when a char-level
# vocabulary has spare ids (model vocab_size > corpus charset — e.g.
# gpt_shakespeare reserves 65 ids over a 50-char corpus), `cli serve`
# maps the spares to these so json_object mode is expressible. Digits
# beyond the first are optional — the grammar only needs ONE digit to
# express numbers.
_JSON_CHARS = '{}":,0[]-123456789. \n'


def extend_token_table(table: list, vocab_size: int) -> list:
    """Grow a token id -> string table to `vocab_size`, assigning spare
    ids to missing JSON structural characters (priority order above).
    Existing entries are never changed; leftover spares stay None
    (never legal)."""
    table = list(table) + [None] * (vocab_size - len(table))
    have = set()
    for t in table:
        if t:
            have.update(t)
    missing = [c for c in _JSON_CHARS if c not in have]
    for i in range(len(table)):
        if table[i] is None and missing:
            table[i] = missing.pop(0)
    return table
