"""CLI entrypoints — `python -m solvingpapers_tpu.cli <cmd>`.

Replaces the reference's notebook cells with commands (BASELINE.json north
star: "every notebook's train() cell becomes a CLI entrypoint"):

    cli list
    cli train  --config gpt_shakespeare [--steps N] [--data-path f.txt]
               [--checkpoint-dir ckpts] [--jsonl metrics.jsonl]
    cli sample --config gpt_shakespeare --checkpoint-dir ckpts
               [--prompt "ROMEO:"] [--max-new-tokens 200] [--top-k 50]
    cli serve  --config gpt_shakespeare [--checkpoint-dir ckpts]
               [--port 8000] — OpenAI-compatible /v1/completions +
               /v1/chat/completions (SSE streaming, json_object mode)
    cli replay --config gpt_shakespeare --journal serve.jsonl
               [--config-overrides kv_quant=int8] [--out report.json]
               — config-canary divergence gate (exit 2 on divergence)
    cli serve-bench --config llama3_shakespeare [--trace] [--http]
    cli kernel-bench [--config gpt_shakespeare] [--out BENCH_kernels.json]
    cli trace-summary serve_trace.json [--top 10]
"""

from __future__ import annotations

import argparse
import dataclasses
import functools
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", required=True)
    p.add_argument("--data-path", default=None)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu", "axon"],
        help="force a JAX platform (the env pins the axon TPU; 'cpu' enables "
        "local debugging and virtual multi-device meshes)",
    )
    p.add_argument(
        "--virtual-devices",
        type=int,
        default=None,
        help="with --platform cpu: number of virtual host devices to "
        "provision (xla_force_host_platform_device_count), so multi-axis "
        "meshes run without hardware; must be set before any JAX "
        "computation, i.e. only works as a process entry flag",
    )


def _apply_platform(args) -> None:
    """Apply --platform/--virtual-devices. Called from main() BEFORE any
    command code touches jax attributes: XLA reads XLA_FLAGS at backend
    initialization, so mutating it after a backend exists is a silent no-op
    — fail loudly instead of quietly running on the wrong device count."""
    n = getattr(args, "virtual_devices", None)
    if n:
        import os
        import re

        try:  # private, so degrade to best-effort if the API moves
            from jax._src import xla_bridge

            if xla_bridge.backends_are_initialized():
                raise RuntimeError(
                    "--virtual-devices must be applied before any JAX "
                    "backend initializes, but one already has; re-exec with "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
                )
        except (ImportError, AttributeError):
            pass
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    if getattr(args, "platform", None):
        jax.config.update("jax_platforms", args.platform)


def cmd_list(_args) -> int:
    from solvingpapers_tpu.configs import list_configs

    for name in list_configs():
        print(name)
    return 0


def cmd_train(args) -> int:
    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import (
        build_char_lm_run,
        build_image_run,
        init_fn_for,
        loss_fn_for,
    )
    from solvingpapers_tpu.metrics import ConsoleWriter, JSONLWriter, MultiWriter
    from solvingpapers_tpu.sharding import batch_sharding, create_mesh
    from solvingpapers_tpu.train import Trainer

    overrides = {}
    if args.steps is not None:
        overrides["steps"] = args.steps
        # keep the LR schedule aligned with the actual horizon
    if args.checkpoint_dir:
        overrides["checkpoint_dir"] = args.checkpoint_dir
        overrides["ckpt_every"] = args.ckpt_every
    cfg = get_config(args.config, **overrides)
    if args.data_path:
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": args.data_path})

    cp = getattr(cfg.model, "context_parallel", False)
    if cp and not cfg.train.context_parallel:
        # a CP model demands the CP train step; keep the two flags in sync
        cfg = dataclasses.replace(
            cfg, train=dataclasses.replace(cfg.train, context_parallel=True)
        )
    mesh = create_mesh(cfg.train.mesh)
    writer = ConsoleWriter()  # fit() gates cadence by log_every
    if args.jsonl:
        writer = MultiWriter(writer, JSONLWriter(args.jsonl))

    kind = cfg.data.get("kind", "char")
    if kind in ("char", "bpe", "tokens"):
        from solvingpapers_tpu.configs.factory import rules_for

        cfg, model, tok, train_iter, eval_iter_fn = build_char_lm_run(
            cfg, sharding=batch_sharding(mesh, context=cp)
        )
        trainer = Trainer(
            model, cfg.train, loss_fn=loss_fn_for(cfg),
            init_fn=init_fn_for(cfg), mesh=mesh, rules=rules_for(cfg),
        )
        callbacks = None
        can_sample = False
        # CP samples through a dense twin (params are replicated at rest);
        # PP stage-stacked params still need the export conversion first
        no_decode = cfg.train.pipeline_parallel
        sample_model = model
        if cp:
            sample_model = type(model)(
                dataclasses.replace(model.cfg, context_parallel=False)
            )
        if args.artifacts_dir and no_decode:
            print("[sample] disabled: decode caches are unsupported under "
                  "pipeline parallelism (export stage params first)",
                  file=sys.stderr)
        elif args.artifacts_dir:
            try:  # token-file runs have no text tokenizer to build prompts
                can_sample = len(tok.encode("\n")) > 0
                if not can_sample:
                    print("[sample] disabled: tokenizer yields an empty "
                          "prompt", file=sys.stderr)
            except Exception as e:
                print(f"[sample] disabled: {e}", file=sys.stderr)
        if can_sample:
            # deepseekv3 cell 54: sample + save generated_{step}.txt each eval
            from solvingpapers_tpu import ops
            from solvingpapers_tpu.infer import generate
            from solvingpapers_tpu.metrics.viz import save_text_sample

            # one sampler object: it is a static jit arg of generate, and a
            # fresh partial per call would retrace + recompile every sample
            sampler = functools.partial(ops.sample_top_k, k=50)

            def sample_cb(state, step, _tok=tok, _model=sample_model, _cp=cp):
                prompt = jnp.asarray(_tok.encode("\n"), jnp.int32)[None, :]
                extra = state.model_state or None
                # CP state lives on the training mesh; pull the replicated
                # params to host so the dense twin decodes on one device
                params = jax.device_get(state.params) if _cp else state.params
                if _cp and extra:
                    extra = jax.device_get(extra)
                limit = getattr(_model, "max_positions", None) or 1_000_000
                out = generate(
                    _model, params, prompt, jax.random.key(step),
                    max_new_tokens=min(200, limit - prompt.shape[1]),
                    sampler=sampler,
                    extra_variables=extra,
                )
                path = save_text_sample(
                    _tok.decode(np.asarray(out[0])), args.artifacts_dir, step
                )
                print(f"[sample] wrote {path}")

            every = cfg.train.eval_every or cfg.train.log_every
            callbacks = [(every, sample_cb)]
        trainer.fit(train_iter, eval_iter_fn, writer=writer, callbacks=callbacks)
        return 0
    if kind == "images":
        if cfg.model_family == "kd":
            return _train_kd(cfg, mesh, writer)
        model, train_iter, eval_iter_fn, loss_fn = build_image_run(cfg, mesh=mesh)
        trainer = Trainer(model, cfg.train, loss_fn=loss_fn, mesh=mesh)
        state = trainer.fit(train_iter, eval_iter_fn, writer=writer)
        if args.artifacts_dir and cfg.model_family in ("ae", "vae"):
            # autoencoder.ipynb cell 9 / vae cell 9: reconstruction grid
            from solvingpapers_tpu.metrics.viz import save_reconstruction_grid

            batch = next(eval_iter_fn())
            out = model.apply(
                {"params": state.params}, batch["x"], deterministic=True
            )
            recon = out[0] if isinstance(out, tuple) else out
            path = save_reconstruction_grid(
                np.asarray(batch["x"]), np.asarray(jax.device_get(recon)),
                f"{args.artifacts_dir}/reconstructions.png",
            )
            print(f"[viz] wrote {path}")
        return 0
    raise ValueError(f"unknown data kind {kind!r}")


def _train_kd(cfg, mesh, writer) -> int:
    """kd.py pipeline: pretrain teacher, freeze, distill student."""
    import jax as _jax

    from solvingpapers_tpu.configs.factory import build_image_run
    from solvingpapers_tpu.models.kd import MLPClassifier, teacher_config
    from solvingpapers_tpu.train import Trainer, make_kd_loss_fn

    _, train_iter, eval_iter_fn, cls_loss = build_image_run(cfg, mesh=mesh)
    teacher_steps = cfg.data.get("teacher_steps", 1200)
    t_cfg = dataclasses.replace(
        cfg.train, steps=teacher_steps, checkpoint_dir=None, ckpt_every=0
    )
    teacher = MLPClassifier(teacher_config(dtype=cfg.model.dtype))
    print(f"[kd] pretraining teacher for {teacher_steps} steps")
    t_trainer = Trainer(teacher, t_cfg, loss_fn=cls_loss, mesh=mesh)
    t_state = t_trainer.fit(train_iter, eval_iter_fn, writer=writer)

    print(f"[kd] distilling student for {cfg.train.steps} steps")
    student = MLPClassifier(cfg.model)
    kd_loss = make_kd_loss_fn(
        teacher,
        _jax.device_get(t_state.params),
        temperature=cfg.data.get("temperature", 7.0),
        alpha=cfg.data.get("alpha", 0.3),
    )
    s_trainer = Trainer(student, cfg.train, loss_fn=kd_loss, mesh=mesh)
    s_trainer.fit(train_iter, eval_iter_fn, writer=writer)
    return 0


def cmd_sample(args) -> int:
    from solvingpapers_tpu import ops
    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_char_lm_run
    from solvingpapers_tpu.infer import generate

    cfg = get_config(args.config)
    if cfg.train.pipeline_parallel:
        print(
            "sampling is unsupported for pipeline-parallel configs; "
            "export the stage-stacked params to the dense family first",
            file=sys.stderr,
        )
        return 2
    if getattr(cfg.model, "context_parallel", False):
        # Single-chip path: CP params are replicated at rest, so a non-CP
        # twin of the same architecture decodes them directly (tested:
        # tests/test_infer_prefill.py::test_cp_trained_weights_export_to_plain_decode).
        # On a real multi-chip mesh, `infer.generate_cp` decodes UNDER CP
        # instead — context-sharded caches, ring prefill, prompts beyond
        # one chip's HBM (tests/test_deepseekv3.py::test_cp_decode_*).
        from solvingpapers_tpu.sharding import MeshConfig

        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(cfg.model, context_parallel=False),
            train=dataclasses.replace(
                cfg.train, context_parallel=False, mesh=MeshConfig()
            ),
        )
    if args.data_path:
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": args.data_path})
    cfg, model, tok, _, _ = build_char_lm_run(cfg)

    rng = jax.random.key(args.seed)
    if getattr(args, "prompt_file", None):
        with open(args.prompt_file, "r", encoding="utf-8") as f:
            prompt_text = f.read()
    else:
        prompt_text = args.prompt or "\n"
    ids = tok.encode(prompt_text)
    limit = getattr(model, "max_positions", None)
    if limit is not None and len(ids) + args.max_new_tokens > limit:
        # keep a multiple of 128 so every flash prefill chunk keeps a
        # Mosaic-legal q block (kernels/flash_attention._pick_block_q);
        # floor at 1 token — tiny contexts truncate unaligned rather than
        # keeping nothing (ids[-0:] would silently keep everything)
        keep = (limit - args.max_new_tokens) // 128 * 128
        if keep <= 0:
            keep = limit - args.max_new_tokens
        if keep <= 0:
            print(f"[sample] max-new-tokens {args.max_new_tokens} >= model "
                  f"max positions {limit}: no room for a prompt",
                  file=sys.stderr)
            return 2
        print(f"[sample] prompt of {len(ids)} tokens truncated to its last "
              f"{keep} (model max positions {limit} - "
              f"{args.max_new_tokens} new)", file=sys.stderr)
        ids = ids[-keep:]
    prompt = jnp.asarray(ids, jnp.int32)[None, :]
    # init on a short dummy: param shapes are seq-independent, and a full
    # uncached forward over a 16k prompt just to initialize would run the
    # single-shot attention the chunked prefill exists to avoid
    init_toks = prompt[:, : min(prompt.shape[1], 128)]
    init_kwargs = {}
    if getattr(args, "speculative", False):
        n_drafts = getattr(args, "spec_drafts", 1)
        if getattr(cfg.model, "mtp_heads", 0) < n_drafts:
            print(
                f"--speculative with --spec-drafts {n_drafts} needs a model "
                f"with mtp_heads >= {n_drafts} "
                f"(config {cfg.name!r} has {getattr(cfg.model, 'mtp_heads', 0)})",
                file=sys.stderr,
            )
            return 1
        if not args.greedy:
            print(
                "--speculative decodes greedily (exact-match draft "
                "verification); pass --greedy — temperature/top-k are "
                "not supported",
                file=sys.stderr,
            )
            return 1
        if prompt.shape[1] < 2:
            print(
                "--speculative needs a prompt of at least 2 tokens "
                "(pass --prompt)",
                file=sys.stderr,
            )
            return 1
        # trace the MTP branch so the head params / routing state exist
        # even without a checkpoint
        init_kwargs["return_mtp"] = True
    variables = model.init({"params": rng}, init_toks, **init_kwargs)
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}

    if args.checkpoint_dir:
        restored = _restore_for_inference(
            cfg, model, args.checkpoint_dir, {"x": prompt, "y": prompt}
        )
        if restored is None:
            print(f"no checkpoint found in {args.checkpoint_dir}", file=sys.stderr)
            return 1
        _, params, extra_restored = restored
        if extra_restored:
            extra = extra_restored

    sampler = (
        ops.sample_greedy
        if args.greedy
        else functools.partial(ops.sample_top_k, k=args.top_k, temperature=args.temperature)
    )
    # long prompts prefill in chunks (static end-aligned flash/causal calls
    # into the cache) so activation memory stays bounded; "auto" = one chunk
    # for short prompts, 2048-token chunks past that
    chunk = args.prefill_chunk
    if chunk is None and prompt.shape[1] > 4096:
        chunk = 2048
    if getattr(args, "speculative", False):
        # MTP self-speculative greedy decode (infer/speculative.py):
        # output identical to --greedy, fewer forwards
        from solvingpapers_tpu.infer import generate_speculative

        out, stats = generate_speculative(
            model, params, prompt, max_new_tokens=args.max_new_tokens,
            extra_variables=extra or None, prefill_chunk=chunk,
            n_drafts=getattr(args, "spec_drafts", 1),
        )
        f, a = int(stats["forwards"]), int(stats["accepted"])
        print(
            f"[speculative] forwards={f} accepted={a} "
            f"tokens/forward={(f + a) / max(f, 1):.2f}",
            file=sys.stderr,
        )
    else:
        out = generate(
            model, params, prompt, rng, max_new_tokens=args.max_new_tokens,
            sampler=sampler, extra_variables=extra or None, prefill_chunk=chunk,
        )
    print(tok.decode(np.asarray(out[0])))
    return 0


def _serve_model(args, *, quiet_random_init: bool = False):
    """Build the serving model EXACTLY as `cli serve` does — config
    densification, `jax.random.key(args.seed)` init, optional
    checkpoint restore, and the full-vocab token table. `cli replay`
    reuses this so a journal recorded by a serving process replays
    byte-exactly in a different process: same seed -> same params ->
    same logits. Returns (model, params, extra, table, encode, decode)
    or an int exit code on a usage error."""
    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import build_char_lm_run
    from solvingpapers_tpu.serve.openai import extend_token_table

    cfg = get_config(args.config)
    if cfg.train.pipeline_parallel:
        print("serving is unsupported for pipeline-parallel configs; "
              "export the stage-stacked params to the dense family first",
              file=sys.stderr)
        return 2
    if getattr(cfg.model, "context_parallel", False):
        # params are replicated at rest: serve the dense twin, exactly
        # like cmd_sample's single-chip path
        from solvingpapers_tpu.sharding import MeshConfig

        cfg = dataclasses.replace(
            cfg,
            model=dataclasses.replace(cfg.model, context_parallel=False),
            train=dataclasses.replace(
                cfg.train, context_parallel=False, mesh=MeshConfig()
            ),
        )
    if args.data_path:
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": args.data_path})
    cfg, model, tok, _, _ = build_char_lm_run(cfg)

    dummy = jnp.zeros((1, 8), jnp.int32)
    variables = model.init({"params": jax.random.key(args.seed)}, dummy)
    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    if args.checkpoint_dir:
        restored = _restore_for_inference(
            cfg, model, args.checkpoint_dir, {"x": dummy, "y": dummy}
        )
        if restored is None:
            print(f"no checkpoint found in {args.checkpoint_dir}",
                  file=sys.stderr)
            return 1
        _, params, extra_restored = restored
        if extra_restored:
            extra = extra_restored
    elif not quiet_random_init:
        print("[serve] no --checkpoint-dir: serving RANDOM-INIT params "
              "(endpoint/latency demo, not a language model)",
              file=sys.stderr)

    # token table over the FULL model vocab: corpus tokenizer ids decode
    # normally, spare ids (model vocab_size > corpus charset) map to the
    # missing JSON structural chars so json_object mode is expressible
    vocab = getattr(model.cfg, "vocab_size", tok.vocab_size)
    table = []
    for i in range(vocab):
        try:
            table.append(tok.decode([i]))
        except (KeyError, IndexError):
            table.append(None)
    table = extend_token_table(table, vocab)
    stoi = {}
    for i, t in enumerate(table):
        if t is not None and len(t) == 1 and t not in stoi:
            stoi[t] = i

    def encode(s: str):
        return [stoi[c] for c in s]

    def decode(ids):
        return "".join(table[int(i)] or "" for i in ids)

    return model, params, extra, table, encode, decode


def cmd_serve(args) -> int:
    """Serve a model over the OpenAI-compatible HTTP front door
    (serve/api.py): POST /v1/completions + /v1/chat/completions (SSE
    streaming, json_object mode) plus /healthz /metrics /statusz on ONE
    port. Ctrl-C / SIGTERM shuts down in order: drain active streams,
    close the engine, stop the HTTP threads."""
    import signal
    import threading

    from solvingpapers_tpu.serve.api import ApiServer
    from solvingpapers_tpu.serve.engine import ServeConfig, ServeEngine

    built = _serve_model(args)
    if isinstance(built, int):
        return built
    model, params, extra, table, encode, decode = built
    slo_targets = None
    if args.slo:
        from solvingpapers_tpu.serve.slo import DEFAULT_SLO_TARGETS

        slo_targets = DEFAULT_SLO_TARGETS
    limit = getattr(model, "max_positions", None) or 512
    max_len = args.max_len or min(512, limit)
    serve_cfg = ServeConfig(
        n_slots=args.slots,
        max_len=max_len,
        decode_block=args.decode_block,
        bucket=min(args.bucket, max_len),
        sample_cap=args.sample_cap,
        paged=args.paged,
        kv_quant=args.kv_quant,
        kv_quant_block=args.kv_quant_block,
        kv_exact_lanes=args.kv_exact_lanes,
        speculative=args.speculative,
        spec_k=args.spec_k,
        spec_rounds=args.spec_rounds,
        api_port=args.port,
        api_host=args.host,
        json_mode=not args.no_json_mode,
        max_waiting=args.max_waiting,
        trace=args.trace,
        slo_targets=slo_targets,
        degrade=args.degrade,
        fault_step_deadline_s=args.step_deadline,
        journal_path=args.journal,
        journal_strict=args.journal_strict,
        timeseries=args.timeseries_interval > 0,
        timeseries_interval_s=args.timeseries_interval or 1.0,
        timeseries_capacity=args.timeseries_capacity,
    )
    n_replicas = max(1, args.replicas)
    engines = []
    for i in range(n_replicas):
        rep_cfg = serve_cfg
        if args.journal and n_replicas > 1:
            # each replica needs its own write-ahead journal — a shared
            # file would interleave records from independent engines
            rep_cfg = dataclasses.replace(
                serve_cfg, journal_path=f"{args.journal}.r{i}"
            )
        eng = ServeEngine(model, params, rep_cfg,
                          extra_variables=extra or None, detokenize=decode)
        if rep_cfg.journal_path:
            # crash-safe warm restart: replay the journal's unfinished
            # entries BEFORE the front door starts stepping — recovered
            # greedy/seeded streams continue token-exactly
            resumed = eng.recover()
            print(f"[serve] journal {rep_cfg.journal_path}: recovered "
                  f"{len(resumed)} in-flight request(s)", file=sys.stderr)
        engines.append(eng)
    router = None
    if n_replicas > 1:
        from solvingpapers_tpu.serve.fleet import FleetRouter

        router = FleetRouter(engines)
        server = ApiServer(encode=encode, decode=decode,
                           token_table=table, model_name=args.config,
                           router=router)
        fleet_note = f" — fleet of {n_replicas} replicas"
    else:
        server = ApiServer(engines[0], encode=encode, decode=decode,
                           token_table=table, model_name=args.config)
        fleet_note = ""
    print(f"[serve] {args.config} on http://{server.host}:{server.port} "
          f"— POST /v1/completions /v1/chat/completions, "
          f"GET /healthz /metrics /statusz{fleet_note}", file=sys.stderr)

    stop = threading.Event()

    def _sig(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop.wait(0.5):
            pass
    finally:
        print("[serve] shutting down: draining streams, closing engine",
              file=sys.stderr)
        server.close()
        if args.trace_out:
            # export AFTER close so the drain/shutdown spans make the
            # file; the recorders outlive their engines
            try:
                if router is not None:
                    router.export_chrome_fleet(args.trace_out)
                elif engines[0].trace is not None:
                    engines[0].trace.export_chrome(args.trace_out)
                else:
                    raise ValueError("tracing is off — pass --trace")
                print(f"[serve] trace -> {args.trace_out}",
                      file=sys.stderr)
            except ValueError as e:
                print(f"[serve] --trace-out skipped: {e}",
                      file=sys.stderr)
    return 0


def cmd_replay(args) -> int:
    """Replay a request journal against a candidate serving config and
    gate on the divergence report (serve/replay.py) — the config-canary
    check: journal production traffic, replay it under the proposed
    knobs, ship only if the streams still match.

    Builds the model exactly as `cli serve` does (same --config /
    --seed / --checkpoint-dir => same params), loads the journal's
    finished streams, re-serves them on a fresh engine shaped by the
    engine flags + --config-overrides, and prints the report JSON.
    With overrides, the un-overridden config is re-served too for
    paired latency/throughput deltas.

    Exit codes: 0 = gate passed; 2 = divergence beyond
    --byte-exact-min / --agreement-min (the CI-able canary signal);
    1 = operational failure (unreadable journal, nothing comparable)."""
    from solvingpapers_tpu.serve.engine import ServeConfig
    from solvingpapers_tpu.serve.journal import JournalError
    from solvingpapers_tpu.serve.replay import ReplayHarness, apply_overrides

    built = _serve_model(args, quiet_random_init=True)
    if isinstance(built, int):
        return built
    model, params, extra, _, _, decode = built
    if not args.checkpoint_dir:
        print("[replay] no --checkpoint-dir: random-init params — fine "
              "iff the journal was recorded by the same seed's "
              "random-init server", file=sys.stderr)

    limit = getattr(model, "max_positions", None) or 512
    max_len = args.max_len or min(512, limit)
    base_cfg = ServeConfig(
        n_slots=args.slots,
        max_len=max_len,
        decode_block=args.decode_block,
        bucket=min(args.bucket, max_len),
        sample_cap=args.sample_cap,
        paged=args.paged,
        kv_quant=args.kv_quant,
        kv_quant_block=args.kv_quant_block,
        kv_exact_lanes=args.kv_exact_lanes,
        speculative=args.speculative,
        spec_k=args.spec_k,
        spec_rounds=args.spec_rounds,
        max_waiting=args.max_waiting,
    )
    overrides = {}
    for kv in args.config_overrides or []:
        if "=" not in kv:
            print(f"[replay] --config-overrides takes KEY=VALUE pairs, "
                  f"got {kv!r}", file=sys.stderr)
            return 2
        k, v = kv.split("=", 1)
        overrides[k] = v
    try:
        candidate = apply_overrides(base_cfg, overrides)
    except (ValueError, TypeError) as e:
        print(f"[replay] {e}", file=sys.stderr)
        return 2

    harness = ReplayHarness(model, params, extra_variables=extra or None,
                            detokenize=decode)
    try:
        entries = harness.load(args.journal)
    except FileNotFoundError:
        print(f"[replay] journal not found: {args.journal}",
              file=sys.stderr)
        return 1
    except JournalError as e:
        print(f"[replay] {e}", file=sys.stderr)
        return 1
    print(f"[replay] {args.journal}: {len(entries)} journaled "
          f"request(s)", file=sys.stderr)

    report = harness.run(
        entries, candidate,
        baseline=base_cfg if overrides else None,
        cut_stride=args.cut_stride,
        max_cuts=args.max_cuts,
        max_requests=args.max_requests,
        pace=args.pace,
        journal_path=args.journal,
    )
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(f"[replay] wrote {args.out}", file=sys.stderr)

    if report["streams_compared"] == 0:
        print("[replay] no byte-comparable streams (greedy or seeded) "
              "in the journal — the gate is undecidable", file=sys.stderr)
        return 1
    bex = report["byte_exact_rate"]
    agr = report["agreement_rate"]
    print(f"[replay] byte_exact_rate={bex} agreement_rate={agr} "
          f"compared={report['streams_compared']} "
          f"skipped={len(report['skipped'])} "
          f"wall={report['replay_wall_s']}s", file=sys.stderr)
    failed = []
    if bex < args.byte_exact_min:
        failed.append(f"byte_exact_rate {bex} < {args.byte_exact_min}")
    if args.agreement_min and (agr is None or agr < args.agreement_min):
        failed.append(f"agreement_rate {agr} < {args.agreement_min}")
    if failed:
        print(f"[replay] DIVERGENCE GATE FAILED: {'; '.join(failed)}",
              file=sys.stderr)
        return 2
    return 0


def cmd_serve_bench(args) -> int:
    """Continuous-batching engine vs sequential one-shot generate on a
    synthetic Poisson arrival stream — or, with --shared-prefix, prefix
    cache on vs off over K shared system prompts, or, with --sampling,
    a per-request SamplingParams mix vs all-greedy on the same trace,
    or, with --paged, the paged KV pool vs the lane pool (throughput,
    equal-HBM capacity, zero-copy prefix TTFT) (serve/bench.py); prints
    the BENCH-shaped JSON and optionally writes it to --out."""
    if args.checkpoint_dir or args.data_path:
        print(
            "serve-bench benchmarks scheduling throughput on random-init "
            "params; --checkpoint-dir/--data-path are not consumed",
            file=sys.stderr,
        )
        return 2
    if sum((args.shared_prefix, args.sampling, args.paged, args.http,
            args.speculative, args.slo, args.chaos, args.journal,
            args.fleet, args.replay, args.kv_quant is not None)) > 1:
        print("--shared-prefix, --sampling, --paged, --http, "
              "--speculative, --slo, --chaos, --journal, --fleet, "
              "--replay and --kv-quant are separate workloads; pick "
              "one per run",
              file=sys.stderr)
        return 2
    from solvingpapers_tpu.serve.bench import (
        bench_provenance,
        run_chaos_bench,
        run_fleet_bench,
        run_http_bench,
        run_journal_bench,
        run_paged_bench,
        run_prefix_bench,
        run_quant_bench,
        run_replay_bench,
        run_sampling_bench,
        run_serve_bench,
        run_slo_bench,
        run_spec_bench,
    )

    max_new = args.max_new_tokens
    if max_new is None:
        max_new = 4 if args.shared_prefix else 64
    decode_block = args.decode_block
    if decode_block is None:
        decode_block = 4 if args.shared_prefix else 16
    n_requests = args.requests
    if n_requests is None:
        n_requests = 48 if args.shared_prefix else 32
    # shared flags with per-workload defaults (None sentinel, so an
    # EXPLICIT value always wins — even one that matches another
    # workload's default)
    n_slots = args.slots
    if n_slots is None:
        n_slots = 4 if args.chaos else 8
    mean_ia = args.mean_interarrival
    if mean_ia is None:
        mean_ia = 0.15 if args.chaos else 0.001
    prompt_lens = args.prompt_lens
    if prompt_lens is None:
        # --speculative defaults to gpt_tiny_long (256 positions):
        # streams must be long enough for drafts to find history
        prompt_lens = [24, 32, 40, 48] if args.speculative \
            else [16, 32, 48, 64]
    trace_kwargs = dict(
        trace=args.trace,
        trace_out=args.trace_out if args.trace else None,
        trace_dump=args.trace_dump if args.trace else None,
        obs=args.obs,
        status_port=args.status_port,
        status_hold_s=args.status_hold_s,
    )
    if args.obs_hlo_dir:
        if any((args.shared_prefix, args.sampling, args.paged, args.http,
                args.speculative, args.slo, args.chaos, args.journal,
                args.fleet, args.replay, args.kv_quant is not None)):
            # say so instead of silently dropping the flag — a user
            # waiting on dumps should not debug an empty directory
            print("--obs-hlo-dir only dumps from the Poisson workload's "
                  "probe engine; ignoring it for this workload (use "
                  "ServeConfig.obs_hlo_dir directly elsewhere)",
                  file=sys.stderr)
        else:
            # Poisson workload: the probe engine is the one that dumps
            trace_kwargs["obs_hlo_dir"] = args.obs_hlo_dir
    if args.replay:
        result = run_replay_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=args.max_new_tokens or 48,
            decode_block=args.decode_block or 8,
            prompt_lens=tuple(prompt_lens),
            train_steps=args.replay_train_steps,
            seed=args.seed,
            page_size=args.page_size,
            kv_quant_block=args.kv_quant_block,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.kv_quant:
        result = run_quant_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            page_size=args.page_size,
            kv_quant_block=args.kv_quant_block,
            train_steps=args.quant_train_steps,
            seed=args.seed,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.speculative:
        result = run_spec_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=args.max_new_tokens or 160,
            decode_block=args.decode_block or 8,
            spec_k=args.spec_k,
            spec_rounds=args.spec_rounds,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            train_steps=args.spec_train_steps,
            seed=args.seed,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.chaos:
        result = run_chaos_bench(
            config=args.config,
            n_requests=args.requests or 48,
            n_slots=n_slots,
            max_new=args.max_new_tokens or 48,
            decode_block=args.decode_block or 8,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            seed=args.seed,
            stall_s=args.chaos_stall,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.fleet:
        result = run_fleet_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            n_replicas=args.fleet_replicas,
            seed=args.seed,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
            trace_out=args.trace_out if args.trace else None,
        )
    elif args.journal:
        result = run_journal_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            seed=args.seed,
            kill_step=args.journal_kill_step,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.slo:
        result = run_slo_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            seed=args.seed,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.http:
        result = run_http_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            seed=args.seed,
        )
    elif args.paged:
        result = run_paged_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            n_prefixes=args.n_prefixes,
            prefix_requests=args.prefix_requests,
            suffix_len=args.suffix_len,
            page_size=args.page_size,
            seed=args.seed,
            status_port=args.status_port,
            status_hold_s=args.status_hold_s,
        )
    elif args.sampling:
        result = run_sampling_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            seed=args.seed,
            **trace_kwargs,
        )
    elif args.shared_prefix:
        result = run_prefix_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            n_prefixes=args.n_prefixes,
            prefix_len=args.prefix_len,
            suffix_len=args.suffix_len,
            mean_interarrival_s=mean_ia,
            prefix_page=args.prefix_page,
            seed=args.seed,
            **trace_kwargs,
        )
    else:
        result = run_serve_bench(
            config=args.config,
            n_requests=n_requests,
            n_slots=n_slots,
            max_new=max_new,
            decode_block=decode_block,
            prompt_lens=tuple(prompt_lens),
            mean_interarrival_s=mean_ia,
            seed=args.seed,
            skip_sequential=args.skip_sequential,
            **trace_kwargs,
        )
    # identity stamp (schema v2): ONE clock reading injected here — the
    # single place entries are written — so every entry is attributable
    # to a git sha / jax / host after any future rebase
    import time as _time

    result = {**bench_provenance(timestamp=_time.time()), **result}
    line = json.dumps(result)
    print(line)
    if args.out:
        with open(args.out, "a" if args.append else "w") as f:
            f.write(line + "\n")
        verb = "appended to" if args.append else "wrote"
        print(f"[serve-bench] {verb} {args.out}", file=sys.stderr)
    return 0


def cmd_kernel_bench(args) -> int:
    """Microbench the serving stack's hot inner ops in isolation over
    the full (pool layout x kv_quant) grid and print/write one
    BENCH_kernels.json entry per grid cell (serve/kernel_bench.py)."""
    from solvingpapers_tpu.serve.bench import bench_provenance
    from solvingpapers_tpu.serve.kernel_bench import run_kernel_bench

    entries = run_kernel_bench(
        config=args.config,
        n_slots=args.slots,
        max_len=args.max_len,
        page_size=args.page_size,
        quant_block=args.kv_quant_block,
        sample_cap=args.sample_cap,
        spec_k=args.spec_k,
        decode_block=args.decode_block,
        reps=args.reps,
        seed=args.seed,
    )
    # one provenance stamp per RUN (the serve-bench discipline: the
    # timestamp is injected at the single write site, so the grid's
    # four entries share one clock reading and one git sha)
    import time as _time

    prov = bench_provenance(timestamp=_time.time())
    lines = [json.dumps({**prov, **e}) for e in entries]
    for line in lines:
        print(line)
    if args.out:
        with open(args.out, "a" if args.append else "w") as f:
            for line in lines:
                f.write(line + "\n")
        verb = "appended to" if args.append else "wrote"
        print(f"[kernel-bench] {verb} {args.out} "
              f"({len(lines)} entries)", file=sys.stderr)
    return 0


def cmd_trace_summary(args) -> int:
    """Rebuild per-request timelines from a Chrome trace-event JSON the
    flight recorder exported (`serve-bench --trace`,
    `engine.trace.export_chrome`, or TrainConfig.trace_path) and print
    phase breakdowns plus the slowest requests (metrics/trace.py)."""
    import os

    from solvingpapers_tpu.metrics.trace import (
        format_summary,
        format_train_summary,
        summarize_trace,
        summarize_train_trace,
    )

    if not os.path.exists(args.trace):
        print(f"no trace file at {args.trace}", file=sys.stderr)
        return 2
    try:
        summary = summarize_trace(args.trace)
    except json.JSONDecodeError as e:
        # truncated exports (a killed run mid-write) and non-JSON files
        # are operator input errors, not tracebacks: say what and where
        print(
            f"{args.trace} is not valid JSON (truncated export?): "
            f"{e.msg} at line {e.lineno} column {e.colno}",
            file=sys.stderr,
        )
        return 2
    except (ValueError, TypeError, AttributeError, KeyError) as e:
        if isinstance(e, ValueError) and "partial fleet export" in str(e):
            # the stitcher's own diagnosis is the clearest message we
            # could print — a truncated fleet file must not masquerade
            # as a generic parse failure
            print(f"{args.trace}: {e}", file=sys.stderr)
            return 2
        print(
            f"{args.trace} does not parse as a Chrome trace-event JSON "
            f"({type(e).__name__}: {e}) — expected the flight recorder's "
            "export format",
            file=sys.stderr,
        )
        return 2
    except OSError as e:
        print(f"cannot read {args.trace}: {e}", file=sys.stderr)
        return 2
    if getattr(args, "fleet", False) and "fleet" not in summary:
        print(
            f"{args.trace} holds no fleet events: --fleet expects the "
            "stitched export (FleetRouter.export_chrome_fleet, "
            "`serve --replicas N --trace --trace-out`, or "
            "`serve-bench --fleet --trace-out`); this looks like a "
            "single-engine trace — rerun without --fleet",
            file=sys.stderr,
        )
        return 2
    if summary["n_requests"] or summary["rejected"]:
        print(format_summary(summary, top=args.top))
        return 0
    # request-less traces: a train trace keeps its per-phase summary even
    # when the observatory also recorded compile events — the roofline
    # and mesh (bubble/comm) sections ride along instead of displacing it
    from solvingpapers_tpu.metrics.hlo_cost import format_anatomy
    from solvingpapers_tpu.metrics.trace import format_mesh, format_roofline

    train = summarize_train_trace(args.trace)
    roofline = format_roofline(summary.get("programs") or {})
    anatomy = format_anatomy(summary.get("anatomy") or {})
    mesh = format_mesh(summary.get("mesh"))
    if train is not None:
        print(format_train_summary(train))
        for section in (roofline, anatomy, mesh):
            if section:
                print()
                print(section)
        return 0
    if roofline or anatomy or mesh:
        print("\n\n".join(s for s in (roofline, anatomy, mesh) if s))
        return 0
    print(
        f"{args.trace} holds neither request lifecycle events "
        "(ServeConfig(trace=True)) nor train spans "
        "(TrainConfig.trace_path) — was it exported by the flight "
        "recorder?",
        file=sys.stderr,
    )
    return 1


def _restore_for_inference(cfg, model, checkpoint_dir, example_batch, trainer=None):
    """Shared restore path: returns (state, params, extra_variables) from
    the newest checkpoint, or None if the directory is empty."""
    from solvingpapers_tpu.checkpoint import CheckpointManager
    from solvingpapers_tpu.configs.factory import init_fn_for, rules_for
    from solvingpapers_tpu.train import Trainer
    from solvingpapers_tpu.train.engine import _apply_pure, _pure_state

    if trainer is None:
        trainer = Trainer(model, cfg.train, init_fn=init_fn_for(cfg),
                          rules=rules_for(cfg))
    state = trainer.init_state(example_batch)
    mgr = CheckpointManager(checkpoint_dir, save_every=0)
    restored = mgr.restore_latest(_pure_state(state))
    if restored is None:
        return None
    state = _apply_pure(state, restored[0])
    extra = restored[0].get("model_state") or {}
    return state, restored[0]["params"], extra


def cmd_eval(args) -> int:
    """estimate_loss over the held-out split (gpt cell 14 / gemma cell 17 /
    dsv3 cell 48) or accuracy for classifiers (ViT cell 15, kd.py:145)."""
    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import (
        build_char_lm_run,
        build_image_run,
        init_fn_for,
        loss_fn_for,
        rules_for,
    )
    from solvingpapers_tpu.sharding import batch_sharding, create_mesh
    from solvingpapers_tpu.train import Trainer

    cfg = get_config(args.config)
    if args.data_path:
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": args.data_path})
    mesh = create_mesh(cfg.train.mesh)
    cp = getattr(cfg.model, "context_parallel", False)
    if cfg.data.get("kind", "char") == "images":
        model, _, eval_iter_fn, loss_fn = build_image_run(cfg, mesh=mesh)
    else:
        cfg, model, _, _, eval_iter_fn = build_char_lm_run(
            cfg, sharding=batch_sharding(mesh, context=cp)
        )
        loss_fn = loss_fn_for(cfg)
    trainer = Trainer(model, cfg.train, loss_fn=loss_fn,
                      init_fn=init_fn_for(cfg), mesh=mesh, rules=rules_for(cfg))
    eval_iter = eval_iter_fn()
    first = next(eval_iter)
    if args.checkpoint_dir:
        restored = _restore_for_inference(
            cfg, model, args.checkpoint_dir, first, trainer=trainer
        )
        if restored is None:
            print(f"no checkpoint found in {args.checkpoint_dir}", file=sys.stderr)
            return 1
        state = restored[0]
    else:
        state = trainer.init_state(first)
    import itertools

    metrics = trainer.evaluate(state, itertools.chain([first], eval_iter))
    print(json.dumps({k: round(float(v), 6) for k, v in metrics.items()}))
    return 0


def cmd_export(args) -> int:
    """Params-only export (the reference publishes bare weights to HF)."""
    from solvingpapers_tpu.checkpoint import export_params
    from solvingpapers_tpu.configs import get_config
    from solvingpapers_tpu.configs.factory import (
        build_char_lm_run,
        build_image_run,
    )
    from solvingpapers_tpu.sharding import create_mesh

    if not args.checkpoint_dir:
        print("export requires --checkpoint-dir", file=sys.stderr)
        return 2
    cfg = get_config(args.config)
    if args.data_path:
        cfg = dataclasses.replace(cfg, data={**cfg.data, "path": args.data_path})
    mesh = create_mesh(cfg.train.mesh)
    if cfg.data.get("kind", "char") == "images":
        model, train_iter, _, _ = build_image_run(cfg, mesh=mesh)
    else:
        cfg, model, _, train_iter, _ = build_char_lm_run(cfg)
    first = next(train_iter)
    restored = _restore_for_inference(cfg, model, args.checkpoint_dir, first)
    if restored is None:
        print(f"no checkpoint found in {args.checkpoint_dir}", file=sys.stderr)
        return 1
    _, params, _ = restored
    export_params(args.out, params)
    print(f"exported params to {args.out}")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="solvingpapers_tpu")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list")

    p_train = sub.add_parser("train")
    _add_common(p_train)
    p_train.add_argument("--steps", type=int, default=None)
    p_train.add_argument("--ckpt-every", type=int, default=1000)
    p_train.add_argument("--jsonl", default=None)
    p_train.add_argument(
        "--artifacts-dir",
        default=None,
        help="write qualitative artifacts here: generated_{step}.txt each "
        "eval for LMs, reconstructions.png after AE/VAE training",
    )

    p_sample = sub.add_parser("sample")
    _add_common(p_sample)
    p_sample.add_argument("--prompt", default=None)
    p_sample.add_argument("--prompt-file", default=None,
                          help="read the prompt text from a file (long-"
                               "context prompts, e.g. 16k tokens)")
    p_sample.add_argument("--prefill-chunk", type=int, default=None,
                          help="prefill the prompt in chunks of this many "
                               "tokens (default: auto — 2048 past 4096)")
    p_sample.add_argument("--max-new-tokens", type=int, default=200)
    p_sample.add_argument("--top-k", type=int, default=50)
    p_sample.add_argument("--temperature", type=float, default=1.0)
    p_sample.add_argument("--greedy", action="store_true")
    p_sample.add_argument(
        "--speculative", action="store_true",
        help="MTP self-speculative greedy decode (models with mtp_heads "
             ">= 1): identical output to --greedy in fewer forwards; "
             "prints acceptance stats to stderr",
    )
    p_sample.add_argument(
        "--spec-drafts", type=int, default=1, choices=(1, 2),
        help="[--speculative] chained MTP heads to draft with (2 needs "
             "mtp_heads >= 2; commits up to 3 tokens per forward)",
    )
    p_sample.add_argument("--seed", type=int, default=0)

    p_serve = sub.add_parser("serve-bench")
    _add_common(p_serve)
    p_serve.add_argument("--requests", type=int, default=None,
                         help="default 32 (48 with --shared-prefix)")
    p_serve.add_argument("--slots", type=int, default=None,
                         help="default 8 (4 with --chaos, whose ladder "
                              "arm needs deliberate overload)")
    p_serve.add_argument("--max-new-tokens", type=int, default=None,
                         help="default 64 (4 with --shared-prefix, whose "
                              "TTFT story is prefill-bound)")
    p_serve.add_argument("--decode-block", type=int, default=None,
                         help="default 16 (4 with --shared-prefix)")
    p_serve.add_argument("--prompt-lens", type=int, nargs="+",
                         default=None,
                         help="prompt-length cycle (bounded set => bounded "
                              "compiles in both arms); default "
                              "16 32 48 64 (24 32 40 48 with "
                              "--speculative)")
    p_serve.add_argument("--mean-interarrival", type=float, default=None,
                         help="Poisson arrival mean gap in seconds; "
                              "default 0.001 (0.15 with --chaos — "
                              "admissions must keep arriving while the "
                              "ladder is up for shedding to be "
                              "observable)")
    p_serve.add_argument("--seed", type=int, default=0)
    p_serve.add_argument("--skip-sequential", action="store_true",
                         help="only run the engine arm")
    p_serve.add_argument("--shared-prefix", action="store_true",
                         help="shared-prefix workload instead: N requests "
                              "over K distinct system prompts, prefix "
                              "cache on vs off (serve/bench.py "
                              "run_prefix_bench)")
    p_serve.add_argument("--sampling", action="store_true",
                         help="sampling workload instead: the same Poisson "
                              "trace decoded all-greedy vs with a "
                              "per-request temperature/top-p/top-k/min-p "
                              "mix (serve/bench.py run_sampling_bench)")
    p_serve.add_argument("--http", action="store_true",
                         help="HTTP soak workload instead: the Poisson "
                              "trace as N concurrent SSE clients through "
                              "the OpenAI front door, ABBA-paired against "
                              "direct engine.submit — req/s, client-side "
                              "TTFT, p99 ITL and http_overhead_pct "
                              "(serve/bench.py run_http_bench)")
    p_serve.add_argument("--paged", action="store_true",
                         help="paged-KV-pool workload instead: ABBA-paired "
                              "paged vs lane pool on the Poisson trace, a "
                              "capacity arm at equal HBM (2x slots, "
                              "lane-equivalent page budget), and a "
                              "shared-prefix arm with zero-copy page "
                              "sharing (serve/bench.py run_paged_bench)")
    p_serve.add_argument("--speculative", action="store_true",
                         help="speculative-decoding workload instead: "
                              "ABBA-paired spec-on (n-gram drafter) vs "
                              "spec-off delivered tokens/sec on a "
                              "briefly-trained model, with a greedy "
                              "token-exactness check and a temperature-"
                              "2.0 zero-acceptance adversarial arm "
                              "(serve/bench.py run_spec_bench; defaults "
                              "max-new-tokens 160, decode-block 8)")
    p_serve.add_argument("--slo", action="store_true",
                         help="SLO-observatory workload instead: the "
                              "Poisson trace with per-request SLO "
                              "classes (interactive/standard/batch "
                              "cycle) through an slo_targets-enabled "
                              "engine, ABBA-paired against the plain "
                              "engine — slo_overhead_pct (<= 2%% "
                              "budget), per-class attainment, burn "
                              "rates and goodput_tokens_per_s "
                              "(serve/bench.py run_slo_bench)")
    p_serve.add_argument("--chaos", action="store_true",
                         help="fault-tolerance soak instead: one seeded "
                              "fault schedule (NaN/Inf slot poisons, "
                              "synthetic XlaRuntimeError + OOM, a step "
                              "stall) over the Poisson trace through a "
                              "fault-free reference, a ladder-off chaos "
                              "arm (streams_survived, survivor token-"
                              "exactness, fault_recovery_s, zero-leak "
                              "drain) and a ladder-on arm (goodput with "
                              "degradation on vs off), plus the ABBA-"
                              "paired armed-but-quiet fault_overhead_pct "
                              "(serve/bench.py run_chaos_bench)")
    p_serve.add_argument("--journal", action="store_true",
                         help="durability workload instead: ABBA-paired "
                              "journal-on vs journal-off req/s on the "
                              "Poisson trace (journal_overhead_pct, "
                              "<= 2%% budget — fsync batched per step) "
                              "plus a kill-and-recover arm: abandon the "
                              "engine mid-decode, replay the journal "
                              "through a fresh one, and record "
                              "recovery_wall_s / recovered_requests / "
                              "recovered_token_exact (serve/bench.py "
                              "run_journal_bench)")
    p_serve.add_argument("--fleet", action="store_true",
                         help="fleet workload instead: the Poisson trace "
                              "through a multi-replica FleetRouter — "
                              "router_overhead_pct (ABBA-paired 1-replica "
                              "router vs bare engine, pure routing tax), "
                              "fleet token-exactness vs a single-engine "
                              "reference, and a mid-decode drain arm: "
                              "drain replica r0 with streams live, adopt "
                              "them on the peer, record migration_wall_s "
                              "/ migrated_streams / migrated_token_exact "
                              "and zero-leak on BOTH replicas "
                              "(serve/bench.py run_fleet_bench)")
    p_serve.add_argument("--fleet-replicas", type=int, default=2,
                         help="[--fleet] replica count for the exactness "
                              "and drain arms (the overhead arm is "
                              "always 1 replica, like-for-like)")
    p_serve.add_argument("--journal-kill-step", type=int, default=None,
                         help="[--journal] engine step at which the "
                              "kill-and-recover arm abandons the first "
                              "engine (default: a mid-decode point "
                              "derived from the workload)")
    p_serve.add_argument("--chaos-stall", type=float, default=0.75,
                         help="[--chaos] injected step-stall seconds; "
                              "the watchdog deadline is set BELOW it "
                              "(max(0.25, 0.75x)) so the stall "
                              "deterministically trips the fire path")
    p_serve.add_argument("--replay", action="store_true",
                         help="replay-observatory workload instead: "
                              "journal a seeded greedy+seeded-sampling "
                              "workload on a briefly-trained model, "
                              "replay it through serve/replay.py "
                              "against (a) the identical config on "
                              "BOTH pool layouts — replay_byte_exact, "
                              "the never-flip CI gate — and (b) an "
                              "int8-kv candidate — "
                              "replay_agreement_rate, the graded "
                              "teacher-forced score (serve/bench.py "
                              "run_replay_bench; defaults config "
                              "gpt_tiny_long via tools/bench_serve.py)")
    p_serve.add_argument("--replay-train-steps", type=int, default=150,
                         help="[--replay] brief training steps before "
                              "journaling (int8 agreement on random "
                              "init measures argmax tie-breaking, not "
                              "quantization quality; 0 = random init)")
    p_serve.add_argument("--kv-quant", default=None, choices=["int8"],
                         help="quantized-KV workload instead: int8 cache "
                              "storage vs exact on a briefly-trained "
                              "model — greedy-token agreement (teacher-"
                              "forced, the >= 0.99 CI gate), ABBA-paired "
                              "like-for-like Poisson overhead, and a "
                              "capacity arm booking slots at the f32 "
                              "paged pool's resident byte budget "
                              "(serve/bench.py run_quant_bench; defaults "
                              "config gpt_tiny_long)")
    p_serve.add_argument("--kv-quant-block", type=int, default=16,
                         help="[--kv-quant] lane-pool absmax-scale block "
                              "length in tokens "
                              "(ServeConfig.kv_quant_block; the paged "
                              "pool always scales per page)")
    p_serve.add_argument("--quant-train-steps", type=int, default=200,
                         help="[--kv-quant] brief training steps before "
                              "benching (agreement on a random-init "
                              "model measures argmax tie-breaking over "
                              "near-uniform logits, not quantization "
                              "quality; 0 = random init)")
    p_serve.add_argument("--spec-k", type=int, default=16,
                         help="[--speculative] draft tokens per round "
                              "(ServeConfig.spec_k)")
    p_serve.add_argument("--spec-rounds", type=int, default=6,
                         help="[--speculative] draft-verify rounds per "
                              "decode call (ServeConfig.spec_rounds)")
    p_serve.add_argument("--spec-train-steps", type=int, default=300,
                         help="[--speculative] brief training steps on "
                              "the synthetic corpus before benching "
                              "(draft quality is the mechanism under "
                              "test; 0 = random init, all-reject "
                              "regime)")
    p_serve.add_argument("--page-size", type=int, default=16,
                         help="[--paged] tokens per KV page "
                              "(ServeConfig.page_size)")
    p_serve.add_argument("--prefix-requests", type=int, default=None,
                         help="[--paged] request count for the "
                              "shared-prefix sub-arm (default 48, the "
                              "committed measurement regime; CI smokes "
                              "pass a small value)")
    p_serve.add_argument("--n-prefixes", type=int, default=4,
                         help="[--shared-prefix] distinct system prompts K")
    p_serve.add_argument("--prefix-len", type=int, default=None,
                         help="[--shared-prefix] shared stem length "
                              "(default: stretch to the model's position "
                              "budget, page-aligned)")
    p_serve.add_argument("--suffix-len", type=int, default=8,
                         help="[--shared-prefix] unique tail length")
    p_serve.add_argument("--prefix-page", type=int, default=16,
                         help="[--shared-prefix] radix-tree page size")
    p_serve.add_argument("--out", default=None,
                         help="also write the JSON result here "
                              "(tools/bench_serve.py default: BENCH_serve.json)")
    p_serve.add_argument("--append", action="store_true",
                         help="append to --out instead of overwriting "
                              "(BENCH_serve.json is JSON-lines: one entry "
                              "per workload)")
    p_serve.add_argument("--trace", action="store_true",
                         help="run one extra arm with the flight recorder "
                              "on and record trace_overhead_pct (tracing-on "
                              "vs tracing-off req/s on the same arrival "
                              "trace) in the result detail")
    p_serve.add_argument("--trace-out", default="serve_trace.json",
                         help="[--trace] write the traced arm's Chrome "
                              "trace-event JSON here (open in Perfetto or "
                              "feed `cli trace-summary`)")
    p_serve.add_argument("--trace-dump", default=None,
                         help="[--trace] anomaly-dump JSONL path "
                              "(ServeConfig.trace_dump_path): timeouts, "
                              "reject bursts, and slow steps append the "
                              "last ring events + a metrics snapshot")
    p_serve.add_argument("--obs", action="store_true",
                         help="run one extra paired arm with the compile "
                              "& memory observatory on "
                              "(ServeConfig.xla_obs) and record "
                              "obs_overhead_pct (enabled-vs-disabled "
                              "req/s, < 2%% budget); compile_time_s and "
                              "peak_hbm_bytes are recorded per entry "
                              "regardless, from the warm-phase probe")
    p_serve.add_argument("--status-port", type=int, default=None,
                         help="serve /healthz /metrics /statusz from the "
                              "observatory probe engine for the duration "
                              "of the bench (0 = ephemeral port, printed "
                              "to stderr)")
    p_serve.add_argument("--status-hold-s", type=float, default=0.0,
                         help="[--status-port] keep the status endpoint "
                              "up this many seconds after the arms "
                              "finish (CI curl window)")
    p_serve.add_argument("--obs-hlo-dir", default=None,
                         help="dump each compiled program's HLO text "
                              "here from the observatory probe engine "
                              "(ServeConfig.obs_hlo_dir: one file per "
                              "signature, atomic writes) so the anatomy "
                              "ledger's claims can be diffed offline; "
                              "Poisson workload only")

    p_kern = sub.add_parser(
        "kernel-bench",
        help="fenced min-of-reps microbenchmarks of the serving stack's "
             "hot inner ops — gather/scatter/quant-roundtrip/splice/"
             "sample/spec-verify over the (pool layout x kv_quant) grid "
             "(serve/kernel_bench.py; tools/bench_kernels.py defaults "
             "--out BENCH_kernels.json)",
    )
    p_kern.add_argument("--config", default="gpt_shakespeare",
                        help="registered decoder config whose cache "
                             "shapes the ops are benched at (default "
                             "gpt_shakespeare — the paged bench's "
                             "model)")
    p_kern.add_argument("--slots", type=int, default=8)
    p_kern.add_argument("--max-len", type=int, default=256,
                        help="lane length in tokens (rounded down to "
                             "the page/quant-block grain and the "
                             "model's position budget)")
    p_kern.add_argument("--page-size", type=int, default=16)
    p_kern.add_argument("--kv-quant-block", type=int, default=16)
    p_kern.add_argument("--sample-cap", type=int, default=64)
    p_kern.add_argument("--spec-k", type=int, default=4,
                        help="draft width of the speculative 1+k verify "
                             "window op")
    p_kern.add_argument("--decode-block", type=int, default=16,
                        help="recorded knob: sets the decomposition's "
                             "scatter multiplier — the paged decode "
                             "program runs (decode_block-1)//page_size "
                             "+ 2 write-back windows per call")
    p_kern.add_argument("--reps", type=int, default=5,
                        help="fenced repetitions per op (min is kept)")
    p_kern.add_argument("--seed", type=int, default=0)
    p_kern.add_argument("--out", default=None,
                        help="also write the JSON-lines entries here "
                             "(tools/bench_kernels.py default: "
                             "BENCH_kernels.json)")
    p_kern.add_argument("--append", action="store_true",
                        help="append to --out instead of overwriting")

    p_srv = sub.add_parser("serve")
    _add_common(p_srv)
    p_srv.add_argument("--port", type=int, default=8000,
                       help="API port (0 = ephemeral, printed to stderr)")
    p_srv.add_argument("--host", default="127.0.0.1",
                       help="bind address (loopback by default — front "
                            "with a real proxy to expose it)")
    p_srv.add_argument("--slots", type=int, default=8)
    p_srv.add_argument("--max-len", type=int, default=None,
                       help="engine sequence capacity (default: min(512, "
                            "model max positions))")
    p_srv.add_argument("--decode-block", type=int, default=8)
    p_srv.add_argument("--bucket", type=int, default=32)
    p_srv.add_argument("--sample-cap", type=int, default=64)
    p_srv.add_argument("--max-waiting", type=int, default=256)
    p_srv.add_argument("--paged", action="store_true",
                       help="serve over the paged KV pool")
    p_srv.add_argument("--kv-quant", default=None, choices=["int8"],
                       help="hold the KV pool as symmetric int8 with "
                            "per-block absmax scales (~half the resident "
                            "KV bytes vs bf16, a quarter vs f32; output "
                            "quality gated by the bench's measured "
                            "greedy-agreement rate, not exactness)")
    p_srv.add_argument("--kv-quant-block", type=int, default=16,
                       help="[--kv-quant] lane-pool scale block length "
                            "in tokens (must divide max-len; the paged "
                            "pool scales per page)")
    p_srv.add_argument("--kv-exact-lanes", type=int, default=0,
                       help="[--kv-quant] full-precision sidecar lanes "
                            "for SamplingParams.kv_exact requests "
                            "(byte-identical streams inside the "
                            "quantized engine; 0 rejects kv_exact "
                            "submissions)")
    p_srv.add_argument("--speculative", default=None,
                       choices=["ngram", "mtp"],
                       help="speculative decoding: n-gram prompt-lookup "
                            "self-drafting (any family) or MTP heads "
                            "(deepseekv3 with mtp_heads >= 1, lane "
                            "pool); greedy streams stay token-exact, "
                            "stochastic distributions unchanged")
    p_srv.add_argument("--spec-k", type=int, default=4,
                       help="[--speculative] draft tokens per round")
    p_srv.add_argument("--spec-rounds", type=int, default=None,
                       help="[--speculative] draft-verify rounds per "
                            "decode call (default: decode-block)")
    p_srv.add_argument("--no-json-mode", action="store_true",
                       help="reject response_format json_object instead "
                            "of grammar-constraining the decode")
    p_srv.add_argument("--slo", action="store_true",
                       help="account every request under an SLO class "
                            "(serve/slo.py DEFAULT_SLO_TARGETS: "
                            "interactive/standard/batch; requests tag "
                            "one via the 'slo' body field, default "
                            "standard) — per-class attainment, burn "
                            "rate and goodput ride /metrics + /statusz")
    p_srv.add_argument("--degrade", action="store_true",
                       help="arm the degradation ladder "
                            "(serve/faults.py): under page exhaustion, "
                            "HBM-projection breach or SLO burn the "
                            "engine sheds prefix-cache leaves, holds "
                            "speculation, then load-sheds admissions "
                            "by class (batch first) with a jittered "
                            "Retry-After; pair with --slo for the "
                            "burn signal and class-aware shedding")
    p_srv.add_argument("--step-deadline", type=float, default=None,
                       help="watchdog: flag engine steps exceeding this "
                            "absolute wall deadline in seconds "
                            "(serve/watchdog_stalls + anomaly dump)")
    p_srv.add_argument("--journal", default=None, metavar="PATH",
                       help="request write-ahead journal "
                            "(ServeConfig.journal_path): fsync'd JSONL "
                            "of submit/commit/finish events; an "
                            "existing file is REPLAYED on boot "
                            "(engine.recover) so a crashed server's "
                            "in-flight streams resume token-exactly, "
                            "and SSE clients reconnect with "
                            "Last-Event-ID")
    p_srv.add_argument("--journal-strict", action="store_true",
                       help="[--journal] journal I/O failures kill "
                            "serving instead of degrading to "
                            "journal-off with a warning (for "
                            "deployments that REQUIRE durability)")
    p_srv.add_argument("--replicas", type=int, default=1,
                       help="serve a FLEET of N identical engine "
                            "replicas behind one port (serve/fleet.py "
                            "FleetRouter): prefix-affinity + SLO-aware "
                            "routing, merged /metrics, fleet /statusz; "
                            "with --journal each replica journals to "
                            "PATH.rN and FleetRouter.drain can migrate "
                            "live streams between replicas")
    p_srv.add_argument("--trace", action="store_true",
                       help="flight recorder on (ServeConfig.trace): "
                            "HTTP accept/parse/handoff/drain spans join "
                            "engine lifecycle spans per request; "
                            "GET /v1/requests/<id> works either way")
    p_srv.add_argument("--trace-out", default=None, metavar="PATH",
                       help="[--trace] on shutdown write the Chrome "
                            "trace-event JSON here — with --replicas > 1 "
                            "the STITCHED fleet export (router + every "
                            "replica as its own Perfetto process, flows "
                            "following requests across reroutes and "
                            "migrations), the single-engine export "
                            "otherwise; feed `cli trace-summary --fleet`")
    p_srv.add_argument("--timeseries-interval", type=float, default=1.0,
                       help="rolling time-series snapshot cadence in "
                            "seconds (ServeConfig.timeseries_interval_s; "
                            "0 disables the store and /timeseriesz)")
    p_srv.add_argument("--timeseries-capacity", type=int, default=120,
                       help="time-series ring capacity in windows — the "
                            "retrospective spans capacity x interval "
                            "seconds at O(capacity x series) memory")
    p_srv.add_argument("--seed", type=int, default=0)

    p_rep = sub.add_parser(
        "replay",
        help="replay a request journal against a candidate config and "
             "gate on stream divergence (serve/replay.py): exit 0 = "
             "match, exit 2 = divergence beyond the thresholds, exit "
             "1 = operational failure",
    )
    _add_common(p_rep)
    p_rep.add_argument("--journal", required=True, metavar="PATH",
                       help="journal to replay — the live file a "
                            "`cli serve --journal` wrote (a concurrent "
                            "rotation mid-read is tolerated) or a "
                            "copied snapshot")
    p_rep.add_argument("--config-overrides", nargs="*", default=None,
                       metavar="KEY=VALUE",
                       help="ServeConfig fields for the CANDIDATE "
                            "(e.g. kv_quant=int8 paged=true "
                            "decode_block=16); values parse as JSON "
                            "then fall back to raw strings; when "
                            "given, the un-overridden config is "
                            "re-served too for paired latency/"
                            "throughput deltas")
    p_rep.add_argument("--out", default=None,
                       help="also write the report JSON here")
    p_rep.add_argument("--byte-exact-min", type=float, default=1.0,
                       help="exit 2 if byte_exact_rate over the "
                            "greedy+seeded streams falls below this "
                            "(default 1.0 — identical configs must "
                            "match exactly)")
    p_rep.add_argument("--agreement-min", type=float, default=0.0,
                       help="exit 2 if the teacher-forced greedy "
                            "agreement_rate falls below this — the "
                            "graded gate for deliberately-lossy "
                            "candidates like kv_quant=int8 (0 "
                            "disables; pair with --byte-exact-min 0)")
    p_rep.add_argument("--max-requests", type=int, default=None,
                       help="replay only the first N journaled "
                            "requests")
    p_rep.add_argument("--cut-stride", type=int, default=8,
                       help="token stride of the teacher-forced "
                            "agreement cuts (0 disables the "
                            "agreement pass)")
    p_rep.add_argument("--max-cuts", type=int, default=512,
                       help="total agreement-cut budget (overflow is "
                            "disclosed as cuts_dropped, never "
                            "silently truncated)")
    p_rep.add_argument("--pace", action="store_true",
                       help="re-serve at the recorded arrival offsets "
                            "instead of submitting upfront (realistic "
                            "latency deltas, slower wall clock)")
    p_rep.add_argument("--slots", type=int, default=8)
    p_rep.add_argument("--max-len", type=int, default=None,
                       help="engine sequence capacity (default: "
                            "min(512, model max positions)) — match "
                            "the recording server's")
    p_rep.add_argument("--decode-block", type=int, default=8)
    p_rep.add_argument("--bucket", type=int, default=32)
    p_rep.add_argument("--sample-cap", type=int, default=64)
    p_rep.add_argument("--max-waiting", type=int, default=256)
    p_rep.add_argument("--paged", action="store_true")
    p_rep.add_argument("--kv-quant", default=None, choices=["int8"])
    p_rep.add_argument("--kv-quant-block", type=int, default=16)
    p_rep.add_argument("--kv-exact-lanes", type=int, default=0)
    p_rep.add_argument("--speculative", default=None,
                       choices=["ngram", "mtp"])
    p_rep.add_argument("--spec-k", type=int, default=4)
    p_rep.add_argument("--spec-rounds", type=int, default=None)
    p_rep.add_argument("--seed", type=int, default=0,
                       help="model-init seed — must match the "
                            "recording server's for byte-exactness "
                            "without a checkpoint")

    p_tsum = sub.add_parser("trace-summary")
    p_tsum.add_argument("trace",
                        help="Chrome trace-event JSON exported by the "
                             "flight recorder (serve-bench --trace-out, "
                             "engine.trace.export_chrome, "
                             "TrainConfig.trace_path)")
    p_tsum.add_argument("--top", type=int, default=5,
                        help="how many slowest requests to print")
    p_tsum.add_argument("--fleet", action="store_true",
                        help="require the stitched fleet section: exit 2 "
                             "with a clear message when the trace holds "
                             "no fleet events (a single-engine export) "
                             "— a manifest that declares replicas the "
                             "file is missing (truncated/partial "
                             "export) is exit 2 with or without this "
                             "flag")

    p_eval = sub.add_parser("eval")
    _add_common(p_eval)

    p_export = sub.add_parser("export")
    _add_common(p_export)
    p_export.add_argument("--out", required=True)

    args = parser.parse_args(argv)
    # kernel-bench skips _apply_platform: it takes no _add_common flags
    # (no data/checkpoint plumbing) — set JAX_PLATFORMS in the env
    if args.cmd not in ("list", "trace-summary", "kernel-bench"):
        # before any command code touches jax (see _apply_platform docstring)
        _apply_platform(args)
    return {
        "list": cmd_list,
        "train": cmd_train,
        "sample": cmd_sample,
        "serve": cmd_serve,
        "replay": cmd_replay,
        "serve-bench": cmd_serve_bench,
        "kernel-bench": cmd_kernel_bench,
        "trace-summary": cmd_trace_summary,
        "eval": cmd_eval,
        "export": cmd_export,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
