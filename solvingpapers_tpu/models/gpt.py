"""GPT decoder-only char LM.

Capability target: gpt/gpt-jax.ipynb cells 8-12 — learned positional
embedding, pre-LN decoder blocks with fused-qkv causal self-attention and
4x GELU MLP, final LayerNorm, untied lm_head. Reference defaults:
block 256, dim 256, 1 head, 8 layers (cell 8).

Differences from the reference (TPU-first): attention/norm math comes from
the shared ops library (f32 reductions under bf16 compute), and the model
supports a preallocated KV cache + absolute positions so decode is a
compiled single-token step instead of the notebook's unjitted
full-prefix python loop (cell 19).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu.infer.cache import KVCache
from solvingpapers_tpu.models.layers import (
    Attention,
    LayerNorm,
    MLP,
    default_positions,
    maybe_remat,
)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 65
    block_size: int = 256
    dim: int = 256
    n_layers: int = 8
    n_heads: int = 1
    mlp_mult: int = 4
    dropout: float = 0.1
    dtype: str = "float32"
    use_flash: bool = False
    remat: bool = False  # jax.checkpoint each block: recompute activations in backward
    # context parallelism (same contract as LlamaConfig: apply inside a
    # shard_map whose 'context' axis shards the sequence)
    context_parallel: bool = False
    context_impl: str = "ring"  # ring | ulysses

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


class GPTBlock(nn.Module):
    # __call__ args are positional so nn.remat can mark `deterministic`
    # static (static_argnums counts self=0, x=1, positions=2, cache=3)
    cfg: GPTConfig

    @nn.compact
    def __call__(self, x, positions=None, cache=None, deterministic=True,
                 attend_len=None):
        cfg = self.cfg
        h, cache = Attention(
            dim=cfg.dim,
            n_heads=cfg.n_heads,
            causal=True,
            dropout=cfg.dropout,
            use_bias=True,
            dtype=cfg.compute_dtype,
            use_flash=cfg.use_flash,
            context_parallel=cfg.context_parallel,
            context_impl=cfg.context_impl,
            name="attn",
        )(LayerNorm(name="ln1")(x), positions=positions, cache=cache, deterministic=deterministic,
           attend_len=attend_len)
        x = x + h
        x = x + MLP(
            dim=cfg.dim,
            hidden_dim=cfg.mlp_mult * cfg.dim,
            dropout=cfg.dropout,
            dtype=cfg.compute_dtype,
            name="mlp",
        )(LayerNorm(name="ln2")(x), deterministic=deterministic)
        return x, cache


class GPT(nn.Module):
    cfg: GPTConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches: list[KVCache] | None = None,
        deterministic: bool = True,
        attend_len: int | None = None,
    ) -> tuple[jax.Array, list[KVCache] | None]:
        cfg = self.cfg
        b, s = tokens.shape
        if positions is None:
            # max_positions: the learned table length — out-of-range global
            # positions fail at trace time instead of silently clamping
            positions = default_positions(
                b, s, cfg.context_parallel, max_positions=cfg.block_size
            )
        tok_emb = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.compute_dtype, name="tok_emb")(
            tokens
        )
        pos_table = self.param(
            "pos_emb", nn.initializers.normal(0.02), (cfg.block_size, cfg.dim)
        )
        x = tok_emb + jnp.take(pos_table, positions, axis=0).astype(cfg.compute_dtype)
        if cfg.dropout > 0.0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        new_caches = [] if caches is not None else None
        block_cls = maybe_remat(GPTBlock, cfg.remat, caches)
        for i in range(cfg.n_layers):
            x, c = block_cls(cfg, name=f"block_{i}")(
                x,
                positions,
                None if caches is None else caches[i],
                deterministic,
                attend_len,
            )
            if new_caches is not None:
                new_caches.append(c)
        x = LayerNorm(name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.compute_dtype, name="lm_head")(x)
        return logits, new_caches

    @property
    def max_positions(self) -> int:
        return self.cfg.block_size

    def init_caches(self, batch: int, max_len: int, dtype=None) -> list[KVCache]:
        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        dtype = dtype or cfg.compute_dtype
        return [
            KVCache.init(batch, max_len, cfg.n_heads, head_dim, dtype)
            for _ in range(cfg.n_layers)
        ]
