"""Knowledge-distillation teacher/student MLP pair.

Capability target: knowledge distillation/kd.py — Teacher 784-1024-1024-10
(kd.py:17-30), Student 784-256-10 (kd.py:33-45), distillation loss T=7,
alpha=0.3 (ops.distillation_loss, kd.py:48-68). The reference pipeline
(pretrain teacher 3 epochs, freeze, distill student 10 epochs, kd.py:85-142)
is train.objectives.make_kd_loss_fn + two Trainer runs; run screenshot
records 97.50% student accuracy at epoch 10.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops


@dataclasses.dataclass(frozen=True)
class MLPClassifierConfig:
    input_dim: int = 784
    hidden_dims: tuple[int, ...] = (1024, 1024)  # teacher; student: (256,)
    n_classes: int = 10
    dropout: float = 0.0
    dtype: str = "float32"

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


def teacher_config(**kw) -> MLPClassifierConfig:
    return MLPClassifierConfig(hidden_dims=(1024, 1024), **kw)


def student_config(**kw) -> MLPClassifierConfig:
    return MLPClassifierConfig(hidden_dims=(256,), **kw)


class MLPClassifier(nn.Module):
    """ReLU MLP over flattened images; serves as both Teacher and Student."""

    cfg: MLPClassifierConfig

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        cfg = self.cfg
        x = x.reshape(x.shape[0], -1).astype(cfg.compute_dtype)
        for i, h in enumerate(cfg.hidden_dims):
            x = ops.relu(nn.Dense(h, dtype=cfg.compute_dtype, name=f"fc{i}")(x))
            if cfg.dropout > 0.0:
                x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        return nn.Dense(cfg.n_classes, dtype=cfg.compute_dtype, name="head")(x)
