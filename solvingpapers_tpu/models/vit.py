"""Vision Transformer classifier.

Capability target: vision transformer/ViT.ipynb — Conv patch embedding with
kernel = stride = patch (cell 9), pre-LN encoder blocks with bidirectional
MHA + GELU MLP (cell 10), CLS token + learned position embedding, head
reading the CLS position (cells 11-12). Reference defaults: MNIST 28x28,
patch 7 -> 16 patches, dim 64, 4 heads, 4 blocks, MLP 2x, no dropout
(cell 5); 97.25% test accuracy after 5 epochs (cell 15).

TPU-first: attention runs through the shared Attention module
(causal=False), so the same flash kernel serves the encoder; images are
NHWC (TPU-native conv layout).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu.models.layers import Attention, LayerNorm, MLP


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 28
    patch_size: int = 7
    in_channels: int = 1
    n_classes: int = 10
    dim: int = 64
    n_layers: int = 4
    n_heads: int = 4
    mlp_mult: int = 2
    dropout: float = 0.0
    dtype: str = "float32"
    use_flash: bool = False

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


class EncoderBlock(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x, *, deterministic=True):
        cfg = self.cfg
        h, _ = Attention(
            dim=cfg.dim,
            n_heads=cfg.n_heads,
            causal=False,
            dropout=cfg.dropout,
            use_bias=True,
            dtype=cfg.compute_dtype,
            use_flash=cfg.use_flash,
            name="attn",
        )(LayerNorm(name="ln1")(x), deterministic=deterministic)
        x = x + h
        x = x + MLP(
            dim=cfg.dim,
            hidden_dim=cfg.mlp_mult * cfg.dim,
            dropout=cfg.dropout,
            dtype=cfg.compute_dtype,
            name="mlp",
        )(LayerNorm(name="ln2")(x), deterministic=deterministic)
        return x


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images: jax.Array, *, deterministic: bool = True) -> jax.Array:
        """images: (B, H, W, C) NHWC -> logits (B, n_classes)."""
        cfg = self.cfg
        b = images.shape[0]
        x = nn.Conv(
            cfg.dim,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            dtype=cfg.compute_dtype,
            name="patch_embed",
        )(images.astype(cfg.compute_dtype))
        x = x.reshape(b, -1, cfg.dim)  # (B, n_patches, dim)

        cls = self.param("cls_token", nn.initializers.normal(0.02), (1, 1, cfg.dim))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, cfg.dim)).astype(x.dtype), x], axis=1
        )
        pos = self.param(
            "pos_emb", nn.initializers.normal(0.02), (1, cfg.n_patches + 1, cfg.dim)
        )
        x = x + pos.astype(x.dtype)
        if cfg.dropout > 0.0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        for i in range(cfg.n_layers):
            x = EncoderBlock(cfg, name=f"block_{i}")(x, deterministic=deterministic)

        x = LayerNorm(name="ln_f")(x[:, 0])  # CLS position
        return nn.Dense(cfg.n_classes, dtype=cfg.compute_dtype, name="head")(x)
