"""Pipeline-parallel LLaMA3: decoder blocks staged over the 'pipe' axis
with the shared staged-LM machinery (models/staged.py) and the GPipe
ppermute schedule (sharding/pipeline.py).

No counterpart in the reference (SURVEY.md §2.3 PP row). Blocks are the
exact LlamaBlock modules of models/llama3.py — GQA + RoPE + SwiGLU — so
staged == dense is a restack away (`to_dense`), which is also the decode
path (PP has no cache support). Stateless blocks make this the simple
instantiation of the pattern; the flagship's stateful-MoE version is
models/deepseekv3_pipe.py. Dropout trains under the schedule via
per-(stage, microbatch, layer) keys (sharding/pipeline.py rng kwarg —
the same regenerable-seed recipe as GPTPipe).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu.models.llama3 import LlamaBlock, LlamaConfig
from solvingpapers_tpu.models.layers import RMSNorm, default_positions
from solvingpapers_tpu.models.staged import init_stage_stack, restack_to_dense
from solvingpapers_tpu.sharding.pipeline import pipeline_local_apply


@dataclasses.dataclass(frozen=True)
class LlamaPipeConfig:
    vocab_size: int = 50257
    max_seq_len: int = 128
    dim: int = 256
    n_layers: int = 4
    n_heads: int = 4
    n_kv_heads: int = 2
    hidden_dim: int | None = None
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    # block-level dropout (the reference's transformer_block Bernoulli
    # masks, LLaMA-jax.ipynb cell 26) via per-(stage, microbatch, layer)
    # schedule keys
    dropout: float = 0.0
    dtype: str = "float32"
    use_flash: bool = False
    remat: bool = False  # jax.checkpoint each block inside the stage_fn
    n_stages: int = 2
    n_microbatches: int = 2
    # interleaved (virtual-stage) schedule: each pipe device holds
    # `virtual_stages` thin stages (n_stages = pipe_size * virtual_stages),
    # shrinking the bubble to (P-1)/(m*v + P - 1). 1 = GPipe. Does not
    # compose with context_parallel (the virtual-slice branch cannot
    # contain the CP ring's collectives).
    virtual_stages: int = 1
    pipeline_parallel: bool = False
    context_parallel: bool = False
    context_impl: str = "ring"

    def __post_init__(self):
        if self.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers {self.n_layers} not divisible by n_stages "
                f"{self.n_stages}"
            )
        from solvingpapers_tpu.models.staged import validate_interleaved_config

        validate_interleaved_config(
            self.n_stages, self.virtual_stages, self.n_microbatches,
            self.context_parallel,
        )

    @property
    def pipe_size(self) -> int:
        """Devices on the pipe axis (= n_stages / virtual_stages)."""
        return self.n_stages // self.virtual_stages

    def storage_index(self, global_stage: int) -> int:
        from solvingpapers_tpu.models.staged import interleaved_storage_index

        return interleaved_storage_index(
            global_stage, self.virtual_stages, self.pipe_size
        )

    @property
    def layers_per_stage(self) -> int:
        return self.n_layers // self.n_stages

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    def block_cfg(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, max_seq_len=self.max_seq_len,
            dim=self.dim, n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, hidden_dim=self.hidden_dim,
            rope_theta=self.rope_theta, norm_eps=self.norm_eps,
            dropout=self.dropout, dtype=self.dtype, use_flash=self.use_flash,
            context_parallel=self.context_parallel,
            context_impl=self.context_impl,
        )


class LlamaPipe:
    """init/apply surface compatible with Trainer + lm_loss_fn."""

    def __init__(self, cfg: LlamaPipeConfig):
        self.cfg = cfg
        self._block = LlamaBlock(cfg.block_cfg())

    def init(self, rngs: dict, tokens: jax.Array) -> dict:
        cfg = self.cfg
        rng = rngs["params"] if isinstance(rngs, dict) else rngs
        k_emb, k_blocks, k_ln, k_head = jax.random.split(rng, 4)
        dummy = jnp.zeros(
            (1, min(tokens.shape[1], cfg.max_seq_len), cfg.dim),
            cfg.compute_dtype,
        )
        if cfg.context_parallel:
            if hasattr(jax.lax, "pcast"):  # no-op without vma typing
                dummy = jax.lax.pcast(dummy, ("context",), to="varying")
        from solvingpapers_tpu.models.staged import interleaved_storage_order

        stacked = init_stage_stack(
            self._block, k_blocks, dummy, cfg.n_stages, cfg.layers_per_stage,
            order=interleaved_storage_order(cfg.n_stages, cfg.virtual_stages),
        )
        params = {
            "tok_emb": {
                "embedding": nn.initializers.variance_scaling(
                    1.0, "fan_in", "normal", out_axis=0
                )(k_emb, (cfg.vocab_size, cfg.dim), jnp.float32)
            },
            "stages": stacked["params"],
            "norm_f": RMSNorm(eps=cfg.norm_eps).init(k_ln, dummy)["params"],
            "lm_head": {
                "kernel": nn.initializers.lecun_normal()(
                    k_head, (cfg.dim, cfg.vocab_size), jnp.float32
                )
            },
        }
        return {"params": params}

    def _stage_fn(self, positions):
        def one(p, x, key):
            if key is None:
                y, _ = self._block.apply({"params": p}, x, positions, None,
                                         True, None)
            else:
                y, _ = self._block.apply(
                    {"params": p}, x, positions, None, False, None,
                    rngs={"dropout": key},
                )
            return y

        if self.cfg.remat:
            # same key on the remat replay -> identical masks in backward
            one = jax.checkpoint(one)

        def stage_fn(sp, x, rng=None, virtual_idx=0):
            for j in range(self.cfg.layers_per_stage):
                x = one(
                    sp[f"block_{j}"], x,
                    None if rng is None else jax.random.fold_in(rng, j),
                )
            return x

        return stage_fn

    def stage_probe_fn(self, mb: int, seq: int):
        """Standalone per-stage callable for the mesh observatory's
        bubble probe (metrics/mesh_obs.probe_stage_costs): the stage
        closure built over plain microbatch positions, rng/virtual
        kwargs stripped."""
        positions = default_positions(
            mb, seq, False, max_positions=self.cfg.max_seq_len
        )
        fn = self._stage_fn(positions)
        return lambda p, x: fn(p, x)

    def apply(
        self,
        variables: dict,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches=None,
        deterministic: bool = True,
        rngs=None,
    ):
        if caches is not None:
            raise NotImplementedError(
                "decode caches are unsupported under pipeline parallelism; "
                "to_dense() the params and decode with Llama"
            )
        cfg = self.cfg
        p = variables["params"]
        b, s = tokens.shape
        if positions is None:
            positions = default_positions(
                b, s, cfg.context_parallel, max_positions=cfg.max_seq_len
            )
        x = jnp.take(p["tok_emb"]["embedding"], tokens, axis=0)
        x = x.astype(cfg.compute_dtype)

        train_drop = (not deterministic) and cfg.dropout > 0.0
        sched_rng = None
        if train_drop:
            if not rngs or "dropout" not in rngs:
                raise ValueError(
                    "dropout > 0 training requires rngs={'dropout': key}"
                )
            sched_rng = rngs["dropout"]

        if cfg.pipeline_parallel and cfg.virtual_stages > 1:
            from solvingpapers_tpu.sharding.pipeline import (
                pipeline_local_apply_interleaved,
            )

            mb = x.shape[0] // cfg.n_microbatches
            stage_fn = self._stage_fn(positions[:mb])
            x = pipeline_local_apply_interleaved(
                p["stages"], x, stage_fn,
                n_microbatches=cfg.n_microbatches,
                n_virtual=cfg.virtual_stages,
                rng=sched_rng,
            )
        elif cfg.pipeline_parallel:
            mb = x.shape[0] // cfg.n_microbatches
            stage_fn = self._stage_fn(positions[:mb])
            x = pipeline_local_apply(
                p["stages"], x, stage_fn,
                n_microbatches=cfg.n_microbatches,
                rng=sched_rng,
            )
        else:
            stage_fn = self._stage_fn(positions)
            for g in range(cfg.n_stages):  # GLOBAL stage order
                x = stage_fn(
                    jax.tree.map(
                        lambda a: a[cfg.storage_index(g)], p["stages"]
                    ),
                    x,
                    None if sched_rng is None
                    else jax.random.fold_in(sched_rng, g),
                )

        x = RMSNorm(eps=cfg.norm_eps).apply({"params": p["norm_f"]}, x)
        logits = (
            x.astype(cfg.compute_dtype)
            @ p["lm_head"]["kernel"].astype(cfg.compute_dtype)
        )
        return logits, None

    @property
    def max_positions(self) -> int:
        return self.cfg.max_seq_len

    def f1b_value_and_grad(self, params, batch, rng=None,
                           model_state=None):
        """Loss AND grads in one 1F1B pass — same contract as
        GPTPipe.f1b_value_and_grad (call inside the Trainer's 'pipe'
        shard_map via TrainConfig.pp_schedule='1f1b'; with `rng`,
        block dropout uses the schedule's per-(stage, microbatch)
        regenerable keys). RoPE positions are baked into the stage_fn
        closure, the RMSNorm+lm_head ride as the schedule's loss head."""
        from solvingpapers_tpu import ops
        from solvingpapers_tpu.models.staged import f1b_lm_value_and_grad

        cfg = self.cfg
        tokens, targets = batch["x"], batch["y"]
        b, s = tokens.shape
        m = cfg.n_microbatches
        positions = default_positions(b, s, False,
                                      max_positions=cfg.max_seq_len)
        head = {"norm_f": params["norm_f"], "lm_head": params["lm_head"]}
        stage_fn = self._stage_fn(positions[: b // m])

        def embed_fn(emb):
            x = jnp.take(emb["embedding"], tokens, axis=0)
            return x.astype(cfg.compute_dtype).reshape(
                m, b // m, s, cfg.dim
            )

        def head_loss(hp, h, t):
            z = RMSNorm(eps=cfg.norm_eps).apply({"params": hp["norm_f"]}, h)
            logits = (
                z.astype(cfg.compute_dtype)
                @ hp["lm_head"]["kernel"].astype(cfg.compute_dtype)
            )
            return ops.cross_entropy(logits, t)

        loss, dstage, dhead, dembed = f1b_lm_value_and_grad(
            params["stages"], params["tok_emb"], head, targets, m,
            embed_fn, stage_fn, head_loss,
            rng=rng if cfg.dropout > 0.0 else None,
        )
        grads = {
            "tok_emb": dembed, "stages": dstage,
            "norm_f": dhead["norm_f"], "lm_head": dhead["lm_head"],
        }
        return loss, grads, model_state

    def to_dense(self, params: dict):
        """Restack into the dense Llama layout (block_{i} keys) — the
        decode path for pipeline-trained weights."""
        from solvingpapers_tpu.models.llama3 import Llama

        cfg = self.cfg
        dense = {k: v for k, v in params.items() if k != "stages"}
        dense.update(restack_to_dense(
            params["stages"], cfg.n_stages, cfg.layers_per_stage,
            lambda i: f"block_{i}", storage_index=cfg.storage_index,
        ))
        dense_cfg = dataclasses.replace(cfg.block_cfg(), context_parallel=False)
        return Llama(dense_cfg), dense
