"""Pipeline-parallel DeepSeekV3: MLA + MoE decoder layers grouped into
stages (stacked variables, leading stage dim sharded over 'pipe'), applied
with the GPipe ppermute schedule inside shard_map.

No counterpart in the reference (its flagship trains under single-process
DataParallel, deepseekv3.ipynb cell 37); SURVEY.md §2.3 lists PP as a
TPU-native capability to add. The blocks are the exact DSV3DecoderLayer
modules of models/deepseekv3.py, so staged == dense is a restack away
(`to_dense`), and decode for PP-trained weights goes through the dense
family after export.

Routing state under PP (the hard part): the aux-free routing bias
(deepseekv3.ipynb cell 23's no-grad buffer) is carried stacked over stages
but REPLICATED across the mesh, and must stay shard-invariant. Inside the
GPipe stage_fn the layers apply with 'moe_state' immutable (a pure
(params, x) function re-runs across schedule ticks), sowing their raw
per-expert loads instead; the schedule sums those over each device's valid
ticks (bubble ticks masked — sharding/pipeline.py with_aux), data-axis
psums make the loads global, and each device's update for ITS stage's
layers is scattered into a zero stack and psum'd over 'pipe' — every
device applies the identical full-stack update, so out_specs P() holds by
construction (verified under the vma checker for non-flash configs).

Dropout trains under the schedule (the reference flagship's recipe is
dropout 0.1, deepseekv3.ipynb cell 4): the GPipe tick derives a
per-(stage, microbatch) key (sharding/pipeline.py rng kwarg), the stage_fn
folds in the layer index, and the post-stack dropout runs replicated
outside the schedule — every mask is a pure function of the base key and
regenerates identically across remat/backward (same recipe as GPTPipe).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops
from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3Config, DSV3DecoderLayer
from solvingpapers_tpu.models.layers import RMSNorm, default_positions
from solvingpapers_tpu.models.staged import (
    init_stage_stack,
    restack_to_dense,
    stage_slice,
)
from solvingpapers_tpu.sharding.pipeline import pipeline_local_apply

_STAT_KEYS = ("load_entropy", "load_max_fraction", "drop_fraction",
              "bias_norm")


@dataclasses.dataclass(frozen=True)
class DSV3PipeConfig:
    vocab_size: int = 50257
    block_size: int = 256
    dim: int = 512
    n_layers: int = 6
    n_heads: int = 8
    latent_dim: int = 64
    rope_dim: int = 0
    rope_theta: float = 10000.0
    pe_scale: float = 1.0
    n_experts: int = 8
    top_experts: int = 2
    use_shared_expert: bool = True
    use_aux_free: bool = True
    aux_free_bias_update_rate: float = 0.001
    moe_impl: str = "dispatch"  # dispatch | dense
    capacity_factor: float = 2.0
    # the reference recipe's dropout 0.1 (cell 4): residual/out-proj and
    # attention-prob dropout inside the staged layers via per-(stage,
    # microbatch, layer) keys, plus the post-stack dropout (cell 31)
    # applied replicated outside the schedule
    dropout: float = 0.0
    attn_dropout: float = 0.0
    dtype: str = "float32"
    use_flash: bool = False
    remat: bool = False  # jax.checkpoint each block inside the stage_fn
    n_stages: int = 2
    n_microbatches: int = 2
    # interleaved (virtual-stage) schedule: each pipe device holds
    # `virtual_stages` thin stages (n_stages = pipe_size * virtual_stages);
    # the MoE routing state rides the schedule's per-virtual-slice aux
    # stack (sharding/pipeline.py with_aux) and is scattered back into the
    # storage rows [d*v, d*v + v). 1 = GPipe. Does not compose with
    # context_parallel (the virtual-slice branch cannot contain the CP
    # ring's collectives).
    virtual_stages: int = 1
    # True: GPipe schedule inside shard_map over 'pipe'; False: sequential
    # scan over stages (the dense oracle the schedule is tested against)
    pipeline_parallel: bool = False
    # compose with context parallelism (sequence over 'context'; each
    # stage's MLA rings within its pipe coordinate's context group)
    context_parallel: bool = False
    # MTP (deepseekv3.ipynb cells 33/46) composes with PP: the schedule's
    # output is psum-broadcast to every pipe device, so the MTP branch
    # (merge + extra decoder layer + proj per head) runs REPLICATED after
    # the staged stack, exactly like the final norm/head — its params and
    # routing bias are plain (unstaged) entries
    mtp_heads: int = 0
    mtp_loss_weight: float = 0.3

    def __post_init__(self):
        if self.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers {self.n_layers} not divisible by n_stages "
                f"{self.n_stages}"
            )
        from solvingpapers_tpu.models.staged import validate_interleaved_config

        validate_interleaved_config(
            self.n_stages, self.virtual_stages, self.n_microbatches,
            self.context_parallel,
        )

    @property
    def pipe_size(self) -> int:
        """Devices on the pipe axis (= n_stages / virtual_stages)."""
        return self.n_stages // self.virtual_stages

    def storage_index(self, global_stage: int) -> int:
        from solvingpapers_tpu.models.staged import interleaved_storage_index

        return interleaved_storage_index(
            global_stage, self.virtual_stages, self.pipe_size
        )

    @property
    def layers_per_stage(self) -> int:
        return self.n_layers // self.n_stages

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def stats_axes(self):
        # engine contract for model_state under shard_map without vma
        # checking (use_flash): the state updates are shard-invariant
        # (psum'd loads + pipe-psum'd stack recombination)
        return ("data", "fsdp") + (("context",) if self.context_parallel else ())

    def layer_cfg(self) -> DeepSeekV3Config:
        return DeepSeekV3Config(
            vocab_size=self.vocab_size, block_size=self.block_size,
            dim=self.dim, n_layers=self.n_layers, n_heads=self.n_heads,
            latent_dim=self.latent_dim, rope_dim=self.rope_dim,
            rope_theta=self.rope_theta, pe_scale=self.pe_scale,
            n_experts=self.n_experts, top_experts=self.top_experts,
            use_shared_expert=self.use_shared_expert,
            use_aux_free=self.use_aux_free,
            aux_free_bias_update_rate=self.aux_free_bias_update_rate,
            moe_impl=self.moe_impl, capacity_factor=self.capacity_factor,
            dropout=self.dropout, attn_dropout=self.attn_dropout,
            mtp_heads=self.mtp_heads, mtp_loss_weight=self.mtp_loss_weight,
            dtype=self.dtype,
            use_flash=self.use_flash,
            context_parallel=self.context_parallel,
        )


class DSV3Pipe:
    """init/apply surface compatible with Trainer + dsv3_loss_fn."""

    def __init__(self, cfg: DSV3PipeConfig):
        self.cfg = cfg
        self._block = DSV3DecoderLayer(cfg.layer_cfg())

    # ------------------------------------------------------------------ init

    def init(self, rngs: dict, tokens: jax.Array, return_mtp: bool = False) -> dict:
        cfg = self.cfg
        rng = rngs["params"] if isinstance(rngs, dict) else rngs
        k_emb, k_blocks, k_ln = jax.random.split(rng, 3)
        dummy = jnp.zeros((1, min(tokens.shape[1], cfg.block_size), cfg.dim),
                          cfg.compute_dtype)
        if cfg.context_parallel:
            # init runs inside shard_map (blocks trace the context ring); a
            # constant dummy is axis-invariant and would clash with the
            # ring's varying carries under the vma checker
            if hasattr(jax.lax, "pcast"):  # no-op without vma typing
                dummy = jax.lax.pcast(dummy, ("context",), to="varying")

        from solvingpapers_tpu.models.staged import interleaved_storage_order

        stacked = init_stage_stack(
            self._block, k_blocks, dummy, cfg.n_stages, cfg.layers_per_stage,
            order=interleaved_storage_order(cfg.n_stages, cfg.virtual_stages),
        )
        params = {
            "tok_emb": {
                "embedding": nn.initializers.normal(0.02)(
                    k_emb, (cfg.vocab_size, cfg.dim), jnp.float32
                )
            },
            "stages": stacked["params"],
            "norm_f": RMSNorm().init(k_ln, dummy)["params"],
        }
        moe_state = {"stages": stacked["moe_state"]}
        if cfg.mtp_heads > 0:
            # dense DeepSeekV3's MTP machinery under the dense family's
            # exact param names, so to_dense export is a plain key copy
            from solvingpapers_tpu.models.layers import LayerNorm

            k_mtp = jax.random.fold_in(k_blocks, 10_000)
            lecun = nn.initializers.lecun_normal()
            for h in range(1, cfg.mtp_heads + 1):
                kh = jax.random.fold_in(k_mtp, h)
                k1, k2, k3, k4, k5 = jax.random.split(kh, 5)
                params[f"mtp_norm_h_{h}"] = LayerNorm().init(k1, dummy)["params"]
                params[f"mtp_norm_e_{h}"] = LayerNorm().init(k2, dummy)["params"]
                params[f"mtp_merge_{h}"] = {
                    "kernel": lecun(k3, (2 * cfg.dim, cfg.dim), jnp.float32)
                }
                lv = self._block.init(k4, dummy)
                params[f"mtp_layer_{h}"] = lv["params"]
                moe_state[f"mtp_layer_{h}"] = lv["moe_state"]
                params[f"mtp_proj_{h}"] = {
                    "kernel": lecun(k5, (cfg.dim, cfg.dim), jnp.float32)
                }
        return {"params": params, "moe_state": moe_state}

    # ----------------------------------------------------------------- apply

    def _make_stage_fn(self, bias_stack, positions, stage_index_fn):
        """stage_fn(stage_params, x) -> (y, aux): applies this stage's
        layers with the routing bias READ-ONLY, collecting per-layer raw
        loads + load stats. `stage_index_fn(virtual_idx)` -> the STORAGE
        row of this unit's stage in the stacked variables (axis index
        under GPipe, d*v + virtual_idx under the interleaved schedule,
        python int under the dense oracle)."""
        cfg = self.cfg

        def one(block_params, bias_j, x, key):
            det = key is None
            (y, _), mut = self._block.apply(
                {"params": block_params, "moe_state": bias_j},
                x, positions, None, det, None,
                mutable=["moe_metrics"],
                **({} if det else {"rngs": {"dropout": key}}),
            )
            stats = mut["moe_metrics"]["moe"]["stats"][0]
            return y, {k: stats[k] for k in (*_STAT_KEYS, "ci")}

        if cfg.remat:
            # same key on the remat replay -> identical masks in backward
            one = jax.checkpoint(one)

        def stage_fn(sp, x, rng=None, virtual_idx=0):
            sid = stage_index_fn(virtual_idx)
            aux_layers = []
            for j in range(cfg.layers_per_stage):
                bias_j = stage_slice(bias_stack[f"block_{j}"], sid)
                x, layer_aux = one(
                    sp[f"block_{j}"], bias_j, x,
                    None if rng is None else jax.random.fold_in(rng, j),
                )
                aux_layers.append(layer_aux)
            aux = {
                k: jnp.stack([a[k] for a in aux_layers])
                for k in aux_layers[0]
            }
            return x, aux

        return stage_fn

    def apply(
        self,
        variables: dict,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches=None,
        deterministic: bool = True,
        rngs=None,
        mutable=(),
        return_mtp: bool = False,
    ):
        if caches is not None:
            raise NotImplementedError(
                "decode caches are unsupported under pipeline parallelism; "
                "to_dense() the params and decode with DeepSeekV3"
            )
        cfg = self.cfg
        use_mtp = return_mtp and cfg.mtp_heads > 0
        if return_mtp and cfg.mtp_heads == 0:
            raise ValueError("return_mtp=True but cfg.mtp_heads == 0")
        p = variables["params"]
        ms_all = variables["moe_state"]
        bias_stack = variables["moe_state"]["stages"]
        b, s = tokens.shape
        if positions is None:
            positions = default_positions(
                b, s, cfg.context_parallel, max_positions=cfg.block_size
            )
        pe = ops.sinusoidal_position_encoding(cfg.block_size, cfg.dim)
        # cast-then-add, matching the dense DeepSeekV3 (its nn.Embed emits
        # compute_dtype before the PE add) so staged and restacked-dense
        # forwards agree bit-for-bit in bf16
        x = jnp.take(p["tok_emb"]["embedding"], tokens, axis=0).astype(
            cfg.compute_dtype
        )
        x = x + cfg.pe_scale * jnp.take(pe, positions, axis=0).astype(
            cfg.compute_dtype
        )

        train_drop = (not deterministic) and (
            cfg.dropout > 0.0 or cfg.attn_dropout > 0.0
        )
        sched_rng = k_out = None
        if train_drop:
            if not rngs or "dropout" not in rngs:
                raise ValueError(
                    "dropout > 0 training requires rngs={'dropout': key}"
                )
            k_out, sched_rng = jax.random.split(rngs["dropout"])

        if cfg.pipeline_parallel and cfg.virtual_stages > 1:
            # interleaved schedule: the routing state rides the schedule's
            # per-virtual-slice aux stack; storage row of slice j on
            # device d is d*v + j
            from solvingpapers_tpu.sharding.pipeline import (
                pipeline_local_apply_interleaved,
            )

            mb = x.shape[0] // cfg.n_microbatches
            v = cfg.virtual_stages
            stage_fn = self._make_stage_fn(
                bias_stack, positions[:mb],
                lambda j: jax.lax.axis_index("pipe") * v + j,
            )
            x, aux = pipeline_local_apply_interleaved(
                p["stages"], x, stage_fn,
                n_microbatches=cfg.n_microbatches,
                n_virtual=v, with_aux=True, rng=sched_rng,
            )
            # aux rows sum over each slice's n_microbatches valid ticks
            n_ticks = cfg.n_microbatches
        elif cfg.pipeline_parallel:
            mb = x.shape[0] // cfg.n_microbatches
            mb_positions = positions[:mb]
            stage_fn = self._make_stage_fn(
                bias_stack, mb_positions,
                lambda j: jax.lax.axis_index("pipe"),
            )
            x, aux = pipeline_local_apply(
                p["stages"], x, stage_fn,
                n_microbatches=cfg.n_microbatches, with_aux=True,
                rng=sched_rng,
            )
            # stack aux like the interleaved path's (v=1, ...) rows so
            # _mutate handles one layout
            aux = jax.tree.map(lambda a: a[None], aux)
            # aux sums over this device's n_microbatches valid ticks
            n_ticks = cfg.n_microbatches
        else:
            # dense oracle: same layers, same aux plumbing, no pipe axis;
            # iterate GLOBAL stage order, slicing the storage row
            aux_stages = []
            for g in range(cfg.n_stages):
                row = cfg.storage_index(g)
                stage_fn = self._make_stage_fn(
                    bias_stack, positions, lambda j, row=row: row
                )
                x, aux_s = stage_fn(
                    jax.tree.map(lambda a: a[row], p["stages"]), x,
                    None if sched_rng is None
                    else jax.random.fold_in(sched_rng, g),
                )
                aux_stages.append((row, aux_s))
            n_ticks = 1

        if train_drop and cfg.dropout > 0.0:
            # the post-stack dropout (cell 31) — replicated on every pipe
            # device with the same key, keeping the psum-broadcast output
            # identical across the axis
            keep = 1.0 - cfg.dropout
            mask = jax.random.bernoulli(k_out, keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)
        x = 2.0 * cfg.n_layers**-0.5 * x  # deepseek depth scaling (cell 31)
        x = RMSNorm().apply({"params": p["norm_f"]}, x)
        emb = p["tok_emb"]["embedding"]
        dt = cfg.compute_dtype
        logits = x.astype(dt) @ emb.T.astype(dt)

        mtp_aux: list = []
        mtp_logits = None
        if use_mtp:
            # replicated MTP branch on the psum-broadcast stream (every
            # pipe device computes the identical heads, like norm_f/head) —
            # the shared functional core (models.deepseekv3.mtp_head_apply;
            # the dense family's flax-module branch is the only other
            # copy). Under CP the i+k shift is the cp_shift_left ppermute.
            from solvingpapers_tpu.models.deepseekv3 import mtp_head_apply

            h_prev = x
            outs = []
            for h in range(1, cfg.mtp_heads + 1):
                if cfg.context_parallel:
                    from solvingpapers_tpu.sharding import cp_shift_left

                    shifted = cp_shift_left(tokens, h, fill=0)
                else:
                    shifted = jnp.pad(tokens[:, h:], ((0, 0), (0, h)))
                head_rngs = None
                if train_drop:
                    # replicated across pipe (same key on every device)
                    head_rngs = {"dropout": jax.random.fold_in(
                        rngs["dropout"], 20_000 + h)}
                head_logits, y, _, stats = mtp_head_apply(
                    self._block.cfg, p, ms_all, h_prev, shifted, positions,
                    head=h, rngs=head_rngs, collect_stats=True,
                )
                mtp_aux.append(
                    (f"mtp_layer_{h}",
                     {k: stats[k] for k in (*_STAT_KEYS, "ci")})
                )
                outs.append(head_logits)
                h_prev = y
            mtp_logits = jnp.stack(outs, axis=2)

        out = (logits, mtp_logits) if use_mtp else logits
        mutated = {}
        wants = set(mutable if not isinstance(mutable, str) else [mutable])
        if wants:
            mutated = self._mutate(
                bias_stack,
                aux if cfg.pipeline_parallel else aux_stages,
                n_ticks, wants, deterministic, ms_all, mtp_aux,
            )
            return (out, None), mutated
        return out, None

    # --------------------------------------------------------- state updates

    def _mutate(self, bias_stack, aux, n_ticks, wants, deterministic,
                ms_all=None, mtp_aux=()):
        """Recombine per-device aux into the shard-invariant moe_state
        update + scalar metrics. Under PP, `aux` holds THIS device's
        per-virtual-slice stage sums, stacked (v, ...) (v=1 under GPipe);
        the update is scattered into the device's storage rows
        [sid*v, sid*v + v) of a zero stack and psum'd over 'pipe'. Under
        the dense oracle, `aux` is a [(storage row, stats)] list in global
        stage order. `mtp_aux`: [(state key, stats)] for the replicated
        MTP layers — their biases update in place (no pipe scatter: every
        device computed the identical global stats)."""
        cfg = self.cfg
        pp = cfg.pipeline_parallel
        v = cfg.virtual_stages
        mutated: dict = {}

        if pp:
            sid = jax.lax.axis_index("pipe")
            ci = aux["ci"]  # (v, layers_per_stage, E), summed over valid ticks
            # make loads global across the data axes (inside the block,
            # stats_axes covered data/fsdp/context only under CP)
            if not cfg.context_parallel:
                ci = jax.lax.psum(ci, ("data", "fsdp"))
        else:
            # (n_stages, lps, E), index-aligned with aux's global order
            ci = jnp.stack([a["ci"] for _, a in aux])

        def global_ci(raw):
            # mtp layers run replicated per device over the local batch
            # shard; outside shard_map (dense oracle) there is no axis
            if pp and not cfg.context_parallel:
                return jax.lax.psum(raw, ("data", "fsdp"))
            return raw

        mtp_ci = {name: global_ci(a["ci"]) for name, a in mtp_aux}

        if "moe_state" in wants:
            new_stack = bias_stack
            new_state: dict = {}
            rate = cfg.aux_free_bias_update_rate
            if cfg.use_aux_free and not deterministic:
                def upd(bias_j, delta_block):
                    # bias_j: (n_stages, E) storage stack; delta_block:
                    # (v, E) for this device's storage rows [sid*v, ..+v)
                    full = jnp.zeros_like(bias_j)
                    full = jax.lax.dynamic_update_slice(
                        full, delta_block.astype(bias_j.dtype), (sid * v, 0)
                    )
                    return bias_j + jax.lax.psum(full, "pipe")

                new_stack = dict(bias_stack)
                for j in range(cfg.layers_per_stage):
                    key = f"block_{j}"
                    if pp:
                        # per virtual slice: err (v, E)
                        err = (
                            jnp.mean(ci[:, j], axis=-1, keepdims=True)
                            - ci[:, j]
                        )
                        delta = rate * jnp.sign(err)
                        new_stack[key] = jax.tree.map(
                            lambda b: upd(b, delta), bias_stack[key]
                        )
                    else:
                        deltas = [None] * cfg.n_stages
                        for idx, (row, _) in enumerate(aux):
                            err = jnp.mean(ci[idx, j]) - ci[idx, j]
                            deltas[row] = rate * jnp.sign(err)
                        new_stack[key] = jax.tree.map(
                            lambda b: b + jnp.stack(deltas).astype(b.dtype),
                            bias_stack[key],
                        )
                for name, ci_m in mtp_ci.items():
                    # the canonical update rule (cell 23), from the
                    # already-psum'd load — no pipe scatter needed
                    # (replicated compute)
                    new_state[name] = jax.tree.map(
                        lambda b, c=ci_m: ops.moe.aux_free_bias_update(
                            None, b, rate, ci=c
                        ),
                        ms_all[name],
                    )
            # entries not updated this step (eval, or aux-free off) pass
            # through unchanged so the state tree keeps its structure
            passthrough = {
                k: v for k, v in (ms_all or {}).items()
                if k != "stages" and k not in new_state
            }
            mutated["moe_state"] = {"stages": new_stack, **new_state,
                                    **passthrough}

        if "moe_metrics" in wants:
            n_total = cfg.n_layers + len(mtp_aux)

            def ci_stats(rows):
                # rows: (..., E) global loads -> summed entropy / max over
                # the leading dims
                load = rows / jnp.maximum(
                    jnp.sum(rows, axis=-1, keepdims=True), 1e-9
                )
                ent = -jnp.sum(load * jnp.log(load + 1e-9), axis=-1) \
                    / jnp.log(float(cfg.n_experts))
                return jnp.sum(ent), jnp.sum(jnp.max(load, axis=-1))

            if pp:
                # load_entropy/load_max_fraction are recomputed from the
                # GLOBAL per-layer ci (tick-summed + data-psum'd above) —
                # averaging the per-tick device-local stats understates
                # routing collapse vs the dense family, which computes them
                # on the globally reduced load (advisor r3). drop_fraction
                # averages exactly (equal-size microbatches share the
                # denominator); bias_norm is tick-invariant, so its mean
                # over ticks is the value itself. MTP layers are replicated
                # per device — added OUTSIDE the pipe psum (a psum would
                # count them n_stages times).
                ent_s, max_s = ci_stats(ci)
                ent_m = max_m = drop_m = bias_m = 0.0
                for name, a in mtp_aux:
                    em, mm = ci_stats(mtp_ci[name])
                    ent_m += em
                    max_m += mm
                    drop_m += a["drop_fraction"]
                    bias_m += a["bias_norm"]
                stats = {
                    "load_entropy":
                        (jax.lax.psum(ent_s, "pipe") + ent_m) / n_total,
                    "load_max_fraction":
                        (jax.lax.psum(max_s, "pipe") + max_m) / n_total,
                }
                for k, extra in (("drop_fraction", drop_m),
                                 ("bias_norm", bias_m)):
                    v = jnp.sum(aux[k]) / n_ticks
                    stats[k] = (jax.lax.psum(v, "pipe") + extra) / n_total
            else:
                stats = {
                    k: (jnp.sum(jnp.stack([a[k] for _, a in aux]))
                        + sum(a[k] for _, a in mtp_aux)) / n_total
                    for k in _STAT_KEYS
                }
            mutated["moe_metrics"] = {"pipeline": {"stats": (stats,)}}
        return mutated

    @property
    def max_positions(self) -> int:
        return self.cfg.block_size

    # ------------------------------------------------------------------ 1f1b

    def f1b_value_and_grad(self, params, batch, rng=None, model_state=None):
        """The FLAGSHIP through the 1F1B schedule (TrainConfig.pp_schedule
        = '1f1b'): the MoE routing loads ride the schedule's aux channel
        (summed over each stage's forward units, the backward recompute's
        aux discarded), the aux-free bias update is recombined exactly
        like the GPipe path's `_mutate` (data-psum'd loads -> per-stage
        sign deltas scattered into a zero stack, pipe-psum'd), and the
        tied lm head rides as the loss head so the embedding's gradient
        sums its embed-side and head-side contributions. v1 scope:
        deterministic (the post-stack dropout of cell 31 has no
        per-microbatch key channel in the loss head), no MTP heads, no
        balance loss — the GPipe schedule serves those."""
        from solvingpapers_tpu.models.staged import f1b_lm_value_and_grad

        cfg = self.cfg
        if cfg.mtp_heads > 0:
            raise NotImplementedError(
                "MTP under pp_schedule='1f1b' is not composed (the heads "
                "need the full hidden stream); use pp_schedule='gpipe'"
            )
        if getattr(cfg, "balance_loss_weight", 0.0) > 0.0:
            raise NotImplementedError(
                "balance_loss_weight under pp_schedule='1f1b' is not "
                "composed; use pp_schedule='gpipe'"
            )
        if cfg.dropout > 0.0 or cfg.attn_dropout > 0.0:
            raise NotImplementedError(
                "the flagship's 1F1B path is deterministic-only (the "
                "post-stack dropout needs a per-microbatch key in the "
                "loss head); set dropout=0 or use pp_schedule='gpipe'"
            )
        ms_all = model_state["moe_state"]
        bias_stack = ms_all["stages"]
        tokens, targets = batch["x"], batch["y"]
        b, s = tokens.shape
        m = cfg.n_microbatches
        dt = cfg.compute_dtype
        positions = default_positions(b, s, False,
                                      max_positions=cfg.block_size)
        stage_fn = self._make_stage_fn(
            bias_stack, positions[: b // m],
            lambda j: jax.lax.axis_index("pipe"),
        )
        head = {"norm_f": params["norm_f"], "tok_emb": params["tok_emb"]}
        pe = ops.sinusoidal_position_encoding(cfg.block_size, cfg.dim)

        def embed_fn(ep):
            x = jnp.take(ep["embedding"], tokens, axis=0).astype(dt)
            x = x + cfg.pe_scale * jnp.take(pe, positions, axis=0).astype(dt)
            return x.reshape(m, b // m, s, cfg.dim)

        def head_loss(hp, h, t):
            # depth scaling -> final RMSNorm -> weight-tied head (cell 31)
            x = 2.0 * cfg.n_layers**-0.5 * h
            x = RMSNorm().apply({"params": hp["norm_f"]}, x)
            emb = hp["tok_emb"]["embedding"]
            logits = x.astype(dt) @ emb.T.astype(dt)
            return ops.cross_entropy(logits, t)

        loss, dstage, dhead, dembed, aux = f1b_lm_value_and_grad(
            params["stages"], params["tok_emb"], head, targets, m,
            embed_fn, stage_fn, head_loss, with_aux=True,
        )
        grads = {
            # tied embedding: embed-side + head-side contributions
            "tok_emb": jax.tree.map(
                lambda a, b_: a + b_, dembed, dhead["tok_emb"]
            ),
            "norm_f": dhead["norm_f"],
            "stages": dstage,
        }

        # routing-state update + metrics through the ONE recombination
        # path (_mutate's PP branch; the schedule's aux sums take the
        # GPipe layout with a leading v=1 dim)
        mutated = self._mutate(
            bias_stack, jax.tree.map(lambda a: a[None], aux),
            cfg.n_microbatches, {"moe_state", "moe_metrics"},
            deterministic=False, ms_all=ms_all,
        )
        new_ms = {"moe_state": mutated["moe_state"]}
        stats = mutated["moe_metrics"]["pipeline"]["stats"][0]
        metrics = {f"moe_{k}": v for k, v in stats.items()}
        return loss, grads, new_ms, metrics

    # ---------------------------------------------------------------- export

    def to_dense(self, params: dict, moe_state: dict):
        """Restack stage-stacked variables into the dense DeepSeekV3 layout
        and return (model, params, moe_state) — the decode path for
        PP-trained weights (PP itself has no cache support). The export
        config drops context_parallel (dense decode runs outside shard_map)."""
        from solvingpapers_tpu.models.deepseekv3 import DeepSeekV3

        cfg = self.cfg
        name = lambda i: f"layer_{i}"  # noqa: E731
        dense_params = {
            # mtp_* entries (stored under the dense family's exact names)
            # and tok_emb/norm_f copy straight across
            **{k: v for k, v in params.items() if k != "stages"},
            **restack_to_dense(params["stages"], cfg.n_stages,
                               cfg.layers_per_stage, name,
                               storage_index=cfg.storage_index),
        }
        dense_state = {
            **{k: v for k, v in moe_state.items() if k != "stages"},
            **restack_to_dense(
                moe_state["stages"], cfg.n_stages, cfg.layers_per_stage,
                name, storage_index=cfg.storage_index,
            ),
        }
        dense_cfg = dataclasses.replace(
            cfg.layer_cfg(), context_parallel=False
        )
        return DeepSeekV3(dense_cfg), dense_params, dense_state
