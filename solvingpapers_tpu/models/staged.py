"""Shared staged-model machinery for pipeline-parallel LM families.

The pattern (models/gpt_pipe.py pioneered it; models/deepseekv3_pipe.py and
models/llama3_pipe.py reuse it): decoder blocks grouped into stages whose
variables are STORED stacked with a leading stage dim sharded over the
'pipe' mesh axis, applied with the GPipe ppermute schedule
(sharding/pipeline.py) inside shard_map. The blocks themselves are the
exact same Flax modules the dense models use, so staged == dense is a
restack away (`restack_to_dense`).

No counterpart in the reference (SURVEY.md §2.3 lists PP as a TPU-native
capability to add; its parallelism ceiling is single-process DataParallel,
deepseekv3.ipynb cell 37).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_stage_stack(
    block,
    key: jax.Array,
    dummy: jax.Array,
    n_stages: int,
    layers_per_stage: int,
    block_args: tuple = (),
):
    """Initialize n_stages x layers_per_stage copies of `block` and stack
    them into {collection: {block_j: stacked-vars}} with a leading stage
    dim (shard over 'pipe'). `block_args` are extra positional args for
    block.init after the dummy activation (e.g. positions)."""

    def stage_init(stage_key):
        per_col: dict = {}
        for j in range(layers_per_stage):
            variables = block.init(
                jax.random.fold_in(stage_key, j), dummy, *block_args
            )
            for col, tree in variables.items():
                per_col.setdefault(col, {})[f"block_{j}"] = tree
        return per_col

    stages = [stage_init(jax.random.fold_in(key, s)) for s in range(n_stages)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def stage_slice(tree, stage_index, keepdims: bool = False):
    """Index the leading stage dim of a stacked pytree (traced index OK)."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(
            a, stage_index, 0, keepdims=keepdims
        ),
        tree,
    )


def restack_to_dense(stages, n_stages: int, layers_per_stage: int,
                     layer_name):
    """Stage-stacked {block_j: stacked-vars} -> {layer_name(i): vars} in the
    dense model's layout. Block j of stage s is dense layer
    s * layers_per_stage + j; module names inside each block are shared
    with the dense family, so the forward is bit-identical."""
    dense = {}
    for s in range(n_stages):
        for j in range(layers_per_stage):
            dense[layer_name(s * layers_per_stage + j)] = jax.tree.map(
                lambda a: a[s], stages[f"block_{j}"]
            )
    return dense
