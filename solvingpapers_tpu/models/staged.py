"""Shared staged-model machinery for pipeline-parallel LM families.

The pattern (models/gpt_pipe.py pioneered it; models/deepseekv3_pipe.py and
models/llama3_pipe.py reuse it): decoder blocks grouped into stages whose
variables are STORED stacked with a leading stage dim sharded over the
'pipe' mesh axis, applied with the GPipe ppermute schedule
(sharding/pipeline.py) inside shard_map. The blocks themselves are the
exact same Flax modules the dense models use, so staged == dense is a
restack away (`restack_to_dense`).

No counterpart in the reference (SURVEY.md §2.3 lists PP as a TPU-native
capability to add; its parallelism ceiling is single-process DataParallel,
deepseekv3.ipynb cell 37).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_stage_stack(
    block,
    key: jax.Array,
    dummy: jax.Array,
    n_stages: int,
    layers_per_stage: int,
    block_args: tuple = (),
    order: list | None = None,
):
    """Initialize n_stages x layers_per_stage copies of `block` and stack
    them into {collection: {block_j: stacked-vars}} with a leading stage
    dim (shard over 'pipe'). `block_args` are extra positional args for
    block.init after the dummy activation (e.g. positions). `order`:
    order[row] = global stage stored at `row` (interleaved_storage_order;
    default identity)."""

    def stage_init(stage_key):
        per_col: dict = {}
        for j in range(layers_per_stage):
            variables = block.init(
                jax.random.fold_in(stage_key, j), dummy, *block_args
            )
            for col, tree in variables.items():
                per_col.setdefault(col, {})[f"block_{j}"] = tree
        return per_col

    stages = [stage_init(jax.random.fold_in(key, s)) for s in range(n_stages)]
    if order is not None:
        stages = [stages[g] for g in order]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def f1b_lm_value_and_grad(stage_params, embed_params, head_params, targets,
                          n_microbatches: int, embed_fn, stage_fn,
                          head_loss, rng=None, with_aux=False):
    """Shared 1F1B scaffold for the staged LM families (the per-family
    f1b_value_and_grad methods differ only in their embed and loss-head):
    embed -> pipeline_1f1b_value_and_grad -> backprop the schedule's input
    cotangent through the embedding. `embed_fn(embed_params)` returns the
    (m, mb, s, d) microbatches (closing over the tokens); `head_loss
    (head_params, h, targets_mb)` is one microbatch's mean loss. Returns
    (loss, dstage, dhead, dembed)."""
    from solvingpapers_tpu.sharding.pipeline import (
        pipeline_1f1b_value_and_grad,
    )

    b, s = targets.shape
    if b % n_microbatches:
        raise ValueError(
            f"batch {b} not divisible by {n_microbatches} microbatches"
        )
    micro, embed_vjp = jax.vjp(embed_fn, embed_params)
    targets_m = targets.reshape(n_microbatches, b // n_microbatches, s)
    out = pipeline_1f1b_value_and_grad(
        stage_params, head_params, micro, targets_m, stage_fn, head_loss,
        rng=rng, with_aux=with_aux,
    )
    loss, dstage, dhead, dmicro = out[:4]
    (dembed,) = embed_vjp(dmicro.astype(micro.dtype))
    if with_aux:
        return loss, dstage, dhead, dembed, out[4]
    return loss, dstage, dhead, dembed


def validate_interleaved_config(n_stages: int, virtual_stages: int,
                                n_microbatches: int,
                                context_parallel: bool) -> None:
    """Shared __post_init__ validation for the staged-LM configs'
    interleaved-schedule knobs (one copy for gpt/llama3/dsv3 pipe)."""
    if n_stages % virtual_stages:
        raise ValueError(
            f"n_stages {n_stages} not divisible by virtual_stages "
            f"{virtual_stages}"
        )
    if virtual_stages > 1:
        if context_parallel:
            raise NotImplementedError(
                "interleaved schedule x context_parallel: the virtual-"
                "slice branch cannot contain the CP ring's collectives"
            )
        pipe_size = n_stages // virtual_stages
        if n_microbatches % pipe_size:
            raise ValueError(
                f"interleaved schedule needs n_microbatches "
                f"({n_microbatches}) divisible by the pipe size "
                f"({pipe_size}): microbatches enter in groups of P"
            )


def interleaved_storage_index(global_stage: int, virtual_stages: int,
                              pipe_size: int) -> int:
    """Stack row holding `global_stage` under the interleaved layout:
    device d stores its v virtual slices contiguously (blocked sharding
    over 'pipe'), so global stage g = j*P + d lives at row d*v + j.
    v == 1 is the identity (GPipe)."""
    if virtual_stages == 1:
        return global_stage
    d, j = global_stage % pipe_size, global_stage // pipe_size
    return d * virtual_stages + j


def interleaved_storage_order(n_stages: int, virtual_stages: int) -> list:
    """order[row] = global stage stored at `row` (inverse of
    interleaved_storage_index): row r = d*v + j holds stage j*P + d."""
    p = n_stages // virtual_stages
    return [
        (r % virtual_stages) * p + r // virtual_stages
        for r in range(n_stages)
    ]


def stage_slice(tree, stage_index, keepdims: bool = False):
    """Index the leading stage dim of a stacked pytree (traced index OK)."""
    return jax.tree.map(
        lambda a: jax.lax.dynamic_index_in_dim(
            a, stage_index, 0, keepdims=keepdims
        ),
        tree,
    )


def restack_to_dense(stages, n_stages: int, layers_per_stage: int,
                     layer_name, storage_index=None):
    """Stage-stacked {block_j: stacked-vars} -> {layer_name(i): vars} in the
    dense model's layout. Block j of stage s is dense layer
    s * layers_per_stage + j; module names inside each block are shared
    with the dense family, so the forward is bit-identical.
    `storage_index(global_stage) -> row` maps global stage to its stack
    row (identity by default; the interleaved layout stores device d's
    virtual slices contiguously)."""
    dense = {}
    for s in range(n_stages):
        row = s if storage_index is None else storage_index(s)
        for j in range(layers_per_stage):
            dense[layer_name(s * layers_per_stage + j)] = jax.tree.map(
                lambda a: a[row], stages[f"block_{j}"]
            )
    return dense
