"""Pipeline-parallel GPT: decoder blocks grouped into stages whose params
are STORED stacked with a leading stage dim sharded over the 'pipe' mesh
axis, and applied with the GPipe ppermute microbatch schedule
(sharding/pipeline.py) inside shard_map.

No counterpart in the reference (SURVEY.md §2.3 lists PP as a TPU-native
capability to add; the reference's ceiling is single-process DataParallel,
deepseekv3.ipynb cell 37). The embedding, final norm and head are small and
run replicated on every pipe device; only the decoder stack — where the
params and FLOPs are — is staged. With pipeline_parallel=False the same
stacked params are applied by a sequential scan over stages, which is the
dense oracle the PP schedule is tested against.

Functional-style module (init/apply duck-typing the Flax surface the
Trainer uses): stacked per-stage params cannot be expressed as ordinary
Flax submodules, so the stage stack is built by initializing each
GPTBlock per layer and stacking — the blocks themselves are the shared
models/layers.py modules.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu.models.gpt import GPTBlock, GPTConfig
from solvingpapers_tpu.models.layers import LayerNorm, default_positions
from solvingpapers_tpu.sharding.pipeline import pipeline_local_apply


@dataclasses.dataclass(frozen=True)
class GPTPipeConfig:
    vocab_size: int = 65
    block_size: int = 256
    dim: int = 256
    n_layers: int = 8
    n_heads: int = 4
    mlp_mult: int = 4
    # dropout trains under the schedule via the regenerable-seed recipe:
    # the GPipe/interleaved tick derives a per-(stage, microbatch) key
    # (sharding/pipeline.py rng kwarg) and the stage_fn folds in the layer
    # index, so every mask is a pure function of (base key, stage, layer,
    # microbatch) and regenerates identically across remat/backward
    dropout: float = 0.0
    dtype: str = "float32"
    n_stages: int = 4
    n_microbatches: int = 4
    # interleaved (virtual-stage) schedule: each pipe device holds
    # n_stages/pipe_size thin stages... concretely `virtual_stages` slices
    # per device (n_stages = pipe_size * virtual_stages), microbatches
    # enter in groups of pipe_size and loop the ring — bubble shrinks from
    # (P-1)/(m+P-1) to (P-1)/(m*v+P-1) (sharding/pipeline.py). 1 = GPipe.
    # Does not compose with context_parallel (slice selection is a
    # data-dependent branch; the CP ring's collectives can't sit inside it).
    virtual_stages: int = 1
    # jax.checkpoint each block inside the stage_fn: the schedule scan then
    # saves only tick-boundary activations (recompute in backward)
    remat: bool = False
    # True: apply inside shard_map over the 'pipe' axis with the GPipe
    # schedule; False: sequential scan over stages (dense oracle)
    pipeline_parallel: bool = False
    # compose with context parallelism: the sequence dim is additionally
    # sharded over 'context' and each stage's attention runs the ppermute
    # ring within its pipe coordinate's context group (orthogonal axes,
    # uniform schedule on every device)
    context_parallel: bool = False
    context_impl: str = "ring"  # ring | ulysses
    use_flash: bool = False

    def __post_init__(self):
        if self.n_layers % self.n_stages:
            raise ValueError(
                f"n_layers {self.n_layers} not divisible by n_stages "
                f"{self.n_stages}"
            )
        from solvingpapers_tpu.models.staged import validate_interleaved_config

        validate_interleaved_config(
            self.n_stages, self.virtual_stages, self.n_microbatches,
            self.context_parallel,
        )

    @property
    def pipe_size(self) -> int:
        """Devices on the pipe axis (= n_stages / virtual_stages)."""
        return self.n_stages // self.virtual_stages

    def storage_index(self, global_stage: int) -> int:
        """Row of the stacked params holding `global_stage` (the shared
        interleaved layout — models/staged.py)."""
        from solvingpapers_tpu.models.staged import interleaved_storage_index

        return interleaved_storage_index(
            global_stage, self.virtual_stages, self.pipe_size
        )

    @property
    def layers_per_stage(self) -> int:
        return self.n_layers // self.n_stages

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    def block_cfg(self) -> GPTConfig:
        return GPTConfig(
            vocab_size=self.vocab_size, block_size=self.block_size,
            dim=self.dim, n_layers=self.n_layers, n_heads=self.n_heads,
            mlp_mult=self.mlp_mult, dropout=self.dropout, dtype=self.dtype,
            use_flash=self.use_flash,
            context_parallel=self.context_parallel,
            context_impl=self.context_impl,
        )


def _emb_dropout(x, key, rate):
    """The embedding-dropout site shared by apply() and the 1F1B path:
    replicated key (every pipe device must agree on stage 0's input)."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0).astype(x.dtype)


class GPTPipe:
    """init/apply surface compatible with Trainer + lm_loss_fn."""

    def __init__(self, cfg: GPTPipeConfig):
        self.cfg = cfg
        self._block = GPTBlock(cfg.block_cfg())

    # ------------------------------------------------------------------ init

    def init(self, rngs: dict, tokens: jax.Array) -> dict:
        cfg = self.cfg
        rng = rngs["params"] if isinstance(rngs, dict) else rngs
        k_emb, k_pos, k_blocks, k_ln, k_head = jax.random.split(rng, 5)
        dummy = jnp.zeros((1, min(tokens.shape[1], cfg.block_size), cfg.dim),
                          cfg.compute_dtype)
        if cfg.context_parallel:
            # init runs inside shard_map (the blocks trace the context
            # ring); a constant dummy is axis-invariant and would clash
            # with the ring's varying carries under the vma checker
            if hasattr(jax.lax, "pcast"):  # no-op without vma typing
                dummy = jax.lax.pcast(dummy, ("context",), to="varying")

        def stage_init(key):
            blocks = {}
            for j in range(cfg.layers_per_stage):
                blocks[f"block_{j}"] = self._block.init(
                    jax.random.fold_in(key, j), dummy
                )["params"]
            return blocks

        stage_list = [
            stage_init(jax.random.fold_in(k_blocks, s))
            for s in range(cfg.n_stages)
        ]
        # storage row r holds global stage order[r] (identity for GPipe;
        # the shared interleaved permutation for virtual_stages > 1)
        from solvingpapers_tpu.models.staged import interleaved_storage_order

        order = interleaved_storage_order(cfg.n_stages, cfg.virtual_stages)
        stages = jax.tree.map(
            lambda *xs: jnp.stack(xs), *[stage_list[g] for g in order]
        )

        params = {
            "tok_emb": {
                "embedding": nn.initializers.normal(0.02)(
                    k_emb, (cfg.vocab_size, cfg.dim), jnp.float32
                )
            },
            "pos_emb": nn.initializers.normal(0.02)(
                k_pos, (cfg.block_size, cfg.dim), jnp.float32
            ),
            "stages": stages,
            "ln_f": LayerNorm().init(k_ln, dummy)["params"],
            "lm_head": {
                "kernel": nn.initializers.lecun_normal()(
                    k_head, (cfg.dim, cfg.vocab_size), jnp.float32
                )
            },
        }
        return {"params": params}

    # ----------------------------------------------------------------- apply

    def _stage_fn(self, stage_params, x, rng=None, virtual_idx=0):
        # virtual_idx: interleaved-schedule slice index (unused here — the
        # unit_rng already encodes the global stage)
        def one(p, x, key):
            if key is None:
                y, _ = self._block.apply({"params": p}, x, None, None, True)
            else:
                y, _ = self._block.apply(
                    {"params": p}, x, None, None, False, None,
                    rngs={"dropout": key},
                )
            return y

        if self.cfg.remat:
            # same key on the remat replay -> identical masks in backward
            one = jax.checkpoint(one)
        for j in range(self.cfg.layers_per_stage):
            x = one(
                stage_params[f"block_{j}"], x,
                None if rng is None else jax.random.fold_in(rng, j),
            )
        return x

    def stage_probe_fn(self, mb: int, seq: int):
        """Standalone per-stage callable for the mesh observatory's
        bubble probe (metrics/mesh_obs.probe_stage_costs): the
        schedule's rng/virtual kwargs stripped. GPT blocks carry their
        positions in the embedded input, so the shape args are unused."""
        del mb, seq
        return lambda p, x: self._stage_fn(p, x)

    def apply(
        self,
        variables: dict,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches=None,
        deterministic: bool = True,
        rngs=None,
    ):
        if caches is not None:
            raise NotImplementedError(
                "decode caches are unsupported under pipeline parallelism; "
                "export the params and restack for the dense GPT to decode"
            )
        cfg = self.cfg
        p = variables["params"]
        b, s = tokens.shape
        if positions is None:
            positions = default_positions(
                b, s, cfg.context_parallel, max_positions=cfg.block_size
            )
        x = jnp.take(p["tok_emb"]["embedding"], tokens, axis=0)
        # full (B, S) positions like models/gpt.py — positions[0] would
        # silently apply the first row's positions to every batch row
        x = x + jnp.take(p["pos_emb"], positions, axis=0)
        x = x.astype(cfg.compute_dtype)

        train_drop = (not deterministic) and cfg.dropout > 0.0
        sched_rng = None
        if train_drop:
            if not rngs or "dropout" not in rngs:
                raise ValueError(
                    "dropout > 0 training requires rngs={'dropout': key}"
                )
            k_emb, sched_rng = jax.random.split(rngs["dropout"])
            # embedding dropout (models/gpt.py's nn.Dropout site) applied
            # manually (shared helper with the 1F1B path)
            x = _emb_dropout(x, k_emb, cfg.dropout)

        if cfg.pipeline_parallel and cfg.virtual_stages > 1:
            # interleaved schedule: local slice holds this device's
            # virtual_stages rows (blocked 'pipe' sharding of the permuted
            # stack — cfg.storage_index)
            from solvingpapers_tpu.sharding.pipeline import (
                pipeline_local_apply_interleaved,
            )

            x = pipeline_local_apply_interleaved(
                p["stages"], x, self._stage_fn,
                n_microbatches=cfg.n_microbatches,
                n_virtual=cfg.virtual_stages,
                rng=sched_rng,
            )
        elif cfg.pipeline_parallel:
            # local stage slice has leading dim n_stages/pipe_size == 1
            # (shard_map over in_specs P('pipe'))
            x = pipeline_local_apply(
                p["stages"], x, self._stage_fn,
                n_microbatches=cfg.n_microbatches,
                rng=sched_rng,
            )
        else:
            for g in range(cfg.n_stages):  # GLOBAL stage order
                x = self._stage_fn(
                    jax.tree.map(
                        lambda a: a[cfg.storage_index(g)], p["stages"]
                    ),
                    x,
                    None if sched_rng is None
                    else jax.random.fold_in(sched_rng, g),
                )

        x = LayerNorm().apply({"params": p["ln_f"]}, x)
        logits = (
            x.astype(cfg.compute_dtype)
            @ p["lm_head"]["kernel"].astype(cfg.compute_dtype)
        )
        return logits, None

    @property
    def max_positions(self) -> int:
        return self.cfg.block_size

    # ------------------------------------------------------------------ 1f1b

    def f1b_value_and_grad(self, params, batch, rng=None,
                           model_state=None):
        """Loss AND grads in one 1F1B pass (sharding.pipeline
        .pipeline_1f1b_value_and_grad) — call INSIDE a shard_map whose
        'pipe' axis shards the stage stack. Returns (loss, grads,
        model_state) — state passed through unchanged (stateless) — with
        `grads` matching the params tree (stage grads keep this device's
        leading-1 stage dim; head/embedding grads are pipe-invariant).
        With `rng` and dropout > 0, masks come from the schedule's
        per-(stage, microbatch) regenerable keys (identical in the
        backward recompute) plus a replicated embedding-dropout key —
        the same recipe as the GPipe path. The Trainer opts in via
        TrainConfig.pp_schedule."""
        from solvingpapers_tpu import ops
        from solvingpapers_tpu.models.staged import f1b_lm_value_and_grad

        cfg = self.cfg
        tokens, targets = batch["x"], batch["y"]
        b, s = tokens.shape
        m = cfg.n_microbatches
        positions = default_positions(b, s, False,
                                      max_positions=cfg.block_size)
        head = {"ln_f": params["ln_f"], "lm_head": params["lm_head"]}
        embed = {"tok_emb": params["tok_emb"], "pos_emb": params["pos_emb"]}

        train_drop = rng is not None and cfg.dropout > 0.0
        sched_rng = k_emb = None
        if train_drop:
            k_emb, sched_rng = jax.random.split(rng)

        def embed_fn(ep):
            x = jnp.take(ep["tok_emb"]["embedding"], tokens, axis=0)
            x = x + jnp.take(ep["pos_emb"], positions, axis=0)
            x = x.astype(cfg.compute_dtype)
            if train_drop:
                x = _emb_dropout(x, k_emb, cfg.dropout)
            return x.reshape(m, b // m, s, cfg.dim)

        def head_loss(hp, h, t):
            z = LayerNorm().apply({"params": hp["ln_f"]}, h)
            logits = (
                z.astype(cfg.compute_dtype)
                @ hp["lm_head"]["kernel"].astype(cfg.compute_dtype)
            )
            return ops.cross_entropy(logits, t)

        loss, dstage, dhead, dembed = f1b_lm_value_and_grad(
            params["stages"], embed, head, targets, m, embed_fn,
            self._stage_fn, head_loss, rng=sched_rng,
        )
        grads = {
            "tok_emb": dembed["tok_emb"], "pos_emb": dembed["pos_emb"],
            "stages": dstage,
            "ln_f": dhead["ln_f"], "lm_head": dhead["lm_head"],
        }
        return loss, grads, model_state

    # ---------------------------------------------------------------- export

    def to_dense(self, params: dict):
        """Restack the stage-stacked params into the dense GPT layout
        (block_{i} keys) and return (GPT model, params) — the decode path
        for pipeline-trained weights (PP itself has no cache support).
        GPTPipe block j of stage s is GPT block s*layers_per_stage + j;
        module names are shared, so the forward is bit-identical. The
        export config drops context_parallel: the dense model decodes
        outside shard_map (no 'context' axis to ring over)."""
        from solvingpapers_tpu.models.gpt import GPT

        cfg = self.cfg
        dense = {k: v for k, v in params.items() if k != "stages"}
        for s in range(cfg.n_stages):  # s = GLOBAL stage index
            row = cfg.storage_index(s)
            for j in range(cfg.layers_per_stage):
                dense[f"block_{s * cfg.layers_per_stage + j}"] = jax.tree.map(
                    lambda a: a[row], params["stages"][f"block_{j}"]
                )
        dense_cfg = dataclasses.replace(cfg.block_cfg(), context_parallel=False)
        return GPT(dense_cfg), dense
