"""LLaMA-3-style decoder-only LM.

Capability target: llama3/LLaMA-jax.ipynb — RMSNorm (cell 15), RoPE
(cells 16-17, complex formulation), GQA attention with n_kv_heads <
n_heads (cells 18, 24), SwiGLU feed-forward (cell 25), pre-norm decoder
blocks with dropout (cell 26), untied output head (cell 27). Reference
defaults: dim 256, 2 layers, 4 heads / 2 kv-heads, seq 128 (cell 9).

Differences from the reference (TPU-first):
  * One shared Attention module (models/layers.py) provides GQA + RoPE +
    a preallocated KV cache that decode actually uses — the reference
    plumbs `(cache, position)` through `attention` (cell 24) but its
    `generate` (cell 14) recomputes the full prefix every token.
  * RoPE comes from the shared real-valued table op (ops/rope.py), proven
    equal to the notebook's complex64 formulation by tests/test_ops.py.
  * freqs_cis / mask are not rebuilt per forward (cell 27 recomputes both
    every call); positions index a precomputed table.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops
from solvingpapers_tpu.infer.cache import KVCache
from solvingpapers_tpu.models.layers import (
    Attention,
    GLUFFN,
    RMSNorm,
    default_positions,
    maybe_remat,
    swiglu_hidden_dim,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 50257  # tiktoken gpt2 (LLaMA-jax.ipynb cell 6)
    max_seq_len: int = 128
    dim: int = 256
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    hidden_dim: int | None = None  # None => swiglu 2/3·4·dim convention
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dropout: float = 0.0
    dtype: str = "float32"
    use_flash: bool = False
    remat: bool = False  # jax.checkpoint each block: recompute activations in backward
    # context parallelism: apply the model inside a shard_map whose
    # 'context' axis shards the sequence; attention runs the ppermute ring
    # or Ulysses all_to_all (sharding/ring_attention.py). Positions default
    # to global (derived from the axis index).
    context_parallel: bool = False
    context_impl: str = "ring"  # ring | ulysses

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def ffn_hidden(self) -> int:
        return self.hidden_dim or swiglu_hidden_dim(self.dim)


class LlamaBlock(nn.Module):
    # __call__ args are positional so nn.remat can mark `deterministic`
    # static (static_argnums counts self=0, x=1, positions=2, cache=3)
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions=None, cache=None, deterministic=True,
                 attend_len=None):
        cfg = self.cfg
        h, cache = Attention(
            dim=cfg.dim,
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads,
            causal=True,
            use_rope=True,
            rope_theta=cfg.rope_theta,
            max_seq_len=cfg.max_seq_len,
            dropout=cfg.dropout,
            use_bias=False,
            dtype=cfg.compute_dtype,
            use_flash=cfg.use_flash,
            context_parallel=cfg.context_parallel,
            context_impl=cfg.context_impl,
            name="attn",
        )(
            RMSNorm(eps=cfg.norm_eps, name="attn_norm")(x),
            positions=positions,
            cache=cache,
            deterministic=deterministic,
            attend_len=attend_len,
        )
        x = x + h
        h = GLUFFN(
            dim=cfg.dim,
            hidden_dim=cfg.ffn_hidden,
            activation=ops.silu,
            dtype=cfg.compute_dtype,
            name="ffn",
        )(RMSNorm(eps=cfg.norm_eps, name="ffn_norm")(x))
        if cfg.dropout > 0.0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return x + h, cache


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches: list[KVCache] | None = None,
        deterministic: bool = True,
        attend_len: int | None = None,
    ) -> tuple[jax.Array, list[KVCache] | None]:
        cfg = self.cfg
        b, s = tokens.shape
        if positions is None:
            positions = default_positions(b, s, cfg.context_parallel)
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.compute_dtype, name="tok_emb")(tokens)

        new_caches = [] if caches is not None else None
        block_cls = maybe_remat(LlamaBlock, cfg.remat, caches)
        for i in range(cfg.n_layers):
            x, c = block_cls(cfg, name=f"block_{i}")(
                x,
                positions,
                None if caches is None else caches[i],
                deterministic,
                attend_len,
            )
            if new_caches is not None:
                new_caches.append(c)
        x = RMSNorm(eps=cfg.norm_eps, name="norm_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.compute_dtype, name="lm_head"
        )(x)
        return logits, new_caches

    @property
    def max_positions(self) -> int:
        return self.cfg.max_seq_len

    def init_caches(self, batch: int, max_len: int, dtype=None) -> list[KVCache]:
        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        dtype = dtype or cfg.compute_dtype
        return [
            KVCache.init(batch, max_len, cfg.n_kv_heads, head_dim, dtype)
            for _ in range(cfg.n_layers)
        ]

    def init_cp_caches(
        self, batch: int, prompt_local: int, tail_len: int, dtype=None
    ) -> list:
        """Context-sharded decode caches for infer.generate_cp."""
        from solvingpapers_tpu.infer.cache import CPKVCache

        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        dtype = dtype or cfg.compute_dtype
        return [
            CPKVCache.init(
                batch, prompt_local, tail_len, cfg.n_kv_heads, head_dim, dtype
            )
            for _ in range(cfg.n_layers)
        ]
