"""Dense autoencoder + VAE.

Capability targets:
  * autoencoder/autoencoder.ipynb cell 4 — AutoEncoder 784-256-32-256-784
    with ReLU hidden layers and Sigmoid output (MSE objective, cell 7)
  * autoencoder/variational autoencoder.ipynb cells 5-6 — VAE(784,256,128)
    with reparameterization and summed BCE + analytic KL (ops.vae_loss)

Both operate on flattened images (B, input_dim) in [0, 1].
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops


@dataclasses.dataclass(frozen=True)
class AutoEncoderConfig:
    input_dim: int = 784
    hidden_dim: int = 256
    latent_dim: int = 32
    dtype: str = "float32"

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


class AutoEncoder(nn.Module):
    cfg: AutoEncoderConfig

    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        return self.decode(self.encode(x))

    def setup(self):
        cfg = self.cfg
        dt = cfg.compute_dtype
        self.enc1 = nn.Dense(cfg.hidden_dim, dtype=dt)
        self.enc2 = nn.Dense(cfg.latent_dim, dtype=dt)
        self.dec1 = nn.Dense(cfg.hidden_dim, dtype=dt)
        self.dec2 = nn.Dense(cfg.input_dim, dtype=dt)

    def encode(self, x: jax.Array) -> jax.Array:
        return self.enc2(ops.relu(self.enc1(x.astype(self.cfg.compute_dtype))))

    def decode(self, z: jax.Array) -> jax.Array:
        return jax.nn.sigmoid(self.dec2(ops.relu(self.dec1(z))))


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    input_dim: int = 784
    hidden_dim: int = 256
    latent_dim: int = 128
    dtype: str = "float32"

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


class VAE(nn.Module):
    cfg: VAEConfig

    def setup(self):
        cfg = self.cfg
        dt = cfg.compute_dtype
        self.enc = nn.Dense(cfg.hidden_dim, dtype=dt)
        self.mu_head = nn.Dense(cfg.latent_dim, dtype=dt)
        self.logvar_head = nn.Dense(cfg.latent_dim, dtype=dt)
        self.dec1 = nn.Dense(cfg.hidden_dim, dtype=dt)
        self.dec2 = nn.Dense(cfg.input_dim, dtype=dt)

    def encode(self, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        h = ops.relu(self.enc(x.astype(self.cfg.compute_dtype)))
        return self.mu_head(h), self.logvar_head(h)

    def reparameterize(self, mu, logvar, *, deterministic: bool = False):
        """z = mu + eps * sigma (variational autoencoder.ipynb cell 5)."""
        if deterministic:
            return mu
        eps = jax.random.normal(self.make_rng("sample"), mu.shape, mu.dtype)
        return mu + eps * jnp.exp(0.5 * logvar)

    def decode(self, z: jax.Array) -> jax.Array:
        return jax.nn.sigmoid(self.dec2(ops.relu(self.dec1(z))))

    def __call__(
        self, x: jax.Array, *, deterministic: bool = False
    ) -> tuple[jax.Array, jax.Array, jax.Array]:
        mu, logvar = self.encode(x)
        z = self.reparameterize(mu, logvar, deterministic=deterministic)
        return self.decode(z), mu, logvar
