"""Flax model zoo (L2/L3).

Every model family from the reference, rebuilt on the shared ops/layers:
gpt, llama3 (GQA+RoPE+SwiGLU), gemma (MQA+GeGLU), deepseekv3 (MLA+MoE+MTP),
vit, alexnet, autoencoder/vae, kd teacher/student.
"""

from solvingpapers_tpu.models.layers import Attention, MLP, GLUFFN, RMSNorm, LayerNorm
from solvingpapers_tpu.models.gpt import GPT, GPTConfig
from solvingpapers_tpu.models.llama3 import Llama, LlamaConfig
from solvingpapers_tpu.models.gemma import Gemma, GemmaConfig
from solvingpapers_tpu.models.vit import ViT, ViTConfig
from solvingpapers_tpu.models.alexnet import AlexNet, AlexNetConfig
from solvingpapers_tpu.models.autoencoder import (
    AutoEncoder,
    AutoEncoderConfig,
    VAE,
    VAEConfig,
)
from solvingpapers_tpu.models.kd import (
    MLPClassifier,
    MLPClassifierConfig,
    teacher_config,
    student_config,
)
