"""Shared Flax building blocks (L2).

One `Attention` module serves every transformer in the zoo — the variants
the reference implements separately are config points here:
  * GPT: causal MHA, fused qkv, no RoPE (gpt/gpt-jax.ipynb cell 9)
  * LLaMA3: causal GQA + RoPE (llama3/LLaMA-jax.ipynb cell 24)
  * Gemma: causal MQA-grouped + RoPE (gemma/gemma.ipynb cell 8)
  * ViT: bidirectional MHA (vision transformer/ViT.ipynb cell 10)
MLA is structurally different (latent cache) and lives in models/deepseekv3.py.

All dense layers take a compute `dtype` (bf16 for TPU training) with f32
params; reductions inside ops.* are f32.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops
from solvingpapers_tpu.infer.cache import KVCache, update_kv_cache


def default_positions(
    b: int, s: int, context_parallel: bool = False,
    context_axis: str = "context", max_positions: int | None = None,
) -> jax.Array:
    """Default (B, S) absolute positions. Under context parallelism the
    caller sees only its local sequence shard inside shard_map, so defaults
    must be GLOBAL (axis_index * s + arange) — otherwise RoPE/learned
    tables restart at 0 on every shard while the ring masks globally. One
    definition for Attention and every model's embedding path.

    `max_positions` (e.g. a learned table length) turns silent clipping
    into a trace-time error: jnp.take would clamp out-of-range global
    positions to the last row and train a silently wrong objective."""
    if context_parallel:
        axis_size = jax.lax.psum(1, context_axis)  # static under shard_map
        if max_positions is not None and axis_size * s > max_positions:
            raise ValueError(
                f"global sequence {axis_size * s} (= {axis_size} context "
                f"shards x {s}) exceeds max positions {max_positions}; "
                "jnp.take would silently clamp to the last table row"
            )
        start = jax.lax.axis_index(context_axis) * s
        return jnp.broadcast_to(start + jnp.arange(s), (b, s))
    if max_positions is not None and s > max_positions:
        raise ValueError(f"sequence {s} exceeds max positions {max_positions}")
    return jnp.broadcast_to(jnp.arange(s), (b, s))


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        weight = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        return ops.rms_norm(x, weight, self.eps)


class LayerNorm(nn.Module):
    eps: float = 1e-5
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        weight = self.param("weight", nn.initializers.ones, (x.shape[-1],))
        bias = (
            self.param("bias", nn.initializers.zeros, (x.shape[-1],))
            if self.use_bias
            else None
        )
        return ops.layer_norm(x, weight, bias, self.eps)


class Attention(nn.Module):
    """Multi-head attention with optional GQA/MQA, RoPE, causality and KV cache.

    Call: (x, *, positions, cache, deterministic) -> (out, new_cache).
    `positions` (B, S) absolute positions are required when a cache is
    passed; otherwise default to arange. The KV cache is preallocated
    (infer/cache.py); masking is position-based so stale slots never leak.
    """

    dim: int
    n_heads: int
    n_kv_heads: int | None = None  # None => MHA
    head_dim: int | None = None
    causal: bool = True
    use_rope: bool = False
    rope_theta: float = 10000.0
    max_seq_len: int = 4096  # rope table length
    dropout: float = 0.0
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32
    # Pallas kernel for the uncached path (supports attention-prob dropout
    # in-kernel). Note: a pallas_call is opaque to GSPMD, so under a sharded
    # mesh this module's direct call would gather its operands — mesh runs
    # should use kernels.sharded_flash_attention (shard_map-wrapped: batch
    # over data/fsdp, heads over model); the dense path partitions anywhere.
    use_flash: bool = False
    # context parallelism: REQUIRES the module to be applied inside a
    # shard_map whose `context_axis` shards the sequence dimension
    # (positions must be global — derived from the axis index when None).
    # context_impl "ring" rotates K/V chunks via ppermute (memory-optimal,
    # any head count); "ulysses" all_to_alls to head sharding around a dense
    # core (needs n_heads and n_kv_heads divisible by the axis size). Decode
    # under CP uses the context-sharded CPKVCache (infer.generate_cp /
    # model.init_cp_caches); a plain per-shard KVCache is rejected.
    context_parallel: bool = False
    context_axis: str = "context"
    context_impl: str = "ring"  # ring | ulysses

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        *,
        positions: jax.Array | None = None,
        cache: KVCache | None = None,
        deterministic: bool = True,
        attend_len: int | None = None,
    ) -> tuple[jax.Array, KVCache | None]:
        b, s, _ = x.shape
        n_kv = self.n_kv_heads or self.n_heads
        head_dim = self.head_dim or self.dim // self.n_heads
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=self.use_bias, dtype=self.dtype, name=name
        )

        if positions is None:
            positions = default_positions(
                b, s, self.context_parallel, self.context_axis
            )

        if n_kv == self.n_heads:
            qkv = dense(3 * self.n_heads * head_dim, "qkv")(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            q = dense(self.n_heads * head_dim, "q")(x)
            kv = dense(2 * n_kv * head_dim, "kv")(x)
            k, v = jnp.split(kv, 2, axis=-1)
        q = q.reshape(b, s, self.n_heads, head_dim)
        k = k.reshape(b, s, n_kv, head_dim)
        v = v.reshape(b, s, n_kv, head_dim)

        if self.use_rope:
            cos, sin = ops.precompute_rope(head_dim, self.max_seq_len, self.rope_theta)
            q = ops.apply_rope(q, cos, sin, positions=positions)
            k = ops.apply_rope(k, cos, sin, positions=positions)

        cp_cache = cache is not None and self.context_parallel
        if cp_cache:
            from solvingpapers_tpu.infer.cache import (
                CPKVCache, validate_cp_cache,
            )

            validate_cp_cache(
                cache, CPKVCache,
                getattr(cache, "k_prompt", jnp.zeros((1, 0, 1, 1))).shape[1],
                s,
            )
            if s > 1:
                # CP PREFILL: this shard's contiguous chunk fills its
                # prompt slice in place; attention falls through to the
                # ring/ulysses branch below
                cache = cache.replace(
                    k_prompt=k.astype(cache.k_prompt.dtype),
                    v_prompt=v.astype(cache.v_prompt.dtype),
                )
        if cp_cache and s == 1:
            # CP DECODE STEP: replicated token, sharded prompt cache.
            # Shard-local logsumexp partials over the local prompt chunk
            # (+ the replicated tail on the last shard only, counted once)
            # combine with one pmax + two psums; the cache never moves.
            from solvingpapers_tpu.infer.cache import cp_cache_partial_softmax_kv
            from solvingpapers_tpu.ops.attention import BIG_NEG, repeat_kv

            axis = self.context_axis
            cp_size = jax.lax.psum(1, axis)
            idx = jax.lax.axis_index(axis)
            s0_glob = cache.k_prompt.shape[1] * cp_size
            tail_len = cache.k_tail.shape[1]
            pos = positions[0, 0]
            cache = cache.replace(
                k_tail=jax.lax.dynamic_update_slice(
                    cache.k_tail, k.astype(cache.k_tail.dtype),
                    (0, pos - s0_glob, 0, 0),
                ),
                v_tail=jax.lax.dynamic_update_slice(
                    cache.v_tail, v.astype(cache.v_tail.dtype),
                    (0, pos - s0_glob, 0, 0),
                ),
            )
            group = self.n_heads // n_kv
            q32 = q.astype(jnp.float32) * head_dim**-0.5
            # every prompt slot precedes pos (pos >= s0_glob): no mask
            scores_p = jnp.einsum(
                "bsnh,btnh->bnst", q32,
                repeat_kv(cache.k_prompt, group).astype(jnp.float32),
            )
            scores_t = jnp.einsum(
                "bsnh,btnh->bnst", q32,
                repeat_kv(cache.k_tail, group).astype(jnp.float32),
            )
            mask_t = (s0_glob + jnp.arange(tail_len) <= pos) & (
                idx == cp_size - 1
            )
            scores_t = jnp.where(
                mask_t[None, None, None, :], scores_t, BIG_NEG
            )
            vals = repeat_kv(
                jnp.concatenate([cache.v_prompt, cache.v_tail], axis=1),
                group,
            )
            out = cp_cache_partial_softmax_kv(
                scores_p, scores_t, vals, axis
            ).astype(self.dtype)
        elif cache is not None and not cp_cache:
            # single contiguous segment per step: write at the first position
            cache = update_kv_cache(cache, k, v, positions[0, 0])
            if attend_len is not None:
                # PREFILL contract: this chunk occupies cache slots
                # [attend_len - S, attend_len) and every earlier slot is
                # written — so attention is exactly end-aligned causal over
                # the first attend_len slots (a STATIC slice: no
                # (S, max_len) mask/prob tensor ever exists, which is what
                # makes 16k-prompt prefill fit in HBM). use_flash runs the
                # Pallas kernel's seq_q != seq_k end-aligned causal mode.
                k_att = jax.lax.slice_in_dim(cache.k, 0, attend_len, axis=1)
                v_att = jax.lax.slice_in_dim(cache.v, 0, attend_len, axis=1)
                if self.use_flash:
                    from solvingpapers_tpu.kernels import flash_attention

                    out = flash_attention(q, k_att, v_att, causal=True)
                else:
                    out = ops.dot_product_attention(
                        q, k_att, v_att, causal=True
                    )
            else:
                k_full, v_full = cache.k, cache.v
                kv_idx = jnp.arange(cache.max_len)
                # (B, 1, S, max_len): query at position p sees kv slots <= p
                mask = kv_idx[None, None, None, :] <= positions[:, None, :, None]
                out = ops.dot_product_attention(q, k_full, v_full, mask=mask)
        elif self.context_parallel:
            from solvingpapers_tpu.sharding.ring_attention import (
                ring_attention_local,
                ring_flash_attention_local,
                ulysses_attention_local,
            )

            from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend

            drop_active = self.dropout > 0.0 and not deterministic
            if drop_active and self.context_impl == "ring" and not (
                self.use_flash and is_tpu_backend()
            ):
                raise NotImplementedError(
                    "attention-prob dropout under ring context parallelism "
                    "requires the flash path on real TPU (in-kernel masks "
                    "salted per (owner, chunk) — "
                    "sharding/ring_attention._chunk_seed); set dropout=0.0, "
                    "use_flash=True, or context_impl='ulysses'"
                )
            if drop_active and self.context_impl == "ulysses" \
                    and self.use_flash and not is_tpu_backend():
                raise NotImplementedError(
                    "in-kernel dropout needs the hardware PRNG: off-TPU "
                    "Ulysses dropout runs the dense core (use_flash=False)"
                )
            if self.context_impl == "ring":
                # GQA kv heads stay un-repeated: the ring repeats them after
                # each transfer so ppermute carries only n_kv heads.
                # use_flash swaps the per-chunk jnp einsum core for the
                # Pallas kernel (custom-VJP ring backward).
                if self.use_flash:
                    kwargs = {}
                    if drop_active:
                        # per-shard decorrelation comes from _chunk_seed's
                        # (owner, chunk) salt; the rng seed is shared so
                        # the same (owner, chunk) mask is used by fwd+bwd
                        kwargs = dict(
                            dropout_rate=self.dropout,
                            dropout_seed=jax.random.randint(
                                self.make_rng("dropout"), (), 0,
                                jnp.iinfo(jnp.int32).max,
                            ),
                        )
                    out = ring_flash_attention_local(
                        q, k, v, self.context_axis, causal=self.causal,
                        **kwargs,
                    )
                else:
                    out = ring_attention_local(
                        q, k, v, self.context_axis, causal=self.causal
                    )
            elif self.context_impl == "ulysses":
                # dropout: after the all_to_all each member computes FULL
                # attention for its own head group, so every (head, block)
                # mask is produced by exactly one member — the engine's
                # per-('context') rng fold already decorrelates members,
                # and the cores decorrelate heads internally (the kernel's
                # per-(bn, block) uid salt / the dense mask shape)
                if self.use_flash:
                    from solvingpapers_tpu.kernels import flash_attention

                    kwargs = {}
                    if drop_active:
                        kwargs = dict(
                            dropout_rate=self.dropout,
                            dropout_seed=jax.random.randint(
                                self.make_rng("dropout"), (), 0,
                                jnp.iinfo(jnp.int32).max,
                            ),
                        )
                    core = functools.partial(
                        flash_attention, causal=self.causal, **kwargs
                    )
                else:
                    kwargs = {}
                    if drop_active:
                        kwargs = dict(
                            dropout_rate=self.dropout,
                            dropout_rng=self.make_rng("dropout"),
                            deterministic=False,
                        )
                    core = functools.partial(
                        ops.dot_product_attention, causal=self.causal,
                        **kwargs,
                    )
                out = ulysses_attention_local(q, k, v, self.context_axis, core)
            else:
                raise ValueError(f"unknown context_impl {self.context_impl!r}")
        else:
            if self.use_flash:
                out = apply_flash_attention(
                    self, q, k, v, causal=self.causal,
                    dropout_rate=self.dropout, deterministic=deterministic,
                )
            else:
                out = ops.dot_product_attention(
                    q,
                    k,
                    v,
                    causal=self.causal,
                    dropout_rate=self.dropout,
                    dropout_rng=(
                        None if deterministic else self.make_rng("dropout")
                    ),
                    deterministic=deterministic,
                )

        out = out.reshape(b, s, self.n_heads * head_dim)
        out = dense(self.dim, "out")(out)
        if self.dropout > 0.0:
            out = nn.Dropout(self.dropout)(out, deterministic=deterministic)
        return out, cache


class MLP(nn.Module):
    """Plain 2-layer MLP (gpt/gpt-jax.ipynb cell 10; ViT.ipynb cell 10)."""

    dim: int
    hidden_dim: int
    activation: Callable[[jax.Array], jax.Array] = ops.gelu_tanh
    dropout: float = 0.0
    use_bias: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array, *, deterministic: bool = True) -> jax.Array:
        x = nn.Dense(self.hidden_dim, use_bias=self.use_bias, dtype=self.dtype, name="fc")(x)
        x = self.activation(x)
        x = nn.Dense(self.dim, use_bias=self.use_bias, dtype=self.dtype, name="proj")(x)
        if self.dropout > 0.0:
            x = nn.Dropout(self.dropout)(x, deterministic=deterministic)
        return x


class GLUFFN(nn.Module):
    """Gated-linear-unit FFN: down(act(gate(x)) * up(x)).

    activation=silu → SwiGLU (llama3 cell 25, deepseekv3 cell 21);
    activation=gelu_tanh → GeGLU (gemma cell 9).
    """

    dim: int
    hidden_dim: int
    activation: Callable[[jax.Array], jax.Array] = ops.silu
    use_bias: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        gate = nn.Dense(self.hidden_dim, use_bias=self.use_bias, dtype=self.dtype, name="gate")(x)
        up = nn.Dense(self.hidden_dim, use_bias=self.use_bias, dtype=self.dtype, name="up")(x)
        return nn.Dense(self.dim, use_bias=self.use_bias, dtype=self.dtype, name="down")(
            self.activation(gate) * up
        )


def swiglu_hidden_dim(dim: int, multiplier: int = 4) -> int:
    """The (2/3)·4·dim sizing convention (deepseekv3 cell 21: ((2D)*4)//3)."""
    return (2 * dim * multiplier) // 3


def apply_flash_attention(module, q, k, v, *, causal, scale=None,
                          dropout_rate=0.0, deterministic=True):
    """Flash attention with the framework's dropout policy, shared by every
    use_flash model (Attention here, DeepSeekV3's MLA): in-kernel prob
    dropout on real TPU (same Bernoulli semantics as the dense path; mask
    regenerated in the backward from the seed, never materialized); when
    dropout is active OFF-TPU the dense path runs instead — interpret-mode
    pltpu PRNG is a zero stub, so in-kernel dropout cannot run there.

    On a >1-device GSPMD mesh (Trainer marks it via sharding.ambient_mesh)
    the call routes through kernels.sharded_flash_attention — pallas_call is
    opaque to GSPMD, so the direct call would silently all-gather q/k/v
    (losing DP batch partitioning and TP head partitioning alike)."""
    from solvingpapers_tpu.kernels import flash_attention, sharded_flash_attention
    from solvingpapers_tpu.kernels.flash_attention import is_tpu_backend
    from solvingpapers_tpu.sharding import get_ambient_mesh

    mesh = get_ambient_mesh()
    if mesh is not None and mesh.devices.size > 1:
        kernel = functools.partial(sharded_flash_attention, mesh=mesh)
    else:
        kernel = flash_attention

    if dropout_rate > 0.0 and not deterministic:
        if is_tpu_backend():
            seed = jax.random.randint(
                module.make_rng("dropout"), (), 0, jnp.iinfo(jnp.int32).max
            )
            return kernel(
                q, k, v, causal=causal, scale=scale,
                dropout_rate=dropout_rate, dropout_seed=seed,
            )
        return ops.dot_product_attention(
            q, k, v, causal=causal, scale=scale, dropout_rate=dropout_rate,
            dropout_rng=module.make_rng("dropout"), deterministic=False,
        )
    return kernel(q, k, v, causal=causal, scale=scale)


def maybe_remat(block_cls, remat: bool, caches) -> type:
    """Wrap a decoder-block class in jax.checkpoint for training (trades
    recompute for HBM — dense attention at dim/seq 1024 OOMs one v5e
    without it). Requires the block's __call__ signature to be
    (self, x, positions, cache, deterministic): static_argnums=(4,) marks
    the python-bool `deterministic` static (self counts as 0). Decode
    (caches present) has no backward pass, so remat is skipped there.
    Numerical equivalence: tests/test_llama3.py::test_remat_matches_noremat.
    """
    if remat and caches is None:
        return nn.remat(block_cls, prevent_cse=False, static_argnums=(4,))
    return block_cls
