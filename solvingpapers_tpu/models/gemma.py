"""Gemma-style decoder-only char LM.

Capability target: gemma/gemma.ipynb — RMSNorm (cell 6), rotary embeddings
(cell 7), grouped "MQA" attention with 4 q-heads / 2 kv-heads (cell 8),
GeGLU FFN with hidden 4*dim and no biases (cells 9-10), pre-norm decoder
layers (cell 11), embed -> dropout -> 12 layers -> norm -> untied linear
head (cell 12). Reference defaults (cell 1): dim 768, 12 layers, block 128,
dropout 0.1, AdamW beta=(0.9, 0.95) wd 0.1 max_lr 2.5e-4.

TPU-first differences:
  * The reference materializes a (seq, D, D) rotation matrix per call per
    layer — its own markdown (cell 21) blames this for slow inference. Here
    RoPE is the shared precomputed cos/sin table op (ops/rope.py), proven
    equal to the rotation-matrix formulation in tests/test_ops.py.
  * The reference's MQA builds `heads//kv_heads` separate full-width query
    Linears sharing one K and one V; semantically that is GQA, served by the
    shared Attention module (one fused q projection, kv-head grouping).
  * KV-cached jitted decode (the reference's generate, cell 20, recomputes
    the full prefix per token).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops
from solvingpapers_tpu.infer.cache import KVCache
from solvingpapers_tpu.models.layers import (
    Attention,
    GLUFFN,
    RMSNorm,
    default_positions,
    maybe_remat,
)


@dataclasses.dataclass(frozen=True)
class GemmaConfig:
    vocab_size: int = 2000  # gemma.ipynb cell 1 (char pipeline resizes to 65)
    max_seq_len: int = 128
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 4
    n_kv_heads: int = 2
    hidden_dim: int | None = None  # None => 4*dim (GeGLU, cell 9)
    # FFN gate activation: "gelu_tanh" (GeGLU, cell 9 — notebook parity)
    # or "silu" (SwiGLU) — an ablation knob (tools/gemma_markov_ablation)
    activation: str = "gelu_tanh"
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dropout: float = 0.1
    dtype: str = "float32"
    use_flash: bool = False
    remat: bool = False  # jax.checkpoint each block: recompute activations in backward
    # context parallelism (same contract as LlamaConfig: apply inside a
    # shard_map whose 'context' axis shards the sequence)
    context_parallel: bool = False
    context_impl: str = "ring"  # ring | ulysses

    def __post_init__(self):
        if self.activation not in ("gelu_tanh", "silu"):
            raise ValueError(
                f"activation must be 'gelu_tanh' or 'silu', got "
                f"{self.activation!r}"
            )

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def ffn_hidden(self) -> int:
        return self.hidden_dim or 4 * self.dim


class GemmaBlock(nn.Module):
    # __call__ args are positional so nn.remat can mark `deterministic`
    # static (static_argnums counts self=0, x=1, positions=2, cache=3)
    cfg: GemmaConfig

    @nn.compact
    def __call__(self, x, positions=None, cache=None, deterministic=True,
                 attend_len=None):
        cfg = self.cfg
        h, cache = Attention(
            dim=cfg.dim,
            n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_kv_heads,
            causal=True,
            use_rope=True,
            rope_theta=cfg.rope_theta,
            max_seq_len=cfg.max_seq_len,
            dropout=cfg.dropout,
            use_bias=False,
            dtype=cfg.compute_dtype,
            use_flash=cfg.use_flash,
            context_parallel=cfg.context_parallel,
            context_impl=cfg.context_impl,
            name="attn",
        )(
            RMSNorm(eps=cfg.norm_eps, name="attn_norm")(x),
            positions=positions,
            cache=cache,
            deterministic=deterministic,
            attend_len=attend_len,
        )
        x = x + h
        h = GLUFFN(
            dim=cfg.dim,
            hidden_dim=cfg.ffn_hidden,
            activation=getattr(ops, cfg.activation),
            dtype=cfg.compute_dtype,
            name="ffn",
        )(RMSNorm(eps=cfg.norm_eps, name="ffn_norm")(x))
        if cfg.dropout > 0.0:
            h = nn.Dropout(cfg.dropout)(h, deterministic=deterministic)
        return x + h, cache


class Gemma(nn.Module):
    cfg: GemmaConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches: list[KVCache] | None = None,
        deterministic: bool = True,
        attend_len: int | None = None,
    ) -> tuple[jax.Array, list[KVCache] | None]:
        cfg = self.cfg
        b, s = tokens.shape
        if positions is None:
            positions = default_positions(b, s, cfg.context_parallel)
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.compute_dtype, name="tok_emb")(tokens)
        if cfg.dropout > 0.0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)

        new_caches = [] if caches is not None else None
        block_cls = maybe_remat(GemmaBlock, cfg.remat, caches)
        for i in range(cfg.n_layers):
            x, c = block_cls(cfg, name=f"block_{i}")(
                x,
                positions,
                None if caches is None else caches[i],
                deterministic,
                attend_len,
            )
            if new_caches is not None:
                new_caches.append(c)
        x = RMSNorm(eps=cfg.norm_eps, name="norm_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=True, dtype=cfg.compute_dtype, name="lm_head"
        )(x)
        return logits, new_caches

    @property
    def max_positions(self) -> int:
        return self.cfg.max_seq_len

    def init_caches(self, batch: int, max_len: int, dtype=None) -> list[KVCache]:
        cfg = self.cfg
        head_dim = cfg.dim // cfg.n_heads
        dtype = dtype or cfg.compute_dtype
        return [
            KVCache.init(batch, max_len, cfg.n_kv_heads, head_dim, dtype)
            for _ in range(cfg.n_layers)
        ]
