"""AlexNet CNN.

Capability target: alexnet/alexnet.py:5-44 — 5-conv feature stack with
ReLU + LocalResponseNorm + MaxPool, then a 3-linear classifier with
Dropout(0.5). The reference hardcodes the classifier input as 256*5*5
(sized for ~227px inputs despite its "#CIFAR10" comment, alexnet.py:4,32);
here the flatten size is derived from the actual feature-map shape, so the
model works at any input size >= 63px.

TPU-first: NHWC layout, LRN as a shared op (ops.local_response_norm).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops


@dataclasses.dataclass(frozen=True)
class AlexNetConfig:
    n_classes: int = 10
    in_channels: int = 3
    dropout: float = 0.5
    dtype: str = "float32"

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)


class AlexNet(nn.Module):
    cfg: AlexNetConfig

    @nn.compact
    def __call__(self, images: jax.Array, *, deterministic: bool = True) -> jax.Array:
        """images: (B, H, W, C) NHWC -> logits (B, n_classes)."""
        cfg = self.cfg
        dt = cfg.compute_dtype
        x = images.astype(dt)
        pool = lambda y: nn.max_pool(y, (3, 3), strides=(2, 2))  # noqa: E731

        x = nn.Conv(96, (11, 11), strides=(4, 4), dtype=dt, name="conv1")(x)
        x = ops.relu(x)
        x = ops.local_response_norm(x, size=5)
        x = pool(x)
        x = nn.Conv(256, (5, 5), padding=2, dtype=dt, name="conv2")(x)
        x = ops.relu(x)
        x = ops.local_response_norm(x, size=5)
        x = pool(x)
        x = nn.Conv(384, (3, 3), padding=1, dtype=dt, name="conv3")(x)
        x = ops.relu(x)
        x = nn.Conv(384, (3, 3), padding=1, dtype=dt, name="conv4")(x)
        x = ops.relu(x)
        x = nn.Conv(256, (3, 3), padding=1, dtype=dt, name="conv5")(x)
        x = ops.relu(x)
        x = pool(x)

        x = x.reshape(x.shape[0], -1)
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        x = ops.relu(nn.Dense(4096, dtype=dt, name="fc1")(x))
        x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        x = ops.relu(nn.Dense(4096, dtype=dt, name="fc2")(x))
        return nn.Dense(cfg.n_classes, dtype=dt, name="fc3")(x)
