"""DeepSeek-V3-style decoder: MLA + MoE + optional MTP.

Capability target: deepseekv3/deepseekv3.ipynb — the reference's flagship.
  * config (cell 4): block 256, dim 512, 8 heads, 6 layers, latent 64,
    8 experts top-2 + shared expert, aux-free load balancing (rate 0.001),
    noisy top-k off, mtp_heads 0, vocab 50257, dropout 0.1
  * sinusoidal PE added to embeddings (cells 16-17; the `base_freq` config
    knob is dead in the reference — not reproduced)
  * MLA with absorbed query attending latents directly (cell 25)
  * MoE with masked-softmax top-2 over biased gate logits, shared expert,
    no-grad bias update sign(mean(load)-load) (cell 23)
  * depth scaling 2*L^-0.5 after the layer stack, final RMSNorm, lm_head
    weight-tied to the embedding (cell 31)
  * MTP: per extra head k, merge Linear(2D->D) of [norm(h), norm(emb of
    token i+k)] -> extra DecoderLayer -> proj head -> shared lm_head
    (cell 33's machinery, vectorized; the shipped config disables it)

TPU-first divergences (documented per SURVEY.md hard part #2):
  * One latent per layer shared by all heads with per-head decompression
    (the paper's MLA); the reference gives each head its own W_dkv and
    threads one growing cache through heads AND layers (cell 27 quirk).
  * MoE dispatch is static-shape one-hot einsums over expert capacity slots
    (ops/moe.py), not a python loop; expert weights are stacked (E, ...)
    so the `expert` mesh axis shards them (EP via GSPMD all_to_all).
  * MTP is computed for all positions in parallel, not a per-position
    python loop.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import linen as nn

from solvingpapers_tpu import ops
from solvingpapers_tpu.infer.cache import (
    CPLatentCache, LatentCache, update_latent_cache,
)
from solvingpapers_tpu.models.layers import (
    GLUFFN, RMSNorm, LayerNorm, maybe_remat, swiglu_hidden_dim,
)


@dataclasses.dataclass(frozen=True)
class DeepSeekV3Config:
    vocab_size: int = 50257
    block_size: int = 256
    dim: int = 512
    n_layers: int = 6
    n_heads: int = 8
    latent_dim: int = 64
    n_experts: int = 8
    top_experts: int = 2
    # decoupled-RoPE branch width for MLA (real DeepSeek-V3's d_h^R; the
    # reference notebook's sinusoidal-only simplification is rope_dim=0).
    # Compressed-latent attention alone has no precise relative-position
    # channel — on position-critical data (e.g. the order-k Markov quality
    # corpus) the notebook variant cannot beat the unigram floor. A small
    # rotary query per head and ONE shared rotary key ride along the latent
    # score via concatenation, so k = v = cat(latent, k_rope) stays MQA and
    # every attention path (dense/flash/ring/cache) is unchanged in shape.
    rope_dim: int = 0
    rope_theta: float = 10000.0
    # Scale on the additive sinusoidal PE. The notebook adds O(1) sinusoids
    # to 0.02-std embeddings (cells 16-17, 31), so position carries ~50x the
    # token signal into layer 1 AND into the gate of every MoE layer — on
    # position-critical corpora the model cannot beat the unigram floor, and
    # the routing gate specializes experts by position (the drop_fraction
    # 0.2-0.5 / load_max 0.7 collapse the round-2 verdict flagged traces to
    # exactly this). 0.02 balances the two signals (measured: markov-corpus
    # val gap 1.80 -> 0.08 nats; drop_fraction 0.5 -> 0.0). Default 1.0 is
    # strict notebook parity (golden tests pin it); every shipped training
    # workload sets 0.02.
    pe_scale: float = 1.0
    use_shared_expert: bool = True
    noisy_topk: bool = False
    use_aux_free: bool = True
    aux_free_bias_update_rate: float = 0.001
    # optional complementary sequence-wise balance loss (DeepSeek-V3 paper's
    # L_Bal, eq. 17-18 — the notebook implements only the bias mechanism):
    # weight * sum_e f_e * P_e with f_e the scaled selection fraction and
    # P_e the mean gate probability. 0.0 = off (notebook parity); small
    # values (1e-3..1e-2) push residual imbalance the bias update alone
    # leaves (drop_fraction > 0 on clustered data).
    balance_loss_weight: float = 0.0
    moe_impl: str = "dispatch"  # dispatch | dense
    capacity_factor: float = 2.0
    mtp_heads: int = 0
    mtp_loss_weight: float = 0.3
    dropout: float = 0.1
    attn_dropout: float = 0.1
    remat: bool = False  # jax.checkpoint each decoder layer
    use_flash: bool = False  # MLA scores via the Pallas flash kernel (train path)
    # context parallelism (apply inside a shard_map whose 'context' axis
    # shards the sequence): MLA runs the kv ring over the LATENT stream
    # (absorbed-query MLA is MQA with k = v = latents, so the ring's
    # n_kv=1 path serves it; Ulysses cannot — 1 kv head can't split).
    # MoE load stats / bias updates are psum'd across the step's axes so
    # the routing state stays shard-invariant.
    context_parallel: bool = False
    # how the 'expert' mesh axis is used inside the CP shard_map:
    #   "sliced"     — tokens replicated over 'expert'; each member runs its
    #                  E/ep expert columns and partial combines psum
    #                  (ops.moe.moe_expert_sliced_combine).
    #   "all_to_all" — token-dispatch EP: each member owns 1/ep of the
    #                  tokens, all_to_all ships capacity slots to the
    #                  experts' owners and back, an all_gather restores the
    #                  replicated-token contract afterwards
    #                  (ops.moe.moe_all_to_all_combine) — communication
    #                  scales with routed capacity, not the full token count.
    ep_impl: str = "sliced"
    norm_eps: float = 1e-6
    dtype: str = "float32"

    def __post_init__(self):
        if self.ep_impl not in ("sliced", "all_to_all"):
            raise ValueError(
                f"ep_impl must be 'sliced' or 'all_to_all', got "
                f"{self.ep_impl!r}"
            )
        if self.moe_impl not in ("dispatch", "dense"):
            raise ValueError(
                f"moe_impl must be 'dispatch' or 'dense', got "
                f"{self.moe_impl!r}"
            )

    @property
    def stats_axes(self) -> tuple | None:
        """Axes MoE state/stats must be psum'd over under shard_map."""
        return ("data", "fsdp", "context") if self.context_parallel else None

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.dtype(self.dtype)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def expert_hidden(self) -> int:
        return swiglu_hidden_dim(self.dim)  # ((2D)*4)//3, cell 21


class MLA(nn.Module):
    """Multi-head latent attention with absorbed queries (cell 25).

    The (B, S, L) latent is both the cache and the attention target:
    scores = (x W_q W_k^T) @ latent^T, context = probs @ latent, decompressed
    per head only on output (@ W_v). No (S, head_dim) k/v are materialized.
    """

    cfg: DeepSeekV3Config

    @nn.compact
    def __call__(self, x, positions=None, cache=None, deterministic=True,
                 attend_len=None):
        cfg = self.cfg
        b, s, _ = x.shape
        n, hd, lat = cfg.n_heads, cfg.head_dim, cfg.latent_dim
        if positions is None:
            # CP-aware default (global positions derived from the axis
            # index) — the PP stage_fn applies layers without positions, so
            # under CP x PP this default must not restart at 0 per shard
            from solvingpapers_tpu.models.layers import default_positions

            positions = default_positions(b, s, cfg.context_parallel)
        cp_cache = cache is not None and cfg.context_parallel
        if cp_cache:
            from solvingpapers_tpu.infer.cache import validate_cp_cache

            validate_cp_cache(
                cache, CPLatentCache,
                getattr(cache, "c_prompt", jnp.zeros((1, 0, 1))).shape[1], s,
            )

        latent = nn.Dense(
            lat, use_bias=False, dtype=cfg.compute_dtype, name="w_dkv"
        )(x)  # (B, S, L)
        init = nn.initializers.normal(0.02)
        w_q = self.param("w_q", init, (cfg.dim, n, hd))
        w_k = self.param("w_k", init, (lat, n, hd))
        w_v = self.param("w_v", init, (lat, n, hd))

        dt = cfg.compute_dtype
        q = jnp.einsum("bsd,dnh->bsnh", x.astype(dt), w_q.astype(dt))
        # absorbed query: project q into latent space once, score vs latents
        q_lat = jnp.einsum("bsnh,lnh->bsnl", q, w_k.astype(dt))

        R = cfg.rope_dim
        if R:
            # decoupled RoPE (real DSV3; see DeepSeekV3Config.rope_dim): the
            # rotary halves concatenate onto the latent score so the cache,
            # ring and flash paths below all operate on (L+R)-wide vectors
            cos, sin = ops.precompute_rope(R, cfg.block_size, cfg.rope_theta)
            w_qr = self.param("w_qr", init, (cfg.dim, n, R))
            q_rope = jnp.einsum("bsd,dnr->bsnr", x.astype(dt), w_qr.astype(dt))
            q_rope = ops.apply_rope(q_rope, cos, sin, positions=positions)
            k_rope = nn.Dense(R, use_bias=False, dtype=dt, name="w_kr")(x)
            k_rope = ops.apply_rope(
                k_rope[:, :, None, :], cos, sin, positions=positions
            )[:, :, 0]
            q_lat = jnp.concatenate([q_lat, q_rope.astype(dt)], axis=-1)
            latent = jnp.concatenate(
                [latent.astype(dt), k_rope.astype(dt)], axis=-1
            )
        scale = (hd + R) ** -0.5 if R else hd**-0.5

        if cp_cache and s > 1:
            # CP PREFILL: this shard's contiguous prompt chunk exactly fills
            # its c_prompt slice — written in place, no resharding — and
            # attention falls through to the ring path below (cross-shard
            # causality is the ring's job, cache slots play no part yet)
            cache = cache.replace(
                c_prompt=latent.astype(cache.c_prompt.dtype)
            )
        if cp_cache and s == 1:
            # CP DECODE STEP: the token is replicated across the context
            # axis; its latent lands in the replicated tail, shard-local
            # logsumexp partials over the sharded prompt chunk (+ tail on
            # the last shard only, counted once) combine with one pmax +
            # two psums — the 32k+ prompt cache never moves off its shard.
            from solvingpapers_tpu.infer.cache import cp_cache_partial_softmax
            from solvingpapers_tpu.ops.attention import BIG_NEG

            cp_size = jax.lax.psum(1, "context")
            idx = jax.lax.axis_index("context")
            s0_glob = cache.c_prompt.shape[1] * cp_size
            tail_len = cache.c_tail.shape[1]
            pos = positions[0, 0]
            cache = cache.replace(
                c_tail=jax.lax.dynamic_update_slice(
                    cache.c_tail, latent.astype(cache.c_tail.dtype),
                    (0, pos - s0_glob, 0),
                )
            )
            q32 = q_lat.astype(jnp.float32) * scale
            # every prompt slot precedes pos (pos >= s0_glob): no mask
            scores_p = jnp.einsum(
                "bsnl,btl->bnst", q32, cache.c_prompt.astype(jnp.float32)
            )
            scores_t = jnp.einsum(
                "bsnl,btl->bnst", q32, cache.c_tail.astype(jnp.float32)
            )
            tail_pos = s0_glob + jnp.arange(tail_len)
            mask_t = (tail_pos[None, None, None, :] <= pos) & (
                idx == cp_size - 1
            )
            scores_t = jnp.where(mask_t, scores_t, BIG_NEG)
            vals = jnp.concatenate([cache.c_prompt, cache.c_tail], axis=1)
            ctx = cp_cache_partial_softmax(
                scores_p, scores_t, vals, "context"
            ).astype(dt)
        elif cfg.context_parallel and (cache is None or s > 1):
            # ring over the latent stream (k = v = latents, one shared kv
            # head): long-context CP for the flagship family. The same
            # latent-space algebra as the dense path — decompression by
            # w_v happens after the ring, on the local ctx shard.
            from solvingpapers_tpu.sharding.ring_attention import (
                ring_attention_local,
                ring_flash_attention_local,
            )

            from solvingpapers_tpu.kernels.flash_attention import (
                is_tpu_backend,
            )

            drop_active = cfg.attn_dropout > 0.0 and not deterministic
            if drop_active and not (cfg.use_flash and is_tpu_backend()):
                raise NotImplementedError(
                    "attention-prob dropout under context_parallel MLA "
                    "requires the ring-flash path on real TPU (per-chunk "
                    "in-kernel masks); set attn_dropout=0.0 or use_flash"
                )
            c_kv = latent.astype(dt)[:, :, None, :]  # (B, S_loc, 1, L)
            if cfg.use_flash:
                kwargs = {}
                if drop_active:
                    kwargs = dict(
                        dropout_rate=cfg.attn_dropout,
                        dropout_seed=jax.random.randint(
                            self.make_rng("dropout"), (), 0,
                            jnp.iinfo(jnp.int32).max,
                        ),
                    )
                ctx = ring_flash_attention_local(
                    q_lat, c_kv, c_kv, "context", causal=True, scale=scale,
                    **kwargs,
                ).astype(dt)
            else:
                ctx = ring_attention_local(
                    q_lat, c_kv, c_kv, "context", causal=True, scale=scale
                ).astype(dt)
        elif cache is None and cfg.use_flash:
            # absorbed-query MLA *is* MQA over the latent stream: scores are
            # q_lat . c and the context is probs @ c, i.e. attention with
            # k = v = c and one shared kv head — so the Pallas flash kernel
            # serves MLA directly (head_dim = latent_dim), giving the
            # flagship family the same long-context memory profile as the
            # GQA models (no (S, S) probs in HBM). Cached decode keeps the
            # dense einsum path (per-step scores are (1, t), already small).
            from solvingpapers_tpu.models.layers import apply_flash_attention

            c_kv = latent.astype(dt)[:, :, None, :]  # (B, S, 1, L)
            ctx = apply_flash_attention(
                self, q_lat, c_kv, c_kv, causal=True, scale=scale,
                dropout_rate=cfg.attn_dropout, deterministic=deterministic,
            ).astype(dt)
        elif cache is not None and attend_len is not None:
            # PREFILL: this chunk occupies cache slots [attend_len - S,
            # attend_len) with every earlier slot written, so attention is
            # end-aligned causal over a STATIC slice of the latent cache —
            # no (S, max_len) score tensor (16k-prompt prefill fits HBM).
            cache = update_latent_cache(cache, latent, positions[0, 0])
            c_att = jax.lax.slice_in_dim(cache.c, 0, attend_len, axis=1)
            c_kv = c_att[:, :, None, :]  # (B, attend_len, 1, L[+R])
            if cfg.use_flash:
                from solvingpapers_tpu.models.layers import apply_flash_attention

                ctx = apply_flash_attention(
                    self, q_lat, c_kv, c_kv, causal=True, scale=scale,
                ).astype(dt)
            else:
                ctx = ops.dot_product_attention(
                    q_lat, c_kv, c_kv, causal=True, scale=scale
                ).astype(dt)
        else:
            if cache is not None:
                cache = update_latent_cache(cache, latent, positions[0, 0])
                c_full = cache.c
                kv_idx = jnp.arange(cache.max_len)
                mask = kv_idx[None, None, None, :] <= positions[:, None, :, None]
            else:
                c_full = latent
                q_idx = jnp.arange(s)
                mask = (q_idx[None, :, None] >= q_idx[None, None, :])[:, None]

            scores = (
                jnp.einsum("bsnl,btl->bnst", q_lat, c_full.astype(dt)).astype(
                    jnp.float32
                )
                * scale
            )
            scores = jnp.where(mask, scores, ops.attention.BIG_NEG)
            probs = jax.nn.softmax(scores, axis=-1)
            if cfg.attn_dropout > 0.0 and not deterministic:
                keep = jax.random.bernoulli(
                    self.make_rng("dropout"), 1.0 - cfg.attn_dropout, probs.shape
                )
                probs = probs * keep / (1.0 - cfg.attn_dropout)
            probs = probs.astype(dt)
            ctx = jnp.einsum("bnst,btl->bsnl", probs, c_full.astype(dt))

        if R:
            # the rotary tail of cat(latent, k_rope) is score-only; values
            # decompress from the latent part alone
            ctx = ctx[..., :lat]
        out = jnp.einsum("bsnl,lnh->bsnh", ctx, w_v.astype(dt))
        out = out.reshape(b, s, n * hd)
        out = nn.Dense(cfg.dim, use_bias=False, dtype=dt, name="out")(out)
        if cfg.attn_dropout > 0.0:
            out = nn.Dropout(cfg.attn_dropout)(out, deterministic=deterministic)
        return out, cache


class MoELayer(nn.Module):
    """Top-k MoE with shared expert and aux-free load balancing (cell 23).

    Expert weights are stacked (E, ...) arrays (SwiGLU per expert, cell 21:
    w3(swish(w1 x) * (w2 x)), hidden ((2D)*4)//3). The routing bias lives in
    the 'moe_state' variable collection — the functional analogue of the
    reference's registered buffer updated under no_grad; the train step
    threads it through TrainState.model_state.
    """

    cfg: DeepSeekV3Config

    @nn.compact
    def __call__(self, x, *, deterministic=True):
        cfg = self.cfg
        b, s, d = x.shape
        h = cfg.expert_hidden
        e = cfg.n_experts
        dt = cfg.compute_dtype
        xt = x.reshape(b * s, d).astype(dt)

        gate_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, name="gate"
        )(xt.astype(jnp.float32))
        if cfg.noisy_topk:
            # layer created unconditionally so init (deterministic) still
            # builds its params; noise applied only in train mode
            noise_scale = jax.nn.softplus(
                nn.Dense(e, use_bias=False, dtype=jnp.float32, name="noise")(
                    xt.astype(jnp.float32)
                )
            )
            if not deterministic:
                gate_logits = gate_logits + noise_scale * jax.random.normal(
                    self.make_rng("dropout"), gate_logits.shape
                )

        bias = self.variable(
            "moe_state", "routing_bias", lambda: jnp.zeros((e,), jnp.float32)
        )
        biased = gate_logits + bias.value if cfg.use_aux_free else gate_logits
        # reference detail: both selection AND softmax weights use the biased
        # logits (cell 23 scatters top_k_values of the biased tensor)
        probs = ops.moe.topk_gate_probs(biased, cfg.top_experts)

        init = nn.initializers.normal(0.02)
        w1 = self.param("w1", init, (e, d, h))
        w2 = self.param("w2", init, (e, d, h))
        w3 = self.param("w3", init, (e, h, d))

        # (probs, axes) the drop metric must count over — the a2a path
        # dispatches per-member token shards, so its drops are counted from
        # the shard's probs and psum'd over the expert axis too
        drop_probs = drop_axes = None

        if cfg.moe_impl == "dense":
            def expert_fn_all(xt):
                a = jnp.einsum("td,edh->eth", xt, w1.astype(dt))
                g = jnp.einsum("td,edh->eth", xt, w2.astype(dt))
                return jnp.einsum("eth,ehd->etd", ops.swish(a) * g, w3.astype(dt))

            out = ops.moe.moe_dense_combine(xt, probs, expert_fn_all)
        else:
            def expert_body(xe, w1s, w2s, w3s):  # (E', C, D) -> (E', C, D)
                a = jnp.einsum("ecd,edh->ech", xe, w1s)
                g = jnp.einsum("ecd,edh->ech", xe, w2s)
                return jnp.einsum("ech,ehd->ecd", ops.swish(a) * g, w3s)

            def expert_fn(xe):  # (E, C, D) -> (E, C, D)
                return expert_body(
                    xe, w1.astype(dt), w2.astype(dt), w3.astype(dt)
                )

            # under CP/shard_map b*s is the LOCAL token count, so capacity
            # is per-shard — the standard distributed-MoE dispatch
            # semantics. Parity with the dense single-device step is exact
            # in the drop-free regime; once capacity binds, drops are
            # decided per shard rather than globally (watch
            # moe_drop_fraction, psum'd across shards).
            cap = ops.moe.expert_capacity(
                b * s, e, cfg.top_experts, cfg.capacity_factor
            )
            if cfg.context_parallel:
                # inside the CP shard_map the 'expert' mesh axis shards
                # expert COMPUTE, not just storage: the in-step ZeRO gather
                # hands every member the full (E, ...) stacks, but each
                # member dispatches only its E/ep expert columns against its
                # own slice and the partial combines psum over the axis
                # (ops.moe.moe_expert_sliced_combine). With ep == 1 the
                # slice is the whole stack and this is exactly the line
                # above. probs stay replicated over 'expert' (gate weights
                # are), so slot assignment per column matches unsharded.
                def expert_fn_sliced(xe, start):  # (E/ep, C, D), first idx
                    sl = lambda w: jax.lax.dynamic_slice_in_dim(  # noqa: E731
                        w.astype(dt), start, xe.shape[0], 0
                    )
                    return expert_body(xe, sl(w1), sl(w2), sl(w3))

                if cfg.ep_impl == "all_to_all":
                    # token-dispatch EP: the gate ran on the full replicated
                    # tokens (cheap, and keeps probs identical across the
                    # axis for the stats below); dispatch/expert/combine run
                    # on this member's 1/ep token slice with tokens moved by
                    # all_to_all, then an all_gather restores the
                    # replicated-token contract for the residual stream.
                    ep = jax.lax.psum(1, "expert")
                    tl = (b * s) // ep
                    if (b * s) % ep:
                        raise ValueError(
                            f"{b * s} local tokens not divisible by the "
                            f"'expert' axis ({ep}) for ep_impl=all_to_all"
                        )
                    idx = jax.lax.axis_index("expert")
                    x_sh = jax.lax.dynamic_slice_in_dim(xt, idx * tl, tl, 0)
                    p_sh = jax.lax.dynamic_slice_in_dim(probs, idx * tl, tl, 0)
                    cap = ops.moe.expert_capacity(
                        tl, e, cfg.top_experts, cfg.capacity_factor
                    )
                    out = ops.moe.moe_all_to_all_combine(
                        x_sh, p_sh, expert_fn_sliced, cap, axis_name="expert"
                    )
                    out = jax.lax.all_gather(out, "expert", axis=0, tiled=True)
                    drop_probs, drop_axes = p_sh, (
                        tuple(cfg.stats_axes) + ("expert",)
                    )
                else:
                    out = ops.moe.moe_expert_sliced_combine(
                        xt, probs, expert_fn_sliced, cap, axis_name="expert"
                    )
            else:
                out = ops.moe.moe_dispatch_combine(xt, probs, expert_fn, cap)

        if cfg.use_shared_expert:
            out = out + GLUFFN(
                dim=d, hidden_dim=h, activation=ops.swish, dtype=dt,
                name="shared_expert",
            )(xt)

        # one load reduction (+ one cross-shard collective under CP) shared
        # by the bias update and the sown stats. probs_g: along a ZeRO'd
        # 'expert' axis every member holds identical probs (tokens are
        # replicated across it) but the vma types them varying after the
        # gathered expert weights touch the residual stream — the pmean is
        # a numeric no-op that certifies the invariant-state contract.
        probs_g = (
            jax.lax.pmean(probs, "expert") if cfg.stats_axes is not None
            else probs
        )
        ci = None
        if (
            cfg.use_aux_free
            and not deterministic
            and self.is_mutable_collection("moe_state")
        ):
            # stats_axes: under shard_map the load is psum'd so every shard
            # applies the identical bias update (shard-invariant state)
            ci = ops.moe.expert_load(probs_g, cfg.stats_axes)
            bias.value = ops.moe.aux_free_bias_update(
                probs_g, bias.value, cfg.aux_free_bias_update_rate, ci=ci
            )

        if (
            cfg.balance_loss_weight > 0.0
            and self.is_mutable_collection("moe_metrics")
        ):
            # sequence-wise balance loss (differentiable — NOT under the
            # stop_gradient the stats below use): f_e = selection fraction
            # scaled by E/k, P_e = mean softmax gate prob over ALL experts.
            # dsv3_loss_fn reads the sown value and adds weight * mean.
            sel_frac = jnp.mean((probs > 0.0).astype(jnp.float32), axis=0)
            f = sel_frac * (e / cfg.top_experts)
            p_full = jnp.mean(
                jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1),
                axis=0,
            )
            self.sow("moe_metrics", "balance_loss", jnp.sum(f * p_full))

        if self.is_mutable_collection("moe_metrics"):
            # load-balance observability (SURVEY.md hard part #1): sown per
            # layer, aggregated into train metrics by dsv3_loss_fn
            if ci is None:
                ci = ops.moe.expert_load(probs_g, cfg.stats_axes)
            stats = ops.moe.load_balance_stats(
                probs_g, axis_names=cfg.stats_axes, ci=ci
            )
            # raw (E,) routed load: consumers that must re-derive the
            # aux-free bias update OUTSIDE the layer (the pipeline-parallel
            # wrapper, where the in-layer update can't run because the
            # GPipe stage_fn applies layers immutably) read it from here;
            # _aggregate_moe_metrics skips it (vector, not a train scalar)
            stats["ci"] = ci
            stats["drop_fraction"] = (
                jnp.zeros(()) if cfg.moe_impl == "dense"
                else ops.moe.dispatch_drop_fraction(
                    probs_g if drop_probs is None else drop_probs,
                    cap,
                    axis_names=(
                        cfg.stats_axes if drop_probs is None else drop_axes
                    ),
                )
            )
            stats["bias_norm"] = jnp.linalg.norm(bias.value)
            self.sow("moe_metrics", "stats", stats)
        return out.reshape(b, s, d).astype(x.dtype)


class DSV3DecoderLayer(nn.Module):
    """Pre-RMSNorm MLA + residual; pre-RMSNorm MoE + residual (cell 29)."""

    cfg: DeepSeekV3Config

    @nn.compact
    def __call__(self, x, positions=None, cache=None, deterministic=True,
                 attend_len=None):
        cfg = self.cfg
        h, cache = MLA(cfg, name="mla")(
            RMSNorm(eps=cfg.norm_eps, name="norm1")(x),
            positions=positions,
            cache=cache,
            deterministic=deterministic,
            attend_len=attend_len,
        )
        x = x + h
        x = x + MoELayer(cfg, name="moe")(
            RMSNorm(eps=cfg.norm_eps, name="norm2")(x),
            deterministic=deterministic,
        )
        return x, cache


class DeepSeekV3(nn.Module):
    cfg: DeepSeekV3Config

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: jax.Array | None = None,
        caches: list[LatentCache] | None = None,
        deterministic: bool = True,
        return_mtp: bool = False,
        attend_len: int | None = None,
        return_hidden: bool = False,
    ):
        """Returns (logits, caches) or ((logits, mtp_logits), caches) when
        return_mtp=True and mtp_heads > 0 (mtp_logits: (B, T, K, V)).
        return_hidden: return ((logits, hidden), caches) with the post-
        norm_f hidden stream — the MTP draft head's input during
        speculative decoding (infer/speculative.py)."""
        cfg = self.cfg
        if return_hidden and return_mtp and cfg.mtp_heads > 0:
            # the two returns share an unpack shape ((logits, X), caches),
            # so allowing both would silently hand mtp_logits to a caller
            # expecting the hidden stream
            raise ValueError("return_hidden and return_mtp are mutually exclusive")
        b, s = tokens.shape
        if positions is None:
            from solvingpapers_tpu.models.layers import default_positions

            # max_positions: the sinusoidal table length (same silent-clamp
            # hazard as a learned table)
            positions = default_positions(
                b, s, cfg.context_parallel, max_positions=cfg.block_size
            )
        embed = nn.Embed(
            cfg.vocab_size, cfg.dim, dtype=cfg.compute_dtype,
            embedding_init=nn.initializers.normal(0.02), name="tok_emb",
        )
        pe = ops.sinusoidal_position_encoding(cfg.block_size, cfg.dim)
        # no input dropout: the reference's forward goes embedding -> PE ->
        # decoder directly (cell 33); dropout appears only after the layer
        # stack (cell 31)
        x = embed(tokens) + cfg.pe_scale * jnp.take(pe, positions, axis=0).astype(
            cfg.compute_dtype
        )

        new_caches = [] if caches is not None else None
        layer_cls = maybe_remat(DSV3DecoderLayer, cfg.remat, caches)
        for i in range(cfg.n_layers):
            x, c = layer_cls(cfg, name=f"layer_{i}")(
                x,
                positions,
                None if caches is None else caches[i],
                deterministic,
                attend_len,
            )
            if new_caches is not None:
                new_caches.append(c)

        if cfg.dropout > 0.0:
            x = nn.Dropout(cfg.dropout)(x, deterministic=deterministic)
        x = 2.0 * cfg.n_layers**-0.5 * x  # deepseek depth scaling (cell 31)
        x = RMSNorm(eps=cfg.norm_eps, name="norm_f")(x)
        logits = embed.attend(x.astype(cfg.compute_dtype))  # weight-tied head

        if not (return_mtp and cfg.mtp_heads > 0):
            if return_hidden:
                return (logits, x), new_caches
            return logits, new_caches

        # ---- MTP: vectorized version of cell 33's per-position loop ----
        # TWIN of DSV3Pipe.apply's functional MTP branch: changes here must
        # be mirrored there (test_dsv3_pipe_mtp_export_matches_dense_family
        # pins the equality).
        mtp_logits = []
        h_prev = x
        for k in range(1, cfg.mtp_heads + 1):
            # embedding of token at position i+k (zero-padded past the end;
            # the loss masks those targets out). Under CP the shift crosses
            # shard boundaries: a k-token halo from the right neighbor
            # (ppermute) makes it local — same global stream, shard-local
            # view (sharding.cp_halo_right)
            if cfg.context_parallel:
                from solvingpapers_tpu.sharding import cp_shift_left

                shifted = cp_shift_left(tokens, k, fill=0)
            else:
                shifted = jnp.pad(tokens[:, k:], ((0, 0), (0, k)))
            emb_k = embed(shifted)
            merged = jnp.concatenate(
                [
                    LayerNorm(name=f"mtp_norm_h_{k}")(h_prev),
                    LayerNorm(name=f"mtp_norm_e_{k}")(emb_k),
                ],
                axis=-1,
            )
            merged = nn.Dense(
                cfg.dim, use_bias=False, dtype=cfg.compute_dtype,
                name=f"mtp_merge_{k}",
            )(merged)
            h_k, _ = DSV3DecoderLayer(cfg, name=f"mtp_layer_{k}")(
                merged, positions=positions, deterministic=deterministic
            )
            proj = nn.Dense(
                cfg.dim, use_bias=False, dtype=cfg.compute_dtype,
                name=f"mtp_proj_{k}",
            )(h_k)
            mtp_logits.append(embed.attend(proj.astype(cfg.compute_dtype)))
            h_prev = h_k
        return (logits, jnp.stack(mtp_logits, axis=2)), new_caches

    @property
    def max_positions(self) -> int:
        return self.cfg.block_size

    def init_caches(self, batch: int, max_len: int, dtype=None) -> list[LatentCache]:
        cfg = self.cfg
        dtype = dtype or cfg.compute_dtype
        return [
            # the cache row is cat(latent, k_rope) when the decoupled-RoPE
            # branch is on (MLA concatenates before the cache update)
            LatentCache.init(batch, max_len, cfg.latent_dim + cfg.rope_dim, dtype)
            for _ in range(cfg.n_layers)
        ]

    def init_cp_caches(
        self, batch: int, prompt_local: int, tail_len: int, dtype=None
    ) -> list[CPLatentCache]:
        """Context-sharded decode caches (one per layer): `prompt_local` is
        the per-shard prompt chunk length (global prompt / context axis),
        `tail_len` the decode budget (replicated)."""
        cfg = self.cfg
        dtype = dtype or cfg.compute_dtype
        return [
            CPLatentCache.init(
                batch, prompt_local, tail_len,
                cfg.latent_dim + cfg.rope_dim, dtype,
            )
            for _ in range(cfg.n_layers)
        ]


def mtp_head_apply(cfg, params, moe_state, h, next_tokens, positions,
                   cache=None, attend_len=None, head=1, rngs=None,
                   collect_stats=False):
    """One MTP head applied functionally from the param dict — the ONE
    functional form of DeepSeekV3.__call__'s flax-module MTP branch (that
    branch is the only other copy; the module/functional boundary keeps
    them separate). Used by the staged family's training branch
    (models/deepseekv3_pipe.py, with `collect_stats`/`rngs`) and by
    speculative decoding (infer/speculative.py, with `cache`): merged =
    merge([norm(h), norm(emb of the NEXT token)]) -> mtp_layer (optionally
    with its OWN latent cache: at decode the head is a little
    autoregressive model over merged reps) -> proj -> tied head.

    h: (B, S, D) post-norm_f hiddens at `positions` (the previous head's
    output when chaining heads); next_tokens: (B, S) the token at
    position+head for each column. Returns (logits, y, cache, stats) —
    logits[:, i] predicts the token at positions[:, i] + head + 1, y is
    the head layer's hidden (the next head's h), stats the layer's sown
    MoE stats dict when collect_stats else None.
    """
    from solvingpapers_tpu.models.layers import LayerNorm

    dt = cfg.compute_dtype
    emb_table = params["tok_emb"]["embedding"]
    emb = jnp.take(emb_table, next_tokens, axis=0).astype(dt)
    merged = jnp.concatenate(
        [
            LayerNorm().apply({"params": params[f"mtp_norm_h_{head}"]}, h),
            LayerNorm().apply({"params": params[f"mtp_norm_e_{head}"]}, emb),
        ],
        axis=-1,
    ).astype(dt)
    merged = merged @ params[f"mtp_merge_{head}"]["kernel"].astype(dt)
    variables = {
        "params": params[f"mtp_layer_{head}"],
        "moe_state": moe_state[f"mtp_layer_{head}"],
    }
    det = rngs is None
    kwargs = {} if det else {"rngs": rngs}
    stats = None
    if collect_stats:
        (y, cache), mut = DSV3DecoderLayer(cfg).apply(
            variables, merged, positions, cache, det, attend_len,
            mutable=["moe_metrics"], **kwargs,
        )
        stats = mut["moe_metrics"]["moe"]["stats"][0]
    else:
        y, cache = DSV3DecoderLayer(cfg).apply(
            variables, merged, positions, cache, det, attend_len, **kwargs,
        )
    proj = y.astype(dt) @ params[f"mtp_proj_{head}"]["kernel"].astype(dt)
    return proj @ emb_table.T.astype(dt), y, cache, stats
