"""Pre-tokenized LM streams.

Capability target: deepseekv3/deepseekv3.ipynb cells 8-14 — the reference
tokenizes TinyStories once, saves tensors to disk, and trains from the
saved tokens (with a commented-out tokenize-to-disk pipeline). Here the
on-disk format is a flat uint16/uint32 `.bin` (memory-mapped, so corpora
larger than RAM stream from disk) or `.npy`.
"""

from __future__ import annotations

import os

import numpy as np


def tokenize_to_file(
    text: str, tokenizer, path: str, *, dtype=None
) -> np.ndarray:
    """Encode `text` and write a flat token file next to a .meta sidecar.

    dtype defaults to uint16 when the vocab fits (gpt2's 50257 does), else
    uint32. Returns the in-memory tokens.
    """
    ids = np.asarray(tokenizer.encode(text))
    if dtype is None:
        dtype = np.uint16 if tokenizer.vocab_size <= np.iinfo(np.uint16).max + 1 else np.uint32
    ids = ids.astype(dtype)
    if path.endswith(".npy"):
        np.save(path, ids)
    else:
        ids.tofile(path)
        max_id = int(ids.max()) if ids.size else -1
        with open(path + ".meta", "w") as f:
            # line 1: dtype; then key=value lines (max_id recorded at write
            # time so loads need not rescan multi-GB files)
            f.write(f"{np.dtype(dtype).name}\nmax_id={max_id}\n")
    return ids


def token_file_max_id(path: str, tokens: np.ndarray) -> int:
    """Largest token id: from the .meta sidecar when recorded, else one
    full pass over `tokens` (O(file size) for memmaps)."""
    meta = path + ".meta"
    if os.path.exists(meta):
        with open(meta) as f:
            for line in f.read().splitlines()[1:]:
                if line.startswith("max_id="):
                    return int(line.split("=", 1)[1])
    return int(np.max(tokens))


def load_token_file(path: str, *, dtype=None) -> np.ndarray:
    """Memory-map a token file written by tokenize_to_file (or any flat
    binary of the given dtype; .npy loads with mmap_mode)."""
    if path.endswith(".npy"):
        return np.load(path, mmap_mode="r")
    if dtype is None:
        meta = path + ".meta"
        if not os.path.exists(meta):
            raise ValueError(
                f"{path} has no .meta sidecar recording its dtype; pass "
                "dtype= explicitly (guessing would silently misparse uint32 "
                "token files as uint16 garbage)"
            )
        with open(meta) as f:
            dtype = np.dtype(f.read().splitlines()[0].strip())
    return np.memmap(path, dtype=dtype, mode="r")
