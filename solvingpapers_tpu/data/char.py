"""Character-level tokenization + corpus loading.

Capability target: the char vocab pipelines of gpt/gpt-jax.ipynb cell 6 and
gemma/gemma.ipynb cells 4-5 (sorted unique chars, stoi/itos maps, 90/10
train/val split).
"""

from __future__ import annotations

import os

import numpy as np

from solvingpapers_tpu.data.synthetic import synthetic_text


class CharTokenizer:
    def __init__(self, text: str):
        self.chars = sorted(set(text))
        self.stoi = {c: i for i, c in enumerate(self.chars)}
        self.itos = dict(enumerate(self.chars))

    @property
    def vocab_size(self) -> int:
        return len(self.chars)

    def encode(self, s: str) -> np.ndarray:
        return np.asarray([self.stoi[c] for c in s], dtype=np.int32)

    def decode(self, ids) -> str:
        return "".join(self.itos[int(i)] for i in ids)


def load_char_corpus(
    path: str | None = None,
    val_fraction: float = 0.1,
    synthetic_chars: int = 200_000,
    seed: int = 0,
) -> tuple[CharTokenizer, np.ndarray, np.ndarray]:
    """Load a text corpus (local file if given/exists, else synthetic),
    build a char vocab, return (tokenizer, train_tokens, val_tokens)."""
    if path is not None and os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    else:
        text = synthetic_text(synthetic_chars, seed)
    tok = CharTokenizer(text)
    data = tok.encode(text)
    n_val = int(len(data) * val_fraction)
    return tok, data[:-n_val], data[-n_val:]
