"""Character-level tokenization + corpus loading.

Capability target: the char vocab pipelines of gpt/gpt-jax.ipynb cell 6 and
gemma/gemma.ipynb cells 4-5 (sorted unique chars, stoi/itos maps, 90/10
train/val split).
"""

from __future__ import annotations

import os

import numpy as np

from solvingpapers_tpu.data.synthetic import synthetic_text


class CharTokenizer:
    def __init__(self, text: str):
        self.chars = sorted(set(text))
        self.stoi = {c: i for i, c in enumerate(self.chars)}
        self.itos = dict(enumerate(self.chars))

    @property
    def vocab_size(self) -> int:
        return len(self.chars)

    def encode(self, s: str) -> np.ndarray:
        return np.asarray([self.stoi[c] for c in s], dtype=np.int32)

    def decode(self, ids) -> str:
        return "".join(self.itos[int(i)] for i in ids)


def load_text(path: str | None = None, synthetic_chars: int = 200_000, seed: int = 0) -> str:
    """Raw corpus text: the local file if given/exists, else synthetic."""
    if path is not None and os.path.exists(path):
        with open(path, "r", encoding="utf-8") as f:
            return f.read()
    return synthetic_text(synthetic_chars, seed)


def split_train_val(
    data: np.ndarray, val_fraction: float = 0.1
) -> tuple[np.ndarray, np.ndarray]:
    """Tail split (gpt/gemma notebooks' 90/10 convention), at least 1 val token."""
    n_val = max(int(len(data) * val_fraction), 1)
    return data[:-n_val], data[-n_val:]


def load_char_corpus(
    path: str | None = None,
    val_fraction: float = 0.1,
    synthetic_chars: int = 200_000,
    seed: int = 0,
) -> tuple[CharTokenizer, np.ndarray, np.ndarray]:
    """Load a text corpus (local file if given/exists, else synthetic),
    build a char vocab, return (tokenizer, train_tokens, val_tokens)."""
    text = load_text(path, synthetic_chars, seed)
    tok = CharTokenizer(text)
    train, val = split_train_val(tok.encode(text), val_fraction)
    return tok, train, val
