"""Batch construction for LM training.

Two strategies from the reference, one implementation each:
  * random-crop batches (gpt cell 13 / llama3 cell 13 / gemma cell 5) —
    done device-side with vmap(dynamic_slice) like the llama3 notebook
    (its one genuinely TPU-friendly pipeline), not a python list-comp;
  * sliding-window split (deepseekv3 cells 12-14 `CausalDataset`).

Both are deterministic given the JAX PRNG key, which makes multi-host
sharding seed-stable (SURVEY.md hard part #6): each host derives its crops
from fold_in(key, host_id).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def random_crop_batch(
    tokens: jax.Array, rng: jax.Array, batch_size: int, block_size: int
) -> tuple[jax.Array, jax.Array]:
    """Sample `batch_size` random crops of length block_size+1; return (x, y)."""
    max_start = tokens.shape[0] - block_size - 1
    starts = jax.random.randint(rng, (batch_size,), 0, max_start)
    crop = jax.vmap(
        lambda s: jax.lax.dynamic_slice(tokens, (s,), (block_size + 1,))
    )(starts)
    return crop[:, :-1], crop[:, 1:]


def lm_batch_iterator(
    tokens: np.ndarray,
    batch_size: int,
    block_size: int,
    seed: int = 0,
    sharding=None,
):
    """Infinite iterator of {'x','y'} LM batches.

    Deterministic in `seed`; if `sharding` is given, batches are placed with
    it (data/fsdp mesh axes) before being yielded. In-memory corpora crop
    device-side under jit (llama3 cell 13's vmap(dynamic_slice) pattern);
    memory-mapped token files crop host-side so corpora larger than HBM
    stream from disk (only the cropped windows are copied to device).
    """
    if len(tokens) < block_size + 2:
        raise ValueError(
            f"corpus of {len(tokens)} tokens is too short for "
            f"block_size {block_size} (need >= block_size + 2)"
        )
    if isinstance(tokens, np.memmap):
        from solvingpapers_tpu import native

        rng = np.random.default_rng(seed)
        max_start = len(tokens) - block_size - 1
        use_native = (
            native.available()
            and np.dtype(tokens.dtype) in native._DTYPE_CODES
            and tokens.flags["C_CONTIGUOUS"]
        )
        while True:
            starts = rng.integers(0, max_start, size=batch_size)
            if use_native:
                # parallel C++ gather+widen (GIL released -> overlaps the
                # device step when wrapped in prefetch_batches)
                x, y = native.gather_windows_native(tokens, starts, block_size)
            else:
                x = np.stack(
                    [tokens[s : s + block_size] for s in starts]
                ).astype(np.int32)
                y = np.stack(
                    [tokens[s + 1 : s + block_size + 1] for s in starts]
                ).astype(np.int32)
            batch = {"x": x, "y": y}
            if sharding is not None:
                batch = jax.device_put(batch, sharding)
            yield batch

    toks = jnp.asarray(tokens)
    crop = jax.jit(random_crop_batch, static_argnames=("batch_size", "block_size"))
    key = jax.random.key(seed)
    i = 0
    while True:
        x, y = crop(toks, jax.random.fold_in(key, i), batch_size, block_size)
        batch = {"x": x, "y": y}
        if sharding is not None:
            batch = jax.device_put(batch, sharding)
        yield batch
        i += 1


def prefetch_batches(iterator, depth: int = 2):
    """Run `iterator` in a background thread, keeping up to `depth` batches
    ready — the TPU-native stand-in for the reference's 2-worker pinned
    DataLoaders (deepseekv3.ipynb cell 14). Host-side gathers (the memmap
    branch above, with its GIL-releasing native path) overlap the device
    step. Order is preserved, so determinism in `seed` is unchanged.
    """
    import queue
    import threading

    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in iterator:
                if not put(batch):
                    return
        except BaseException as e:  # surfaced to the consumer, not swallowed
            put(e)
            return
        put(_END)

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            batch = q.get()
            if batch is _END:
                return
            if isinstance(batch, BaseException):
                raise batch
            yield batch
    finally:
        stop.set()


def sliding_window_split(
    tokens: np.ndarray, block_size: int, stride: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Materialize (x, y) pairs with a sliding window (deepseekv3's
    CausalDataset uses stride 1; default here is block_size, the sane
    packing — pass stride=1 for reference-faithful behavior)."""
    stride = stride or block_size
    # last valid start s satisfies s + block_size + 1 <= len(tokens)
    starts = np.arange(0, len(tokens) - block_size, stride)
    x = np.stack([tokens[s : s + block_size] for s in starts])
    y = np.stack([tokens[s + 1 : s + block_size + 1] for s in starts])
    return x, y
