"""Image dataset loading + batch iteration.

Capability target: the torchvision MNIST loaders of ViT.ipynb cells 4/7,
autoencoder.ipynb cell 2 and kd.py:71-82. Zero-egress environment: a local
.npz (keys: images, labels) is used when provided; otherwise the seeded
synthetic MNIST-shaped set from data/synthetic.py (class-separable, so
accuracy targets remain meaningful).
"""

from __future__ import annotations

import os
from typing import Iterator

import jax
import numpy as np

from solvingpapers_tpu.data.synthetic import synthetic_images


def load_image_dataset(
    path: str | None = None,
    *,
    n_train: int = 8192,
    n_test: int = 2048,
    side: int = 28,
    n_classes: int = 10,
    seed: int = 0,
    source: str = "separable",
    snr: float = 2.8,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Returns (train_x, train_y, test_x, test_y); x is NHWC float32.

    source: "separable" = the class-separable grating set ([0,1] pixels,
    accuracy saturates at 1.0); "bayes" = the Gaussian set with an exactly
    computable Bayes-optimal accuracy < 1 (synthetic.GaussianImageSource —
    calibrated targets for the vision stack; pixels are unbounded floats).
    """
    if source == "bayes" and path is None:
        from solvingpapers_tpu.data.synthetic import GaussianImageSource

        src = GaussianImageSource(n_classes=n_classes, side=side, snr=snr,
                                  seed=seed + 7)
        train_x, train_y = src.sample(n_train, seed=0)
        test_x, test_y = src.sample(n_test, seed=1)
        return train_x, train_y, test_x, test_y
    if path is not None and os.path.exists(path):
        with np.load(path) as z:
            images = z["images"].astype(np.float32)
            labels = z["labels"].astype(np.int32)
        if images.ndim == 3:
            images = images[..., None]
        if images.max() > 1.5:
            images = images / 255.0
        if len(images) < 2:
            raise ValueError(f"dataset at {path} has {len(images)} images; need >= 2")
        n_test = max(1, min(n_test, len(images) // 5))
        split = len(images) - n_test
        return images[:split], labels[:split], images[split:], labels[split:]
    train_x, train_y = synthetic_images(n_train, side, n_classes, seed)
    test_x, test_y = synthetic_images(n_test, side, n_classes, seed + 1)
    return train_x, train_y, test_x, test_y


def image_batch_iterator(
    images: np.ndarray,
    labels: np.ndarray,
    batch_size: int,
    *,
    seed: int = 0,
    flatten: bool = False,
    mesh=None,
    loop: bool = True,
) -> Iterator[dict]:
    """Yields {'x': images, 'y': labels} with per-epoch reshuffling.

    flatten=True reshapes x to (B, H*W*C) for the MLP/AE families.
    `mesh` device-puts batches sharded over the (data, fsdp) axes; x and y
    get rank-appropriate specs (x is 2-D or 4-D, y is 1-D).
    """
    n = len(images)
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > dataset size {n}")
    batch_shardings = None
    if mesh is not None:
        from solvingpapers_tpu.sharding.mesh import batch_sharding

        x_dims = 1 if flatten else images.ndim - 1
        batch_shardings = {
            "x": batch_sharding(mesh, x_dims),
            "y": batch_sharding(mesh, 0),
        }
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n)
        for start in range(0, n - batch_size + 1, batch_size):
            idx = order[start : start + batch_size]
            x = images[idx]
            if flatten:
                x = x.reshape(len(idx), -1)
            batch = {"x": x, "y": labels[idx]}
            if batch_shardings is not None:
                batch = jax.device_put(batch, batch_shardings)
            yield batch
        if not loop:
            return
