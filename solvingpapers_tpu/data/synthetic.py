"""Deterministic synthetic datasets for zero-egress environments.

The reference fetches Tiny-Shakespeare by URL (gpt/gpt-jax.ipynb cell 4,
gemma/gemma.ipynb cell 4) and MNIST via torchvision. This environment has
no network egress, so every data module falls back to seeded synthetic data
with the same shapes/statistics; real files are used when a local path is
supplied.
"""

from __future__ import annotations

import functools
import string

import numpy as np

_WORDS = (
    "the quick brown fox jumps over lazy dog when winter comes to verona "
    "and all our yesterdays have lighted fools the way to dusty death out "
    "brief candle life is but walking shadow a poor player that struts and "
    "frets his hour upon the stage and then is heard no more it is a tale "
    "told by an idiot full of sound and fury signifying nothing my lord "
    "what say you to this most noble friend shall we proceed anon good sir"
).split()


def synthetic_text(n_chars: int = 200_000, seed: int = 0) -> str:
    """Pseudo-prose with word/sentence structure (learnable char statistics)."""
    rng = np.random.default_rng(seed)
    out: list[str] = []
    total = 0
    while total < n_chars:
        sent_len = int(rng.integers(4, 12))
        words = rng.choice(_WORDS, size=sent_len)
        sent = " ".join(words).capitalize() + ". "
        if rng.random() < 0.1:
            sent = "\n" + sent
        out.append(sent)
        total += len(sent)
    return "".join(out)[:n_chars]


class MarkovSource:
    """Seeded order-k Markov chain over a printable alphabet with an exactly
    computable per-token entropy rate (nats).

    Purpose: give held-out loss an ABSOLUTE target in a zero-egress
    environment (the verification contract of SURVEY.md §4 items 1-2 — the
    reference validates against real Shakespeare/TinyStories val losses,
    gpt/gpt-jax.ipynb cell 18). The chain's entropy rate
    ``H = sum_s pi(s) * H(T[s, :])`` is the information-theoretic floor for
    per-token cross-entropy on held-out text: an ideal order-k model attains
    exactly H, while a model that memorizes the training stream stays near
    the unconditional entropy (~ln(vocab)) on validation. ``val_loss - H``
    is therefore a calibrated generalization gap that table lookup cannot
    fake.

    Transitions are Dirichlet(alpha) draws per state — ``alpha`` tunes the
    entropy rate (smaller = peakier = lower H). Everything is derived from
    the seed; the same (vocab, order, alpha, seed) always yields the same
    chain, so entropy numbers are comparable across rounds.
    """

    def __init__(self, vocab: int = 64, order: int = 2, alpha: float = 0.1,
                 seed: int = 1234):
        if not (2 <= vocab <= 64):
            raise ValueError(f"vocab must be in [2, 64], got {vocab}")
        self.vocab = vocab
        self.order = order
        self.alpha = alpha
        self.seed = seed
        # 64 distinct printable symbols, no regex/JSON metacharacters
        self.alphabet = (string.ascii_lowercase + string.ascii_uppercase
                         + string.digits + " .")[:vocab]
        self.n_states = vocab ** order
        rng = np.random.default_rng(seed)
        # (S, V) conditional distributions; float64 so entropy sums are exact
        self.T = rng.dirichlet(np.full(vocab, alpha), size=self.n_states)

    @functools.cached_property
    def stationary(self) -> np.ndarray:
        """Stationary distribution over order-k states (power iteration).

        State s = last k symbols; emitting c moves s -> (s mod V^(k-1))*V + c.
        """
        V, S = self.vocab, self.n_states
        target = (np.arange(S)[:, None] % (S // V)) * V + np.arange(V)[None, :]
        pi = np.full(S, 1.0 / S)
        for _ in range(500):
            nxt = np.bincount(target.ravel(), weights=(pi[:, None] * self.T).ravel(),
                              minlength=S)
            if np.abs(nxt - pi).sum() < 1e-13:
                pi = nxt
                break
            pi = nxt
        return pi / pi.sum()

    @functools.cached_property
    def entropy_rate_nats(self) -> float:
        """Exact per-token conditional entropy H(X_t | last k symbols), nats."""
        Hs = -np.sum(np.where(self.T > 0, self.T * np.log(self.T), 0.0), axis=1)
        return float(self.stationary @ Hs)

    @classmethod
    def from_config(cls, data_cfg: dict) -> "MarkovSource":
        """The single source of chain hyperparameter defaults — used by both
        the data factory (corpus construction) and markov_entropy_nats (the
        gating floor), so the trained-on chain and the entropy target can
        never drift apart. Returns a cached instance per parameter tuple
        (the Dirichlet draw + power iteration are worth building once)."""
        return _cached_source(
            data_cfg.get("markov_vocab", 64),
            data_cfg.get("markov_order", 2),
            data_cfg.get("markov_alpha", 0.1),
            data_cfg.get("markov_seed", 1234),
        )

    def sample(self, n_chars: int, seed: int = 0) -> str:
        """Draw n_chars symbols; start state from the stationary distribution."""
        V = self.vocab
        rng = np.random.default_rng((self.seed, seed))
        cdf = np.cumsum(self.T, axis=1)
        cdf[:, -1] = 1.0  # guard fp round-off at the tail
        state = int(rng.choice(self.n_states, p=self.stationary))
        u = rng.random(n_chars)
        wrap = self.n_states // V
        out = np.empty(n_chars, np.int64)
        for i in range(n_chars):
            c = int(np.searchsorted(cdf[state], u[i], side="right"))
            out[i] = c
            state = (state % wrap) * V + c
        syms = np.frombuffer(self.alphabet.encode(), np.uint8)
        return syms[out].tobytes().decode()


@functools.lru_cache(maxsize=4)
def _cached_source(vocab: int, order: int, alpha: float, seed: int) -> MarkovSource:
    return MarkovSource(vocab=vocab, order=order, alpha=alpha, seed=seed)


def markov_entropy_nats(data_cfg: dict) -> float:
    """Entropy rate for a ``{"source": "markov", ...}`` data config — the
    absolute val-loss target its corpus carries."""
    return MarkovSource.from_config(data_cfg).entropy_rate_nats


@functools.lru_cache(maxsize=4)
def _sample_cached(vocab: int, order: int, alpha: float, seed: int,
                   n_chars: int, sample_seed: int) -> str:
    # value-tuple key (not source identity): entries stay reachable even
    # after the source instance is evicted from _cached_source
    return _cached_source(vocab, order, alpha, seed).sample(
        n_chars, seed=sample_seed
    )


def markov_text(data_cfg: dict) -> str:
    """Corpus text for a markov data config. Cached: the parity suite's four
    LM rows share one pinned chain, and the sequential sampler is a
    per-character Python loop (~10s per 4M chars) worth running once."""
    src = MarkovSource.from_config(data_cfg)
    return _sample_cached(
        src.vocab, src.order, src.alpha, src.seed,
        data_cfg.get("n_chars", 1_000_000), data_cfg.get("sample_seed", 0),
    )


class GaussianImageSource:
    """Class-conditional Gaussian image set with an exactly computable
    Bayes-optimal accuracy < 1 — the Markov corpus idea (absolute targets
    for held-out metrics) applied to the vision stack.

    Class c's mean image is ``0.5 + snr * e_c`` with ``{e_c}`` orthonormal
    2-D DCT patterns; samples add iid N(0, 1) per-pixel noise (pixel values
    are unbounded floats — clipping would break the Gaussian geometry).
    With orthonormal means the Bayes rule is the matched filter
    ``argmax_c <x - 0.5, e_c>`` and, writing z_c = <eps, e_c> ~ iid N(0,1),
    a class-0 sample classifies correctly iff z_c < z_0 + snr for all c —
    so the Bayes accuracy reduces to the 1-D integral

        P* = E_z[ Phi(z + snr)^(K-1) ]

    evaluated numerically to machine precision. ``bayes_accuracy`` is an
    absolute ceiling no model can beat (up to test-set sampling noise) and
    a calibrated target a good model should approach; the saturating
    separable set (synthetic_images) can't fail for that reason.
    """

    def __init__(self, n_classes: int = 10, side: int = 28,
                 snr: float = 2.8, seed: int = 7):
        self.n_classes = n_classes
        self.side = side
        self.snr = snr
        self.seed = seed
        # orthonormal DCT-II product patterns, skipping the DC term so
        # every mean is zero-sum (brightness carries no label signal)
        pats = []
        u = (np.arange(side) + 0.5) / side
        k = 1
        while len(pats) < n_classes:
            p, q = k % (side - 1) + 1, k // (side - 1)
            pat = np.outer(np.cos(np.pi * q * u), np.cos(np.pi * p * u))
            pats.append(pat / np.linalg.norm(pat))
            k += 1
        self.means = np.stack(pats).astype(np.float64)  # (K, side, side)

    @functools.cached_property
    def bayes_accuracy(self) -> float:
        """P* = ∫ phi(z) Phi(z + snr)^(K-1) dz on a fine grid (the tails
        beyond |z| = 8 contribute < 1e-15)."""
        from math import erf

        z = np.linspace(-8.0, 8.0, 160_001)
        phi = np.exp(-0.5 * z**2) / np.sqrt(2 * np.pi)
        Phi = 0.5 * (1.0 + np.vectorize(erf)((z + self.snr) / np.sqrt(2.0)))
        trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2
        return float(trapezoid(phi * Phi ** (self.n_classes - 1), z))

    def sample(self, n: int, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """(images (n, side, side, 1) float32, labels (n,) int32)."""
        rng = np.random.default_rng((self.seed, seed))
        labels = rng.integers(0, self.n_classes, size=n).astype(np.int32)
        eps = rng.standard_normal((n, self.side, self.side))
        x = 0.5 + self.snr * self.means[labels] + eps
        return x[..., None].astype(np.float32), labels

    def matched_filter_accuracy(self, images: np.ndarray,
                                labels: np.ndarray) -> float:
        """Accuracy of the Bayes rule itself on a finite sample — the
        empirical check that bayes_accuracy describes this data."""
        flat = (images[..., 0].astype(np.float64) - 0.5).reshape(len(images), -1)
        scores = flat @ self.means.reshape(self.n_classes, -1).T
        return float(np.mean(np.argmax(scores, axis=1) == labels))


def synthetic_images(
    n: int = 2048, side: int = 28, n_classes: int = 10, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped synthetic classification set: class-dependent blob patterns.

    Returns (images (n, side, side, 1) float32 in [0,1], labels (n,) int32).
    Classes are separable (distinct frequency/phase gratings + noise) so
    accuracy-style smoke tests can actually learn.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, size=n).astype(np.int32)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    images = np.empty((n, side, side, 1), np.float32)
    for c in range(n_classes):
        freq = 1.0 + c // 2
        phase = (c % 2) * np.pi / 2
        base = 0.5 + 0.5 * np.sin(2 * np.pi * freq * (xx * ((c % 3) + 1) + yy) + phase)
        idx = labels == c
        noise = rng.normal(0, 0.15, size=(idx.sum(), side, side))
        images[idx, :, :, 0] = np.clip(base[None] + noise, 0, 1)
    return images, labels
