"""Deterministic synthetic datasets for zero-egress environments.

The reference fetches Tiny-Shakespeare by URL (gpt/gpt-jax.ipynb cell 4,
gemma/gemma.ipynb cell 4) and MNIST via torchvision. This environment has
no network egress, so every data module falls back to seeded synthetic data
with the same shapes/statistics; real files are used when a local path is
supplied.
"""

from __future__ import annotations

import numpy as np

_WORDS = (
    "the quick brown fox jumps over lazy dog when winter comes to verona "
    "and all our yesterdays have lighted fools the way to dusty death out "
    "brief candle life is but walking shadow a poor player that struts and "
    "frets his hour upon the stage and then is heard no more it is a tale "
    "told by an idiot full of sound and fury signifying nothing my lord "
    "what say you to this most noble friend shall we proceed anon good sir"
).split()


def synthetic_text(n_chars: int = 200_000, seed: int = 0) -> str:
    """Pseudo-prose with word/sentence structure (learnable char statistics)."""
    rng = np.random.default_rng(seed)
    out: list[str] = []
    total = 0
    while total < n_chars:
        sent_len = int(rng.integers(4, 12))
        words = rng.choice(_WORDS, size=sent_len)
        sent = " ".join(words).capitalize() + ". "
        if rng.random() < 0.1:
            sent = "\n" + sent
        out.append(sent)
        total += len(sent)
    return "".join(out)[:n_chars]


def synthetic_images(
    n: int = 2048, side: int = 28, n_classes: int = 10, seed: int = 0
) -> tuple[np.ndarray, np.ndarray]:
    """MNIST-shaped synthetic classification set: class-dependent blob patterns.

    Returns (images (n, side, side, 1) float32 in [0,1], labels (n,) int32).
    Classes are separable (distinct frequency/phase gratings + noise) so
    accuracy-style smoke tests can actually learn.
    """
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, size=n).astype(np.int32)
    yy, xx = np.mgrid[0:side, 0:side].astype(np.float32) / side
    images = np.empty((n, side, side, 1), np.float32)
    for c in range(n_classes):
        freq = 1.0 + c // 2
        phase = (c % 2) * np.pi / 2
        base = 0.5 + 0.5 * np.sin(2 * np.pi * freq * (xx * ((c % 3) + 1) + yy) + phase)
        idx = labels == c
        noise = rng.normal(0, 0.15, size=(idx.sum(), side, side))
        images[idx, :, :, 0] = np.clip(base[None] + noise, 0, 1)
    return images, labels
