"""Data pipelines (L4): tokenizers, LM streams, image datasets, sharded batches."""

from solvingpapers_tpu.data.char import CharTokenizer, load_char_corpus
from solvingpapers_tpu.data.batches import (
    prefetch_batches,
    random_crop_batch,
    sliding_window_split,
)
from solvingpapers_tpu.data.synthetic import synthetic_text, synthetic_images
from solvingpapers_tpu.data.bpe import ByteBPETokenizer, gpt2_tokenizer
from solvingpapers_tpu.data.tokens import load_token_file, tokenize_to_file
