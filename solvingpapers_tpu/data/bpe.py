"""Byte-level BPE tokenization.

Capability target: the reference's subword pipelines — tiktoken GPT-2 in
llama3 (LLaMA-jax.ipynb cell 6) and HF AutoTokenizer('gpt2') in deepseekv3
(deepseekv3.ipynb cell 6, vocab 50257). This environment has no network
egress (both libraries fetch their BPE tables on first use), so this module
provides a self-contained byte-level BPE with three sources:

  1. `ByteBPETokenizer.train(text, vocab_size)` — learn merges from a local
     corpus (classic BPE: iteratively merge the most frequent symbol pair);
  2. `ByteBPETokenizer.from_files(vocab.json, merges.txt)` — load GPT-2
     format tables if the user has them locally;
  3. `gpt2_tokenizer()` — best-effort tiktoken / HF fast paths when their
     caches exist, else a clear error.

Byte-level means no <unk>: any UTF-8 string round-trips exactly.
"""

from __future__ import annotations

import json
from collections import Counter

import numpy as np

try:  # stdlib `re` lacks \p{L}; `regex` ships with transformers
    import regex as _re

    _GPT2_SPLIT = _re.compile(
        r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
    )
except ImportError:  # pragma: no cover
    import re as _re

    _GPT2_SPLIT = _re.compile(r" ?\w+| ?[^\w\s]+|\s+")


def bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte <-> printable-unicode mapping (so merges
    files are text-safe). Standard table: printable ASCII + latin-1 ranges
    stay themselves; the rest shift up past 255."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_BYTE_ENC = bytes_to_unicode()
_BYTE_DEC = {v: k for k, v in _BYTE_ENC.items()}


def _get_pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return set(zip(word[:-1], word[1:]))


def _best_pair(pairs: Counter, vocab: dict[str, int]):
    """Canonical best pair: max count, tie-break smallest (left, right) id."""
    best = max(
        pairs.items(),
        key=lambda kv: (kv[1], -vocab[kv[0][0]], -vocab[kv[0][1]]),
    )
    return best[0], best[1]


class ByteBPETokenizer:
    def __init__(self, vocab: dict[str, int], merges: list[tuple[str, str]]):
        self.vocab = vocab
        self.inv_vocab = {v: k for k, v in vocab.items()}
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        self._cache: dict[str, list[str]] = {}
        self._native = None       # lazily-built native encoder (or False)

    def _native_encoder(self):
        """Native merge-loop encoder in vocab-id space, if buildable."""
        if self._native is None:
            try:
                from solvingpapers_tpu import native

                if not native.available():
                    raise RuntimeError(native.load_error() or "unavailable")
                byte_to_id = np.asarray(
                    [self.vocab[_BYTE_ENC[b]] for b in range(256)], np.int32
                )
                merges = np.asarray(
                    [
                        (self.vocab[a], self.vocab[b], self.vocab[a + b])
                        for (a, b) in sorted(self.ranks, key=self.ranks.get)
                    ],
                    np.int32,
                ).reshape(-1, 3)
                self._native = native.NativeBpeEncoder(byte_to_id, merges)
            except (RuntimeError, KeyError, OSError):
                self._native = False
        return self._native or None

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    # ------------------------------------------------------------ construct

    @classmethod
    def from_files(cls, vocab_path: str, merges_path: str) -> "ByteBPETokenizer":
        """Load GPT-2-format vocab.json + merges.txt."""
        with open(vocab_path, encoding="utf-8") as f:
            vocab = json.load(f)
        merges = []
        with open(merges_path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.rstrip("\n")
                # only the optional '#version' header is metadata — '#' is a
                # legitimate merge symbol (e.g. GPT-2's '# #' -> '##')
                if not line.strip() or (i == 0 and line.startswith("#version")):
                    continue
                a, b = line.split(" ")
                merges.append((a, b))
        return cls(vocab, merges)

    @classmethod
    def train(
        cls, text: str, vocab_size: int, *, min_pair_count: int = 2
    ) -> "ByteBPETokenizer":
        """Learn merges from `text` until `vocab_size` (>= 256) is reached.

        Best pair per round: max count, tie-break smallest (left_id,
        right_id) — the canonical order shared with the native trainer, so
        both produce identical tables. The native incremental trainer
        (native/_src/native.cpp) is used when available; this Python loop
        is the fallback and the parity oracle.
        """
        if vocab_size < 256:
            raise ValueError("byte-level BPE needs vocab_size >= 256")
        # word frequency over pre-tokenized chunks, as byte-unicode symbols
        words = Counter(
            tuple(_BYTE_ENC[b] for b in tok.encode("utf-8"))
            for tok in _GPT2_SPLIT.findall(text)
        )
        vocab = {c: i for i, c in enumerate(_BYTE_ENC[b] for b in range(256))}
        native_tok = cls._train_native(words, vocab, vocab_size, min_pair_count)
        if native_tok is not None:
            return native_tok
        merges: list[tuple[str, str]] = []
        while len(vocab) < vocab_size:
            pairs: Counter = Counter()
            for word, freq in words.items():
                for pair in zip(word[:-1], word[1:]):
                    pairs[pair] += freq
            if not pairs:
                break
            best, count = _best_pair(pairs, vocab)
            if count < min_pair_count:
                break
            merges.append(best)
            merged = best[0] + best[1]
            vocab[merged] = len(vocab)

            def apply(word: tuple[str, ...]) -> tuple[str, ...]:
                out, i = [], 0
                while i < len(word):
                    if (
                        i < len(word) - 1
                        and word[i] == best[0]
                        and word[i + 1] == best[1]
                    ):
                        out.append(merged)
                        i += 2
                    else:
                        out.append(word[i])
                        i += 1
                return tuple(out)

            words = Counter(
                {apply(w): f for w, f in words.items()}
            )
        return cls(vocab, merges)

    @classmethod
    def _train_native(cls, words: Counter, base_vocab: dict[str, int],
                      vocab_size: int, min_pair_count: int):
        """Run the C++ incremental trainer; None if unavailable. Byte
        symbols map to ids 0..255 (base_vocab's assignment) and merge i
        creates id 256+i, matching the Python loop exactly."""
        try:
            from solvingpapers_tpu import native

            if not native.available():
                return None
        except ImportError:  # pragma: no cover
            return None
        items = list(words.items())
        flat, offsets, freqs = [], [0], []
        for word, freq in items:
            flat.extend(_BYTE_DEC[c] for c in word)
            offsets.append(len(flat))
            freqs.append(freq)
        pairs = native.bpe_train_native(
            np.asarray(flat, np.int32), np.asarray(offsets, np.int64),
            np.asarray(freqs, np.int64), vocab_size - 256, min_pair_count,
        )
        syms = [_BYTE_ENC[b] for b in range(256)]
        vocab = dict(base_vocab)
        merges: list[tuple[str, str]] = []
        for left, right in pairs:
            a, b = syms[int(left)], syms[int(right)]
            merges.append((a, b))
            syms.append(a + b)
            vocab[a + b] = len(vocab)
        return cls(vocab, merges)

    def save(self, vocab_path: str, merges_path: str) -> None:
        with open(vocab_path, "w", encoding="utf-8") as f:
            json.dump(self.vocab, f, ensure_ascii=False)
        with open(merges_path, "w", encoding="utf-8") as f:
            f.write("#version: 0.2\n")
            for a, b in sorted(self.ranks, key=self.ranks.get):
                f.write(f"{a} {b}\n")

    # --------------------------------------------------------------- encode

    def _bpe(self, token: str) -> list[str]:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token)
        while len(word) > 1:
            pairs = _get_pairs(word)
            best = min(pairs, key=lambda p: self.ranks.get(p, float("inf")))
            if best not in self.ranks:
                break
            out, i = [], 0
            while i < len(word):
                if i < len(word) - 1 and (word[i], word[i + 1]) == best:
                    out.append(word[i] + word[i + 1])
                    i += 2
                else:
                    out.append(word[i])
                    i += 1
            word = tuple(out)
        result = list(word)
        self._cache[token] = result
        return result

    def encode(self, text: str) -> np.ndarray:
        chunks = _GPT2_SPLIT.findall(text)
        enc = self._native_encoder()
        if enc is not None:
            return enc.encode_texts(chunks)
        ids: list[int] = []
        for tok in chunks:
            symbols = "".join(_BYTE_ENC[b] for b in tok.encode("utf-8"))
            ids.extend(self.vocab[s] for s in self._bpe(symbols))
        return np.asarray(ids, dtype=np.int32)

    def decode(self, ids) -> str:
        text = "".join(self.inv_vocab[int(i)] for i in ids)
        return bytes(_BYTE_DEC[c] for c in text).decode("utf-8", errors="replace")


def gpt2_tokenizer(vocab_path: str | None = None, merges_path: str | None = None):
    """The reference's GPT-2 BPE (50257 tokens) if obtainable offline:
    local files > tiktoken cache > HF cache; raises with guidance otherwise."""
    if vocab_path and merges_path:
        return ByteBPETokenizer.from_files(vocab_path, merges_path)
    try:
        import tiktoken

        enc = tiktoken.get_encoding("gpt2")

        class _Tik:
            vocab_size = enc.n_vocab

            def encode(self, text):
                return np.asarray(
                    enc.encode(text, allowed_special="all"), dtype=np.int32
                )

            def decode(self, ids):
                return enc.decode([int(i) for i in ids])

        return _Tik()
    except Exception:
        pass
    try:
        from transformers import AutoTokenizer

        tok = AutoTokenizer.from_pretrained("gpt2", local_files_only=True)

        class _HF:
            vocab_size = tok.vocab_size

            def encode(self, text):
                return np.asarray(tok.encode(text), dtype=np.int32)

            def decode(self, ids):
                return tok.decode([int(i) for i in ids])

        return _HF()
    except Exception:
        pass
    raise RuntimeError(
        "GPT-2 BPE tables unavailable offline. Pass vocab.json/merges.txt "
        "paths, or train a corpus tokenizer with ByteBPETokenizer.train()."
    )
