"""Token samplers.

Single-step sampling functions (logits -> token ids) shared by all decode
loops, covering the reference's four decoding strategies:
  * greedy argmax           (gpt/gpt-jax.ipynb cell 19)
  * categorical sampling    (llama3/LLaMA-jax.ipynb cell 14)
  * multinomial             (gemma/gemma.ipynb cell 20 — same as categorical)
  * temperature + top-k     (deepseekv3/deepseekv3.ipynb cell 40)
plus nucleus (top-p, Holtzman et al., "The Curious Case of Neural Text
Degeneration") and min-p truncation.

All are jit-safe (static shapes, no python branching on values) so they can
live inside a lax.while_loop/scan decode body (infer/decode.py).

The `*_mask` helpers are the single source of the top-p/min-p truncation
logic: `sample_top_p`/`sample_min_p` below AND the serving engine's fused
per-slot sampler (`serve/sampling.py`) both call them. Unlike
`lax.top_k`-based masking, they accept TRACED, per-row cutoffs
(`k`/`p`/`min_p` may be arrays broadcastable against
``logits[..., :1]``), which is what lets every slot of a vmapped decode
block carry different sampling params without recompiling — disabled
values (k <= 0, p >= 1, min_p <= 0) keep every token, so a greedy row
rides the same program unchanged. `sample_top_k` keeps its own
static-k `lax.top_k` threshold path on purpose: inside `generate`'s
decode scan a partial selection is far cheaper than `top_k_mask`'s full
sort, and its k is a static jit arg anyway (the serve path gets the same
economics from its top-`sample_cap` pre-selection).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def top_k_mask(logits: jax.Array, k) -> jax.Array:
    """Mask all but the `k` largest logits per row to -inf.

    `k` may be a python int, a traced scalar, or an array broadcastable
    against ``logits[..., :1]`` (per-row k). ``k <= 0`` disables the mask
    for that row (all tokens kept). Ties at the k-th value are all kept,
    matching `sample_top_k`'s threshold semantics.
    """
    k = jnp.asarray(k, jnp.int32)
    vocab = logits.shape[-1]
    sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    idx = jnp.broadcast_to(
        jnp.clip(k - 1, 0, vocab - 1), logits.shape[:-1] + (1,)
    )
    thresh = jnp.take_along_axis(sorted_desc, idx, axis=-1)
    return jnp.where((logits >= thresh) | (k <= 0), logits, -jnp.inf)


def top_p_mask(logits: jax.Array, p) -> jax.Array:
    """Nucleus mask: keep the smallest prefix of descending-probability
    tokens whose cumulative mass reaches `p`; mask the rest to -inf.

    The token that crosses the `p` boundary is KEPT (standard nucleus
    semantics: the kept set's mass is the least value >= p). `p` may be a
    scalar or an array broadcastable against ``logits[..., :1]``;
    ``p >= 1`` keeps every token with nonzero probability.
    """
    probs = jax.nn.softmax(logits, axis=-1)
    sorted_probs = jnp.flip(jnp.sort(probs, axis=-1), axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # token i (sorted) is kept iff the mass BEFORE it is < p: the first
    # token is always kept, and the one crossing p is included
    keep_sorted = (cum - sorted_probs) < jnp.asarray(p, logits.dtype)
    kth = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
    thresh = jnp.take_along_axis(sorted_probs, kth, axis=-1)
    return jnp.where(probs >= thresh, logits, -jnp.inf)


def min_p_mask(logits: jax.Array, min_p) -> jax.Array:
    """Keep tokens whose probability is >= ``min_p * max probability``;
    mask the rest to -inf. ``min_p <= 0`` disables (all kept); the argmax
    row is always kept, so the masked row is never empty. `min_p` may be
    a scalar or an array broadcastable against ``logits[..., :1]``."""
    probs = jax.nn.softmax(logits, axis=-1)
    thresh = jnp.asarray(min_p, logits.dtype) * jnp.max(
        probs, axis=-1, keepdims=True
    )
    return jnp.where(probs >= thresh, logits, -jnp.inf)


def allowed_logits(logits: jax.Array, allow: jax.Array):
    """Gather `logits` at the `allow` token ids; -1 pads gather index 0
    but land at -inf, so padded entries are never chosen.

    `allow` is (..., A) int32 of token ids with -1 padding — the serving
    engine's grammar-constrained allow-list (`serve/grammar.py`), packed
    per slot into the jitted programs' control transfers. Returns
    ``(vals, idx)`` where `vals` is the gathered (-inf-padded) logit row
    over the allowed support and `idx` the (clipped) gather ids — the
    same (values, indices) domain shape `lax.top_k` produces, so
    `serve.sampling.fused_sample` swaps one for the other per row and
    every downstream truncation mask applies unchanged."""
    idx = jnp.clip(allow, 0, logits.shape[-1] - 1).astype(jnp.int32)
    vals = jnp.take_along_axis(logits, idx, axis=-1)
    return jnp.where(allow >= 0, vals, -jnp.inf), idx


def sample_greedy(logits: jax.Array, rng: jax.Array | None = None) -> jax.Array:
    """Argmax over the last axis. rng accepted (ignored) for API uniformity."""
    del rng
    return jnp.argmax(logits, axis=-1)


def sample_categorical(
    logits: jax.Array, rng: jax.Array, temperature: float = 1.0
) -> jax.Array:
    return jax.random.categorical(rng, logits.astype(jnp.float32) / temperature, axis=-1)


def sample_top_k(
    logits: jax.Array,
    rng: jax.Array,
    k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """Temperature + top-k sampling: mask all but the k largest logits, sample.

    Static k (jit-friendly): uses lax.top_k threshold rather than a sort.
    """
    logits = logits.astype(jnp.float32) / temperature
    top_vals, _ = jax.lax.top_k(logits, k)
    thresh = top_vals[..., -1:]
    masked = jnp.where(logits >= thresh, logits, -jnp.inf)
    return jax.random.categorical(rng, masked, axis=-1)


def sample_top_p(
    logits: jax.Array,
    rng: jax.Array,
    p: float = 0.9,
    temperature: float = 1.0,
) -> jax.Array:
    """Temperature + nucleus (top-p) sampling: sample from the smallest
    token set whose cumulative probability reaches `p`. ``p=1.0`` is plain
    categorical sampling (same draw for the same rng)."""
    logits = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(rng, top_p_mask(logits, p), axis=-1)


def sample_min_p(
    logits: jax.Array,
    rng: jax.Array,
    min_p: float = 0.05,
    temperature: float = 1.0,
) -> jax.Array:
    """Temperature + min-p sampling: drop tokens whose probability is
    below ``min_p`` times the top token's. ``min_p=0`` is plain
    categorical sampling (same draw for the same rng)."""
    logits = logits.astype(jnp.float32) / temperature
    return jax.random.categorical(rng, min_p_mask(logits, min_p), axis=-1)
