"""Token samplers.

Single-step sampling functions (logits -> token ids) shared by all decode
loops, covering the reference's four decoding strategies:
  * greedy argmax           (gpt/gpt-jax.ipynb cell 19)
  * categorical sampling    (llama3/LLaMA-jax.ipynb cell 14)
  * multinomial             (gemma/gemma.ipynb cell 20 — same as categorical)
  * temperature + top-k     (deepseekv3/deepseekv3.ipynb cell 40)

All are jit-safe (static shapes, no python branching on values) so they can
live inside a lax.while_loop/scan decode body (infer/decode.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_greedy(logits: jax.Array, rng: jax.Array | None = None) -> jax.Array:
    """Argmax over the last axis. rng accepted (ignored) for API uniformity."""
    del rng
    return jnp.argmax(logits, axis=-1)


def sample_categorical(
    logits: jax.Array, rng: jax.Array, temperature: float = 1.0
) -> jax.Array:
    return jax.random.categorical(rng, logits.astype(jnp.float32) / temperature, axis=-1)


def sample_top_k(
    logits: jax.Array,
    rng: jax.Array,
    k: int = 50,
    temperature: float = 1.0,
) -> jax.Array:
    """Temperature + top-k sampling: mask all but the k largest logits, sample.

    Static k (jit-friendly): uses lax.top_k threshold rather than a sort.
    """
    logits = logits.astype(jnp.float32) / temperature
    top_vals, _ = jax.lax.top_k(logits, k)
    thresh = top_vals[..., -1:]
    masked = jnp.where(logits >= thresh, logits, -jnp.inf)
    return jax.random.categorical(rng, masked, axis=-1)
