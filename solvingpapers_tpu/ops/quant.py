"""Symmetric int8 quantization with per-block absmax scales — the KV
cache compression primitive behind `ServeConfig.kv_quant` (serve/kv_pool.py).

Layout contract (the cache layout of `infer/cache.py`): a cache leaf is
``(batch, time, n_heads, head_dim)`` (KVCache k/v) or ``(batch, time,
channels)`` (LatentCache c). Quantization blocks tile the TIME axis with
a static `block` length and scales are kept at LLM.int8()-style fine
granularity so one outlier cannot flatten a whole lane:

* 4-D leaves: one f32 scale per ``(batch, time-block, head)`` — the
  "per-(page, head)-block" granularity (the paged pool passes
  ``block = page_size``, so each physical page carries one scale row per
  head; the lane pool tiles lanes with `ServeConfig.kv_quant_block`).
* 3-D leaves (MLA latents): one f32 scale per ``(batch, time-block)``.
  Per-channel scales would cost 4 bytes per `block` int8 entries (25%
  at block 16 — enough to push the latent pool past the 0.6x byte
  budget), so latents take the coarser per-block scalar and the quality
  gate (greedy-agreement rate, serve/bench.py) measures the cost.

Scale semantics: ``scale = absmax / 127`` over the block, so the
block's max-magnitude entry maps to exactly +-127 and every entry obeys
``|x - q * scale| <= scale / 2`` (the classic symmetric-absmax bound).
An all-zero block has scale 0 and round-trips bit-exact (q = 0 -> 0).
Round-tripping an already-dequantized block IN F32 with an unchanged
absmax reproduces the identical int8 payload. That fixed point is what
keeps committed entries stable under the serving programs' windowed
stores (serve/kv_pool.py): untouched blocks are never
re-read-modify-written at all, and within a block a step did write,
positions outside the written window are re-encoded from their own
f32-dequantized codes — NOT from the compute-dtype lane view, where a
bf16 cast breaks the fixed point (the cast shifts the block absmax and
walks committed codes step to step) — so repeated decode steps cannot
random-walk old entries on any compute dtype.

All math runs in f32 regardless of the cache compute dtype (bf16
reductions are scalar-emulated on XLA:CPU, and a bf16 absmax would also
quantize against a degraded scale).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def scale_shape(shape: tuple, block: int) -> tuple:
    """Scale-array shape for a cache leaf of `shape` tiled by `block`
    along the time axis: ``(B, T//block, H)`` for 4-D leaves,
    ``(B, T//block)`` for 3-D ones. The shapes the sidecar pools pin."""
    if len(shape) not in (3, 4):
        raise ValueError(
            f"cache leaves are (B, T, H, D) or (B, T, C); got {shape}"
        )
    b, t = shape[0], shape[1]
    if t % block:
        raise ValueError(
            f"time length {t} is not a multiple of the quant block {block}"
        )
    if len(shape) == 4:
        return (b, t // block, shape[2])
    return (b, t // block)


def _reduce_axes(ndim: int) -> tuple:
    # blocked view (B, nb, block, ...): reduce the block axis plus every
    # trailing axis EXCEPT the head axis of 4-D leaves
    if ndim == 4:
        return (2, 4)
    if ndim == 3:
        return (2, 3)
    raise ValueError(f"cache leaves are 3-D or 4-D; got ndim {ndim}")


def quantize(x, block: int):
    """Symmetric int8 quantization of a cache leaf (traced).

    Returns ``(q int8, scale f32)`` with `q` shaped like `x` and `scale`
    shaped `scale_shape(x.shape, block)`. ``q = round(x / scale)``
    clipped to [-127, 127] (the -128 code is unused, keeping the code
    space symmetric); zero-absmax blocks quantize to q = 0, scale = 0.
    """
    sshape = scale_shape(x.shape, block)  # validates shape + block
    b, t = x.shape[0], x.shape[1]
    xs = x.astype(jnp.float32).reshape((b, t // block, block) + x.shape[2:])
    red = _reduce_axes(x.ndim)
    absmax = jnp.max(jnp.abs(xs), axis=red, keepdims=True)
    sfull = absmax / 127.0
    q = jnp.where(sfull > 0.0, xs / jnp.where(sfull > 0.0, sfull, 1.0), 0.0)
    q = jnp.clip(jnp.round(q), -127.0, 127.0).astype(jnp.int8)
    return q.reshape(x.shape), sfull.reshape(sshape)


def dequantize(q, scale, dtype=jnp.float32):
    """Inverse of `quantize`: ``q * scale`` broadcast per block, cast to
    `dtype` (the cache compute dtype). The block length is recovered from
    the shapes, so the scale array IS the layout metadata."""
    b, t = q.shape[0], q.shape[1]
    nb = scale.shape[1]
    if nb < 1 or t % nb:
        raise ValueError(
            f"scale blocks {nb} do not tile the time axis {t}"
        )
    block = t // nb
    qs = q.astype(jnp.float32).reshape((b, nb, block) + q.shape[2:])
    if q.ndim == 4:
        sfull = scale[:, :, None, :, None]
    elif q.ndim == 3:
        sfull = scale[:, :, None, None]
    else:
        raise ValueError(f"cache leaves are 3-D or 4-D; got ndim {q.ndim}")
    return (qs * sfull).reshape(q.shape).astype(dtype)


def quantize_tree(tree, block: int):
    """Quantize every leaf of a cache pytree: ``(q_tree, scale_tree)``
    with both trees matching the input structure (flax-struct cache
    nodes keep their class — a KVCache of scales is just a container)."""
    flat, treedef = jax.tree_util.tree_flatten(tree)
    pairs = [quantize(a, block) for a in flat]
    return (jax.tree_util.tree_unflatten(treedef, [q for q, _ in pairs]),
            jax.tree_util.tree_unflatten(treedef, [s for _, s in pairs]))


def dequantize_tree(q_tree, scale_tree, dtype=jnp.float32):
    """Leafwise `dequantize` over parallel payload/scale pytrees."""
    return jax.tree_util.tree_map(
        lambda q, s: dequantize(q, s, dtype), q_tree, scale_tree
    )
