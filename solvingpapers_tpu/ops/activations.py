"""Activation primitives.

Covers the reference's `activation functions/` directory (ReLU.ipynb
cells 1-4: relu/leakyrelu/prelu/elu; GELU.ipynb cell 4: tanh-approx GELU)
plus the gated activations used by the LMs (silu/swish for SwiGLU —
llama3/LLaMA-jax.ipynb cell 25, deepseekv3/deepseekv3.ipynb cell 21;
gelu for GeGLU — gemma/gemma.ipynb cell 9).

All are pure elementwise functions; XLA fuses them into adjacent matmuls
so there is no reason to hand-write kernels for these on TPU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def relu(x: jax.Array) -> jax.Array:
    return jax.nn.relu(x)


def leaky_relu(x: jax.Array, negative_slope: float = 0.01) -> jax.Array:
    return jax.nn.leaky_relu(x, negative_slope)


def prelu(x: jax.Array, alpha: jax.Array) -> jax.Array:
    """Parametric ReLU; `alpha` is a learned scalar or per-channel array."""
    return jnp.where(x >= 0, x, alpha * x)


def elu(x: jax.Array, alpha: float = 1.0) -> jax.Array:
    # expm1 for numerical accuracy near 0; where() keeps the positive branch exact.
    safe = jnp.minimum(x, 0.0)
    return jnp.where(x >= 0, x, alpha * jnp.expm1(safe))


_GELU_C = math.sqrt(2.0 / math.pi)


def gelu_tanh(x: jax.Array) -> jax.Array:
    """Tanh-approximation GELU: 0.5*x*(1 + tanh(sqrt(2/pi)*(x + 0.044715*x^3)))."""
    return 0.5 * x * (1.0 + jnp.tanh(_GELU_C * (x + 0.044715 * jnp.power(x, 3))))


def silu(x: jax.Array) -> jax.Array:
    return jax.nn.silu(x)


def swish(x: jax.Array, beta: float = 1.0) -> jax.Array:
    """Swish with temperature beta; beta=1 is SiLU."""
    return x * jax.nn.sigmoid(beta * x)
