"""Rotary position embeddings — one implementation, three formulations.

The reference contains two independent RoPE implementations:
  * complex-number rotation (llama3/LLaMA-jax.ipynb cells 16-17: interpret
    consecutive feature pairs as complex numbers, multiply by e^{i m θ_j});
  * explicit (seq, D, D) rotation matrices rebuilt per call
    (gemma/gemma.ipynb cell 7 — whose own markdown cell 21 complains about
    the resulting inference latency).

The TPU-native primary form here is the split cos/sin formulation
(`precompute_rope` + `apply_rope`): real-valued, static-shaped, fusable by
XLA, and cheap to slice for cached decode (one row per position). The
complex and matrix forms are kept as reference implementations so tests can
prove all three agree (SURVEY.md §4 test plan).

Pairing convention: features are split into interleaved (even, odd) pairs
(x[..., 0::2], x[..., 1::2]) — matching the complex-reshape convention of
the llama3 notebook.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def precompute_rope(
    head_dim: int,
    max_seq_len: int,
    theta: float = 10000.0,
    dtype: jnp.dtype = jnp.float32,
) -> tuple[jax.Array, jax.Array]:
    """Returns (cos, sin), each of shape (max_seq_len, head_dim // 2)."""
    if head_dim % 2:
        raise ValueError(f"head_dim must be even, got {head_dim}")
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = jnp.outer(jnp.arange(max_seq_len, dtype=jnp.float32), freqs)
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    positions: jax.Array | None = None,
) -> jax.Array:
    """Rotate feature pairs of `x` by position-dependent angles.

    x:    (..., seq, num_heads, head_dim)  — seq is axis -3.
    cos/sin: (max_seq_len, head_dim // 2) tables from `precompute_rope`.
    positions: optional int array (..., seq) of absolute positions; defaults
        to arange(seq). Used for cached decode where seq==1 at offset p.
    """
    seq = x.shape[-3]
    if positions is None:
        cos_p = jax.lax.dynamic_slice_in_dim(cos, 0, seq, axis=0)
        sin_p = jax.lax.dynamic_slice_in_dim(sin, 0, seq, axis=0)
    else:
        cos_p = jnp.take(cos, positions, axis=0)
        sin_p = jnp.take(sin, positions, axis=0)
    # broadcast over the heads axis: (..., seq, 1, head_dim//2)
    cos_p = jnp.expand_dims(cos_p, axis=-2)
    sin_p = jnp.expand_dims(sin_p, axis=-2)
    x32 = x.astype(jnp.float32)
    x_even = x32[..., 0::2]
    x_odd = x32[..., 1::2]
    out_even = x_even * cos_p - x_odd * sin_p
    out_odd = x_even * sin_p + x_odd * cos_p
    # re-interleave: stack pairs on a trailing axis then flatten
    out = jnp.stack([out_even, out_odd], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# Reference formulations (used by tests to cross-validate `apply_rope`).
# ---------------------------------------------------------------------------


def precompute_freqs_cis(head_dim: int, max_seq_len: int, theta: float = 10000.0) -> jax.Array:
    """Complex e^{i m θ} table, shape (max_seq_len, head_dim // 2), complex64.

    Mirrors llama3/LLaMA-jax.ipynb cell 16 semantics.
    """
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = jnp.outer(jnp.arange(max_seq_len, dtype=jnp.float32), freqs)
    return jax.lax.complex(jnp.cos(angles), jnp.sin(angles))


def apply_rotary_emb_complex(x: jax.Array, freqs_cis: jax.Array) -> jax.Array:
    """Complex-multiplication RoPE (llama3/LLaMA-jax.ipynb cell 17 semantics).

    x: (..., seq, num_heads, head_dim); freqs_cis: (seq, head_dim//2).
    """
    x32 = x.astype(jnp.float32)
    xc = jax.lax.complex(x32[..., 0::2], x32[..., 1::2])
    fc = freqs_cis.reshape((x.shape[-3], 1, x.shape[-1] // 2))
    out = xc * fc
    out = jnp.stack([jnp.real(out), jnp.imag(out)], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def rope_rotation_matrix(head_dim: int, max_seq_len: int, theta: float = 10000.0) -> jax.Array:
    """Dense (max_seq_len, head_dim, head_dim) block-diagonal rotation matrices.

    The gemma/gemma.ipynb cell 7 formulation (built per call there; built
    once here). Only used in tests — O(T·D²) memory makes it a non-starter
    as a production op, which is exactly the latency bug the reference's
    own gemma markdown cell 21 reports.
    """
    cos, sin = precompute_rope(head_dim, max_seq_len, theta)
    mats = jnp.zeros((max_seq_len, head_dim, head_dim), dtype=jnp.float32)
    idx = jnp.arange(head_dim // 2)
    even, odd = 2 * idx, 2 * idx + 1
    mats = mats.at[:, even, even].set(cos)
    mats = mats.at[:, even, odd].set(-sin)
    mats = mats.at[:, odd, even].set(sin)
    mats = mats.at[:, odd, odd].set(cos)
    return mats


def sinusoidal_position_encoding(max_len: int, dim: int) -> jax.Array:
    """Classic sin/cos position table (deepseekv3/deepseekv3.ipynb cell 16):
    pe[p, 2i] = sin(p / 10000^(2i/dim)), pe[p, 2i+1] = cos(...). Returns
    (max_len, dim) float32, precomputed once and indexed by position."""
    pos = jnp.arange(max_len, dtype=jnp.float32)[:, None]
    i = jnp.arange(0, dim, 2, dtype=jnp.float32)[None, :]
    angle = pos / jnp.power(10000.0, i / dim)
    pe = jnp.zeros((max_len, dim), jnp.float32)
    pe = pe.at[:, 0::2].set(jnp.sin(angle))
    pe = pe.at[:, 1::2].set(jnp.cos(angle[:, : dim // 2]))
    return pe
