"""Normalization primitives.

Single shared implementation replacing the reference's three independent
RMSNorm impls (llama3/LLaMA-jax.ipynb cell 15, gemma/gemma.ipynb cell 6,
deepseekv3/deepseekv3.ipynb cell 19) and its LayerNorm usages
(gpt/gpt-jax.ipynb cell 11, vision transformer/ViT.ipynb cell 10).

TPU notes: statistics are computed in float32 regardless of input dtype
(bf16-safe), and the result is cast back to the input dtype so the op can
sit inside a bf16 matmul chain without precision loss in the reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array | None = None, eps: float = 1e-6) -> jax.Array:
    """Root-mean-square normalization: x / sqrt(mean(x^2) + eps) * weight."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(dtype)


def local_response_norm(
    x: jax.Array,
    size: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
    k: float = 1.0,
) -> jax.Array:
    """AlexNet cross-channel LRN: x / (k + alpha/size * sum_adj(x^2))^beta.

    Matches torch.nn.LocalResponseNorm semantics used by alexnet/alexnet.py:9
    (channel-last layout here: x is (..., C); the window of `size` channels
    is centered on each channel with zero padding).
    """
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    sq = jnp.square(x32)
    half = size // 2
    # sum over a sliding channel window via cumulative sums
    pad = [(0, 0)] * (sq.ndim - 1) + [(half, size - 1 - half)]
    padded = jnp.pad(sq, pad)
    csum = jnp.cumsum(padded, axis=-1)
    zero = jnp.zeros_like(csum[..., :1])
    csum = jnp.concatenate([zero, csum], axis=-1)
    c = x.shape[-1]
    window = csum[..., size : size + c] - csum[..., :c]
    denom = jnp.power(k + (alpha / size) * window, beta)
    return (x32 / denom).astype(dtype)


def layer_norm(
    x: jax.Array,
    weight: jax.Array | None = None,
    bias: jax.Array | None = None,
    eps: float = 1e-5,
) -> jax.Array:
    """LayerNorm over the last axis with optional affine transform."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)
