"""Shared primitive ops (L1).

One implementation of each primitive the reference re-implements per
notebook: norms, RoPE (both formulations), activations, attention cores,
losses, and samplers.
"""

from solvingpapers_tpu.ops.norms import rms_norm, layer_norm, local_response_norm
from solvingpapers_tpu.ops.rope import (
    precompute_rope,
    precompute_freqs_cis,
    apply_rope,
    apply_rotary_emb_complex,
    rope_rotation_matrix,
    sinusoidal_position_encoding,
)
from solvingpapers_tpu.ops import moe
from solvingpapers_tpu.ops.activations import (
    relu,
    leaky_relu,
    prelu,
    elu,
    gelu_tanh,
    silu,
    swish,
)
from solvingpapers_tpu.ops.attention import (
    repeat_kv,
    causal_mask,
    dot_product_attention,
    luong_attention,
)
from solvingpapers_tpu.ops.losses import (
    cross_entropy,
    distillation_loss,
    vae_loss,
    mtp_loss,
)
from solvingpapers_tpu.ops.quant import (
    quantize,
    dequantize,
    quantize_tree,
    dequantize_tree,
    scale_shape,
)
from solvingpapers_tpu.ops.sampling import (
    sample_greedy,
    sample_categorical,
    sample_top_k,
    sample_top_p,
    sample_min_p,
    top_k_mask,
    top_p_mask,
    min_p_mask,
    allowed_logits,
)
