"""Loss functions.

One shared implementation of every loss in the reference:
  * token cross-entropy             (gpt/gpt-jax.ipynb cell 13; manual
                                     log-softmax gather llama3 cell 28)
  * CE with ignore_index            (deepseekv3/deepseekv3.ipynb cell 54)
  * multi-token-prediction loss     (deepseekv3 cell 46)
  * distillation CE + T^2*KL        (knowledge distillation/kd.py:48-68)
  * VAE summed BCE + analytic KL    (autoencoder/variational autoencoder.ipynb cell 6)
  * classification CE / MSE         (ViT cell 13; autoencoder cell 6 — via
                                     cross_entropy / plain jnp mean-square)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# past this many logit elements (f32 log-probs > 1 GB) the loss chunks
# itself; every CE caller (LM, DSV3, MTP) is covered without opting in.
# Threshold sized so the reference-scale dsv3 config (4096 rows x 50257 =
# 206M elements) stays single-pass (chunking costs it ~7% throughput for
# memory it does not need) while 16k-context LM runs (524M+) chunk.
_AUTO_CHUNK_ELEMENTS = 2**28
_AUTO_CHUNK_ROWS = 8192


def cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: int | None = None,
    chunk_size: int | None | str = "auto",
) -> jax.Array:
    """Mean cross-entropy of integer labels; optionally masks ignore_index.

    logits: (..., V); labels: (...) int. Computed in float32.

    chunk_size: when set, rows are processed in `chunk_size` slices under
    jax.checkpoint — the f32 log-softmax exists for one chunk at a time and
    is recomputed in the backward, so peak HBM for the loss drops from
    O(rows x V) f32 to O(chunk x V). Long-context single-chip training
    (tools/scale_350m.py --seq 16384) OOMs without this: at seq 16k,
    vocab 32k the unchunked f32 logits + log-probs + cotangent cost ~6G of
    the 15.75G HBM. Same math, summation order differs only across chunks.
    The default "auto" chunks at 8192 rows once logits exceed 2^28 elements
    (small models keep the single-pass form); pass None to force one pass.
    """
    if chunk_size == "auto":
        chunk_size = (
            _AUTO_CHUNK_ROWS if logits.size > _AUTO_CHUNK_ELEMENTS else None
        )
    if chunk_size is not None:
        rows = logits.size // logits.shape[-1]
        # a single whole-size chunk still pays off: jax.checkpoint drops the
        # f32 log-softmax from the saved residuals either way
        return _chunked_cross_entropy(
            logits, labels, ignore_index, min(chunk_size, rows)
        )
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    if ignore_index is None:
        nll = -jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)
    valid = labels != ignore_index
    # Gather with sanitized indices: take_along_axis uses fill-mode for OOB
    # indices, so a sentinel like -100 gathers NaN, and NaN * 0 mask = NaN.
    safe = jnp.where(valid, labels, 0)
    nll = -jnp.take_along_axis(log_probs, safe[..., None], axis=-1)[..., 0]
    mask = valid.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def _chunked_cross_entropy(
    logits: jax.Array, labels: jax.Array, ignore_index: int | None, chunk: int
) -> jax.Array:
    """Scan over row chunks; each chunk's f32 softmax is rematerialized in
    the backward (jax.checkpoint), so only the source-dtype logits persist."""
    tot, num = _chunked_nll_sum_count(logits, labels, ignore_index, chunk)
    return tot / jnp.maximum(num, 1.0)


def _chunked_nll_sum_count(
    logits: jax.Array, labels: jax.Array, ignore_index: int | None, chunk: int
) -> tuple[jax.Array, jax.Array]:
    """(masked nll SUM, valid COUNT) over rows via the chunked checkpoint
    scan — shared by cross_entropy (which divides here) and mtp_loss's CP
    path (which psums sum/count across shards before dividing)."""
    v = logits.shape[-1]
    flat = logits.reshape(-1, v)
    lab = labels.reshape(-1)
    n = flat.shape[0]
    pad = (-n) % chunk
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
        # padded rows are masked out via an out-of-band label
        sentinel = -1 if ignore_index is None else ignore_index
        lab = jnp.pad(lab, (0, pad), constant_values=sentinel)
        if ignore_index is None:
            ignore_index = -1
    flat = flat.reshape(-1, chunk, v)
    lab = lab.reshape(-1, chunk)

    @jax.checkpoint
    def body(carry, xs):
        lg, lb = xs
        lg = lg.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, axis=-1)
        if ignore_index is None:
            picked = jnp.take_along_axis(lg, lb[:, None], axis=-1)[:, 0]
            nll_sum = jnp.sum(lse - picked)
            cnt = jnp.float32(lb.shape[0])
        else:
            valid = lb != ignore_index
            safe = jnp.where(valid, lb, 0)
            picked = jnp.take_along_axis(lg, safe[:, None], axis=-1)[:, 0]
            m = valid.astype(jnp.float32)
            nll_sum = jnp.sum((lse - picked) * m)
            cnt = jnp.sum(m)
        tot, num = carry
        return (tot + nll_sum, num + cnt), None

    # under shard_map with vma tracking, the carry must match the body
    # output's varying axes (the logits are shard-varying on CP paths)
    zero = jnp.float32(0.0)
    _typeof = getattr(jax, "typeof", None)  # absent pre-vma jax: no tracking
    vma = tuple(getattr(_typeof(flat), "vma", ()) or ()) if _typeof else ()
    if vma:
        zero = jax.lax.pcast(zero, vma, to="varying")
    (tot, num), _ = jax.lax.scan(body, (zero, zero), (flat, lab))
    return tot, num


def distillation_loss(
    student_logits: jax.Array,
    teacher_logits: jax.Array,
    labels: jax.Array,
    temperature: float = 7.0,
    alpha: float = 0.3,
) -> jax.Array:
    """Hinton KD loss: alpha*CE(student, labels) + (1-alpha)*T^2*KL(teacher||student).

    Matches knowledge distillation/kd.py:48-68 (T=7, alpha=0.3): KL of
    temperature-softened distributions, scaled by T^2 to keep gradient
    magnitude comparable to the CE term.
    """
    hard = cross_entropy(student_logits, labels)
    t = temperature
    s_log = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, axis=-1)
    t_prob = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, axis=-1)
    # batchmean KL(teacher || student)
    kl = jnp.sum(t_prob * (jnp.log(jnp.maximum(t_prob, 1e-12)) - s_log), axis=-1)
    soft = jnp.mean(kl) * (t * t)
    return alpha * hard + (1.0 - alpha) * soft


def vae_loss(
    recon: jax.Array,
    target: jax.Array,
    mu: jax.Array,
    logvar: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Summed BCE reconstruction + analytic KL to N(0, I).

    Matches autoencoder/variational autoencoder.ipynb cell 6 (sum
    reduction, per batch). `recon` is post-sigmoid probabilities in (0,1).
    Returns (total, bce, kl).
    """
    recon32 = jnp.clip(recon.astype(jnp.float32), 1e-7, 1.0 - 1e-7)
    target32 = target.astype(jnp.float32)
    bce = -jnp.sum(
        target32 * jnp.log(recon32) + (1.0 - target32) * jnp.log(1.0 - recon32)
    )
    kl = -0.5 * jnp.sum(1.0 + logvar - jnp.square(mu) - jnp.exp(logvar))
    return bce + kl, bce, kl


def mtp_loss(
    logits: jax.Array,
    tokens: jax.Array,
    num_heads: int,
    ignore_index: int | None = None,
    axis_names: tuple | None = None,
) -> jax.Array:
    """Multi-token-prediction loss (deepseekv3/deepseekv3.ipynb cell 46).

    logits: (B, T, K, V) where head k at position i predicts token i+k+1.
    tokens: (B, T + K) raw token stream providing the shifted targets.
    Flat mean CE over all (position, head) pairs with valid targets.

    axis_names: inside shard_map (context parallelism, T = local shard),
    psum the masked nll SUM and the valid COUNT across the axes before
    dividing — shards hold different valid counts (only the last shard
    loses the k tail targets), so a pmean of local means would weight the
    tail shard's targets differently from the dense computation.
    """
    b, t, k, v = logits.shape
    assert k == num_heads
    if tokens.shape[-1] != t + k:
        raise ValueError(
            f"tokens must have T+K={t + k} columns to provide shifted targets, "
            f"got {tokens.shape[-1]}"
        )
    # targets[b, i, k] = tokens[b, i + k + 1]
    idx = jnp.arange(t)[:, None] + jnp.arange(1, k + 1)[None, :]
    targets = tokens[:, idx]  # (B, T, K)
    if axis_names is None:
        return cross_entropy(
            logits.reshape(b * t * k, v), targets.reshape(-1), ignore_index
        )
    # CP path: masked-nll SUM and valid COUNT via cross_entropy's chunked
    # checkpoint scan (one chunk's f32 log-probs at a time — long-context
    # configs like dsv3_long_cp have 131k local rows x 50k vocab, which
    # unchunked would be ~26 GB of f32), then psum'd before dividing.
    rows = b * t * k
    chunk = (
        min(_AUTO_CHUNK_ROWS, rows)
        if logits.size > _AUTO_CHUNK_ELEMENTS else rows
    )
    s, c = _chunked_nll_sum_count(
        logits.reshape(rows, v), targets.reshape(-1), ignore_index, chunk
    )
    s = jax.lax.psum(s, axis_names)
    c = jax.lax.psum(c, axis_names)
    return s / jnp.maximum(c, 1.0)
