"""Mixture-of-Experts routing + dispatch primitives.

Capability target: deepseekv3/deepseekv3.ipynb cell 23 (`MoeLayer`) — linear
gate, optional softplus-noise top-k, learned routing bias added before
selection (aux-free load balancing), top-k -inf-masked softmax over all
experts, weighted expert combine, shared expert, and the no-grad bias update
`bias += rate * sign(mean(load) - load)`.

TPU-first: the reference's python loop over experts with boolean gather/
scatter becomes static-shape one-hot einsum dispatch (tokens -> expert
capacity slots) so the whole layer is three MXU einsums; a dense
all-experts path is kept as the numerics reference (exact — no capacity
drops) and for tiny configs. Expert weights are stacked (E, ...) arrays so
an `expert` mesh axis shards them directly and GSPMD inserts the
all_to_alls (SURVEY.md §2.3 EP row).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from solvingpapers_tpu.ops.attention import BIG_NEG


def topk_gate_probs(gate_logits: jax.Array, k: int) -> jax.Array:
    """(T, E) logits -> (T, E) probs: softmax over the top-k entries per row,
    zero elsewhere (deepseekv3 cell 23's masked-scatter softmax; computed in
    float32)."""
    logits32 = gate_logits.astype(jnp.float32)
    kth = jax.lax.top_k(logits32, k)[0][..., -1:]
    masked = jnp.where(logits32 >= kth, logits32, BIG_NEG)
    return jax.nn.softmax(masked, axis=-1)


def aux_free_bias_update(
    probs: jax.Array, bias: jax.Array, rate: float, axis_names=None, ci=None
) -> jax.Array:
    """New routing bias per deepseekv3 cell 23: load c_i = sum of routed
    probabilities per expert; bias += rate * sign(mean(c) - c). Run under
    stop_gradient (the reference wraps it in torch.no_grad).

    `axis_names`: mesh axes to psum the per-expert load over — REQUIRED
    inside shard_map (context/data-parallel steps), where each shard sees
    only its tokens and a local update would silently diverge per shard.
    `ci`: precomputed (already psum'd) per-expert load, to share one
    reduction/collective with load_balance_stats."""
    if ci is None:
        ci = expert_load(probs, axis_names)
    err = jnp.mean(ci) - ci
    return bias + rate * jnp.sign(err).astype(bias.dtype)


def _psum_axes(x: jax.Array, axis_names) -> tuple:
    """Restrict a psum to the axes `x` actually varies over — under the
    shard_map vma checker a psum over an invariant axis is a type error
    (e.g. CP x PP meshes where 'data' has size 1); without vma tracking
    the full tuple is kept (the extra psums are numeric no-ops)."""
    _typeof = getattr(jax, "typeof", None)  # absent pre-vma jax: no tracking
    vma = getattr(_typeof(x), "vma", None) if _typeof else None
    if vma is None:
        return tuple(axis_names)
    return tuple(a for a in axis_names if a in vma)


def expert_load(probs: jax.Array, axis_names=None) -> jax.Array:
    """(E,) routed probability mass per expert under stop_gradient,
    psum'd over `axis_names` when inside shard_map."""
    ci = jax.lax.stop_gradient(jnp.sum(probs.astype(jnp.float32), axis=0))
    if axis_names:
        axes = _psum_axes(ci, axis_names)
        if axes:
            ci = jax.lax.psum(ci, axes)
    return ci


def expert_capacity(
    n_tokens: int, n_experts: int, top_k: int, capacity_factor: float
) -> int:
    """Per-expert slot count for dispatch: ceil(T*k/E * cf), 8-aligned."""
    c = int(n_tokens * top_k / n_experts * capacity_factor)
    return max(8, -(-c // 8) * 8)


def _dispatch_slots(probs: jax.Array, capacity: int):
    """Slot assignment shared by dispatch and the drop metric (they must
    never disagree about what is dropped): sel = routed (token, expert)
    pairs, pos = slot index within the expert queue (ordered by token id),
    keep = pairs inside capacity."""
    sel = probs > 0.0
    pos = jnp.cumsum(sel.astype(jnp.int32), axis=0) - 1  # (T, E)
    keep = sel & (pos < capacity)
    return sel, pos, keep


def moe_dispatch_combine(
    x: jax.Array,
    probs: jax.Array,
    expert_fn,
    capacity: int,
) -> jax.Array:
    """Static-shape MoE: route (T, D) tokens to (E, C, D) slots, run
    `expert_fn((E, C, D)) -> (E, C, D)`, combine back weighted by probs.

    Tokens beyond an expert's capacity are dropped for that expert (their
    probability mass contributes nothing) — set capacity_factor high enough
    that drops are rare; the dense path below is drop-free.
    """
    sel, pos, keep = _dispatch_slots(probs, capacity)
    # (T, E, C); dropped/unselected tokens index the sentinel `capacity`,
    # which one_hot encodes as an all-zero row — no extra masking needed
    dispatch = jax.nn.one_hot(
        jnp.where(keep, pos, capacity), capacity, dtype=x.dtype
    )
    xe = jnp.einsum("tec,td->ecd", dispatch, x)
    ye = expert_fn(xe)
    combine = dispatch * probs[..., None].astype(x.dtype)
    return jnp.einsum("tec,ecd->td", combine, ye)


def dispatch_drop_fraction(
    probs: jax.Array, capacity: int, axis_names=None
) -> jax.Array:
    """Fraction of routed (token, expert) assignments that
    moe_dispatch_combine drops at this capacity (same cumsum slot
    assignment), under stop_gradient. 0.0 = no dropped probability mass —
    the load-balance observability SURVEY.md hard part #1 calls for;
    silent drops were VERDICT r1 missing item 5. `axis_names`: psum counts
    across shards (each shard dispatches its local tokens independently)."""
    sel, _, keep = _dispatch_slots(jax.lax.stop_gradient(probs), capacity)
    kept = jnp.sum(keep.astype(jnp.float32))
    routed = jnp.sum(sel.astype(jnp.float32))
    if axis_names:
        axes = _psum_axes(kept, axis_names)
        if axes:
            kept = jax.lax.psum(kept, axes)
            routed = jax.lax.psum(routed, axes)
    return (routed - kept) / jnp.maximum(routed, 1.0)


def load_balance_stats(
    probs: jax.Array, axis_names=None, ci=None
) -> dict[str, jax.Array]:
    """Routing-load summary from (T, E) gate probs, under stop_gradient:
    load_entropy (normalized to [0, 1]; 1 = perfectly balanced),
    load_max_fraction (1/E = balanced, 1 = collapsed). `axis_names`: psum
    the per-expert load across shards first; `ci`: precomputed load."""
    if ci is None:
        ci = expert_load(probs, axis_names)
    e = probs.shape[-1]
    load = ci / jnp.maximum(jnp.sum(ci), 1e-9)
    entropy = -jnp.sum(load * jnp.log(load + 1e-9)) / jnp.log(float(e))
    return {"load_entropy": entropy, "load_max_fraction": jnp.max(load)}


def moe_expert_sliced_combine(
    x: jax.Array,
    probs: jax.Array,
    expert_fn,
    capacity: int,
    axis_name: str = "expert",
) -> jax.Array:
    """Expert-parallel MoE for shard_map bodies: the caller's expert
    weights are SHARDED over `axis_name` (each member holds E/ep experts)
    while tokens/probs are replicated across it. Each member dispatches its
    local expert columns (identical slot assignment to the unsharded
    dispatch, per-column independent), runs
    ``expert_fn((E_local, C, D), start)`` — `start` is the member's first
    global expert index, so callers slice their weight stacks by the SAME
    convention this op slices probs (contiguous blocks) — and the partial
    combines psum over the axis. No all_to_all needed — token replication
    over 'expert' makes EP a slice + reduce, composing freely with the
    data/context axes of the same shard_map."""
    t, e = probs.shape
    ep = jax.lax.psum(1, axis_name)
    if e % ep:
        raise ValueError(f"{e} experts not divisible by '{axis_name}' axis {ep}")
    e_local = e // ep
    start = jax.lax.axis_index(axis_name) * e_local
    probs_local = jax.lax.dynamic_slice(probs, (0, start), (t, e_local))
    partial = moe_dispatch_combine(
        x, probs_local, lambda xe: expert_fn(xe, start), capacity
    )
    return jax.lax.psum(partial, axis_name)


def moe_all_to_all_combine(
    x: jax.Array,
    probs: jax.Array,
    expert_fn,
    capacity: int,
    axis_name: str = "expert",
) -> jax.Array:
    """Token-dispatch expert parallelism: tokens physically move to their
    experts over `axis_name` (SURVEY.md §2.3 EP row; the communication
    pattern the reference's distributed MoE would use, rebuilt on XLA
    collectives instead of NCCL).

    Contract (differs from moe_expert_sliced_combine, which replicates
    tokens): `x` (T_local, D) / `probs` (T_local, E) are this member's
    TOKEN SHARD over `axis_name`; expert weights are sharded over the same
    axis. Each member one-hot-dispatches its local tokens into per-expert
    capacity slots (E, C, D), one tiled `all_to_all` ships each expert's
    slot block to the member that owns it — landing as (E/ep, ep*C, D),
    slot blocks ordered by source member — the local expert matmul runs via
    ``expert_fn((E/ep, ep*C, D), start)`` (same `start` slicing convention
    as the sliced op), a second `all_to_all` ships results back to the
    slots' owners, and each member combines into its own (T_local, D).

    Bytes on the wire per member (one direction, elements): the two
    all_to_alls move 2*(ep-1)/ep * E*C*D ≈ 2*(ep-1)/ep * k*cf*T_local*D,
    i.e. only the routed capacity — vs the replicate+psum path whose
    combine all-reduce moves 2*(ep-1)/ep * T_full*D with T_full = ep *
    T_local. See `ep_comm_elements` for the accounting used by dryrun/bench.

    Capacity (and therefore dropping) is decided per member from its local
    token count — the standard distributed-MoE semantics, identical to how
    the sliced path decides drops per CP shard. In the drop-free regime the
    result equals `moe_dispatch_combine` over the gathered tokens exactly.
    """
    t, e = probs.shape
    ep = jax.lax.psum(1, axis_name)
    if e % ep:
        raise ValueError(f"{e} experts not divisible by '{axis_name}' axis {ep}")
    e_local = e // ep
    start = jax.lax.axis_index(axis_name) * e_local

    sel, pos, keep = _dispatch_slots(probs, capacity)
    dispatch = jax.nn.one_hot(
        jnp.where(keep, pos, capacity), capacity, dtype=x.dtype
    )  # (T, E, C)
    xe = jnp.einsum("tec,td->ecd", dispatch, x)  # (E, C, D) — my tokens
    # ship: split the expert dim across members, concat received blocks
    # along the slot dim (source-member order) -> (E/ep, ep*C, D)
    xe = jax.lax.all_to_all(
        xe, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    ye = expert_fn(xe, start)  # (E/ep, ep*C, D) through MY experts
    # ship back: split the slot dim by destination member, concat along the
    # expert dim -> (E, C, D) with exactly my original slot layout
    ye = jax.lax.all_to_all(
        ye, axis_name, split_axis=1, concat_axis=0, tiled=True
    )
    combine = dispatch * probs[..., None].astype(x.dtype)
    return jnp.einsum("tec,ecd->td", combine, ye)


def ep_comm_elements(
    t_local: int, d: int, capacity: int, n_experts: int, ep: int
) -> dict[str, float]:
    """Per-member elements on the wire for one MoE layer's combine, for the
    two EP strategies (ring-collective model, one direction):

    * ``all_to_all``: two tiled all_to_alls of the (E, C, D) slot tensor —
      each ships (ep-1)/ep of it.
    * ``replicate_psum``: `moe_expert_sliced_combine`'s psum of the full
      (T_full, D) partial combine, T_full = ep * t_local (tokens are
      replicated across the axis), costing 2*(ep-1)/ep*T_full*D as a ring
      all-reduce (reduce-scatter + all-gather).

    Used by the dryrun/bench notes; ratios < 1 mean all_to_all moves less.
    """
    a2a = 2 * (ep - 1) / ep * n_experts * capacity * d
    psum = 2 * (ep - 1) / ep * (ep * t_local) * d
    return {
        "all_to_all": a2a,
        "replicate_psum": psum,
        "ratio": a2a / max(psum, 1.0),
    }


def moe_dense_combine(x: jax.Array, probs: jax.Array, expert_fn_all) -> jax.Array:
    """Drop-free reference path: run every expert on every token.

    `expert_fn_all((T, D)) -> (E, T, D)`. Exact semantics of the reference's
    per-expert loop; costs E/k times the dispatch path's FLOPs.
    """
    ye = expert_fn_all(x)  # (E, T, D)
    return jnp.einsum("te,etd->td", probs.astype(x.dtype), ye)
