"""Attention cores (pure-jnp reference path).

Covers every attention variant in the reference from one implementation:
  * vanilla causal MHA        (gpt/gpt-jax.ipynb cell 9)
  * GQA with repeat_kv        (llama3/LLaMA-jax.ipynb cells 18, 24)
  * MQA-grouped               (gemma/gemma.ipynb cell 8)
  * bidirectional encoder MHA (vision transformer/ViT.ipynb cell 10)
  * Luong dot-score attention (attention/luong.ipynb cell 1)

MLA (latent attention) lives with the DeepSeekV3 model (models/deepseekv3.py)
since its cache layout is model-specific. The Pallas flash-attention kernel
(kernels/flash_attention.py) is a drop-in replacement for
`dot_product_attention`; this module is the numerics reference for it.

Layout convention: (batch, seq, num_heads, head_dim) — "BSNH". This keeps
the sequence axis adjacent to batch for sequence sharding and lets XLA pick
MXU-friendly contractions via dot_general.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

BIG_NEG = -2.0**30  # mask fill; finite to keep softmax NaN-free in bf16/f32


def repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    """(B, S, n_kv, H) -> (B, S, n_kv * n_rep, H), repeating each kv head.

    Single shared impl of llama3/LLaMA-jax.ipynb cell 18.
    """
    if n_rep == 1:
        return x
    b, s, n_kv, h = x.shape
    x = jnp.broadcast_to(x[:, :, :, None, :], (b, s, n_kv, n_rep, h))
    return x.reshape(b, s, n_kv * n_rep, h)


def causal_mask(q_len: int, kv_len: int, dtype: jnp.dtype = jnp.bool_) -> jax.Array:
    """(q_len, kv_len) lower-triangular mask aligned to the *end* of the kv axis.

    With kv_len > q_len (cached decode), query i attends to kv positions
    [0, kv_len - q_len + i].
    """
    q_idx = jnp.arange(q_len)[:, None]
    kv_idx = jnp.arange(kv_len)[None, :]
    return (kv_idx <= q_idx + (kv_len - q_len)).astype(dtype)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: jax.Array | None = None,
    *,
    causal: bool = False,
    scale: float | None = None,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
) -> jax.Array:
    """Scaled dot-product attention over BSNH tensors.

    q: (B, Sq, N, H); k, v: (B, Skv, Nkv, H) with N % Nkv == 0 (GQA/MQA
    handled by repeating kv heads). `mask` is broadcastable to
    (B, N, Sq, Skv), True = attend. Softmax is computed in float32.
    """
    n, n_kv = q.shape[-2], k.shape[-2]
    if n != n_kv:
        if n % n_kv:
            raise ValueError(f"num q heads {n} not a multiple of kv heads {n_kv}")
        k = repeat_kv(k, n // n_kv)
        v = repeat_kv(v, n // n_kv)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # (B, N, Sq, Skv)
    scores = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32) * scale
    if causal:
        cmask = causal_mask(q.shape[1], k.shape[1])
        mask = cmask if mask is None else jnp.logical_and(mask, cmask)
    if mask is not None:
        scores = jnp.where(mask, scores, BIG_NEG)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0 and not deterministic:
        if dropout_rng is None:
            raise ValueError("dropout_rng is required when dropout is active")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep / (1.0 - dropout_rate)
    probs = probs.astype(v.dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


def luong_attention(
    decoder_state: jax.Array, encoder_states: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Luong global (dot-score) attention — attention/luong.ipynb cell 1.

    decoder_state:  (B, D)        current decoder hidden state
    encoder_states: (B, T, D)     encoder outputs over source time
    Returns (context (B, D), weights (B, T)).
    """
    scores = jnp.einsum("bd,btd->bt", decoder_state, encoder_states)
    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
        encoder_states.dtype
    )
    context = jnp.einsum("bt,btd->bd", weights, encoder_states)
    return context, weights
