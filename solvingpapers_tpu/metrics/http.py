"""Live status endpoint: /healthz, /metrics, /statusz over stdlib HTTP.

A running engine (serve or train) is otherwise a black box unless a
tracer was attached before launch; this module gives it the vLLM-style
first-line inspection surface with zero dependencies:

    /healthz   200 "ok" while the server thread is alive (the probe a
               load balancer or CI smoke polls)
    /metrics   the current metric snapshot in Prometheus text exposition
               format — the exact same rendering `PrometheusTextWriter`
               writes to textfiles (`PrometheusTextWriter.render`), so
               names and dedupe rules cannot drift between the pull and
               push paths
    /statusz   one JSON document: engine snapshot, slot occupancy,
               compile registry, memory ledger, mesh observatory
               (collective ledger + pipeline-bubble report) — whatever
               the owner's `statusz_fn` assembles

`StatusServer` is a `ThreadingHTTPServer` on a daemon thread bound to
127.0.0.1 by default (inspection surface, not an API — front it with a
real proxy to expose it). Providers are zero-arg callables resolved per
request, so responses always reflect live state; a provider that raises
returns a 500 with the error text instead of killing the serving loop.
Opt-in via `ServeConfig.status_port` / `TrainConfig.status_port`
(port 0 binds an ephemeral port, published as `server.port`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from solvingpapers_tpu.metrics.writer import PrometheusTextWriter


class StatusServer:
    """Serve /healthz, /metrics, /statusz from live provider callables.

    `statusz_fn() -> dict` builds the JSON status document;
    `metrics_fn() -> (step, {name: value})` feeds the Prometheus text
    rendering. Both run on the request thread — keep them snapshot-cheap
    (the engines' providers read host-side mirrors, never the device).
    """

    def __init__(
        self,
        statusz_fn: Callable[[], dict],
        metrics_fn: Callable[[], tuple[int, dict]],
        host: str = "127.0.0.1",
        port: int = 0,
        prefix: str = "",
    ):
        self.statusz_fn = statusz_fn
        self.metrics_fn = metrics_fn
        self.prefix = prefix
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 — silence
                pass  # per-request stderr spam would drown engine logs

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send(200, "ok\n", "text/plain")
                    elif path == "/metrics":
                        step, metrics = server.metrics_fn()
                        self._send(
                            200,
                            PrometheusTextWriter.render(
                                step, metrics, prefix=server.prefix
                            ),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/statusz":
                        self._send(
                            200,
                            json.dumps(server.statusz_fn(), default=str)
                            + "\n",
                            "application/json",
                        )
                    else:
                        self._send(
                            404,
                            "not found — try /healthz, /metrics, "
                            "/statusz\n",
                            "text/plain",
                        )
                except BrokenPipeError:  # client went away mid-write
                    pass
                except Exception as e:  # noqa: BLE001 — a bad provider
                    # must answer 500, not kill the handler thread
                    try:
                        self._send(500, f"{type(e).__name__}: {e}\n",
                                   "text/plain")
                    except BrokenPipeError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="statusz", daemon=True
        )
        self._thread.start()

    def url(self, path: str = "/statusz") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        """Stop serving and release the port (idempotent)."""
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._httpd = None
