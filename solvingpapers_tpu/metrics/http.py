"""Live status endpoint: /healthz, /metrics, /statusz over stdlib HTTP.

A running engine (serve or train) is otherwise a black box unless a
tracer was attached before launch; this module gives it the vLLM-style
first-line inspection surface with zero dependencies:

    /healthz   200 "ok" while the owner is healthy (the probe a load
               balancer or CI smoke polls); with a `health_fn` bound,
               200 "degraded" on a degradation-ladder rung and 503
               "unhealthy" while the engine drains after persistent
               failures
    /metrics   the current metric snapshot in Prometheus text exposition
               format — the exact same rendering `PrometheusTextWriter`
               writes to textfiles (`PrometheusTextWriter.render`), so
               names and dedupe rules cannot drift between the pull and
               push paths
    /statusz   one JSON document: engine snapshot, slot occupancy,
               health state machine (fault plan + degradation ladder),
               write-ahead journal (records/bytes/fsyncs, live set,
               recovered_requests — present iff journaled), compile
               registry, memory ledger, mesh observatory (collective
               ledger + pipeline-bubble report) — whatever the owner's
               `statusz_fn` assembles
    /timeseriesz  the rolling in-process time-series ring
               (metrics/timeseries.TimeSeriesStore.doc()) as JSON —
               present iff the owner bound a `timeseries_fn`, 404
               otherwise

`StatusServer` is a `ThreadingHTTPServer` on a daemon thread bound to
127.0.0.1 by default (inspection surface, not an API — front it with a
real proxy to expose it). Providers are zero-arg callables resolved per
request, so responses always reflect live state; a provider that raises
returns a 500 with the error text instead of killing the serving loop.
Opt-in via `ServeConfig.status_port` / `TrainConfig.status_port`
(port 0 binds an ephemeral port, published as `server.port`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from solvingpapers_tpu.metrics.writer import PrometheusTextWriter


def healthz_response(state: str) -> tuple[int, str]:
    """ONE mapping from the engine health state machine to the /healthz
    wire contract, shared by this status-port server and the OpenAI
    front door (serve/api.py) so the two endpoints can never diverge:
    ``unhealthy`` -> 503 (a load balancer must drop the replica),
    ``degraded`` -> 200 "degraded" (keep it — still serving, just
    shedding load), anything else -> 200 "ok"."""
    if state == "unhealthy":
        return 503, "unhealthy\n"
    if state == "degraded":
        return 200, "degraded\n"
    return 200, "ok\n"


class StatusServer:
    """Serve /healthz, /metrics, /statusz from live provider callables.

    `statusz_fn() -> dict` builds the JSON status document;
    `metrics_fn() -> (step, {name: value})` feeds the Prometheus text
    rendering. Both run on the request thread — keep them snapshot-cheap
    (the engines' providers read host-side mirrors, never the device).
    """

    def __init__(
        self,
        statusz_fn: Callable[[], dict],
        metrics_fn: Callable[[], tuple[int, dict]],
        host: str = "127.0.0.1",
        port: int = 0,
        prefix: str = "",
        health_fn: Callable[[], str] | None = None,
        timeseries_fn: Callable[[], dict] | None = None,
    ):
        self.statusz_fn = statusz_fn
        self.metrics_fn = metrics_fn
        self.prefix = prefix
        # timeseries_fn() -> TimeSeriesStore.doc(): the rolling
        # retrospective served as /timeseriesz JSON; None (an owner
        # without a store) keeps the endpoint a 404
        self.timeseries_fn = timeseries_fn
        # health_fn() -> "healthy" | "degraded" | "unhealthy": /healthz
        # answers 503 for "unhealthy" (a draining engine must fall out
        # of its load balancer), 200 otherwise — "degraded" keeps the
        # replica in rotation but names its state in the body. None
        # keeps the historical always-200 "ok".
        self.health_fn = health_fn
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 — silence
                pass  # per-request stderr spam would drown engine logs

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        state = ("healthy" if server.health_fn is None
                                 else server.health_fn())
                        code, body = healthz_response(state)
                        self._send(code, body, "text/plain")
                    elif path == "/metrics":
                        step, metrics = server.metrics_fn()
                        self._send(
                            200,
                            PrometheusTextWriter.render(
                                step, metrics, prefix=server.prefix
                            ),
                            "text/plain; version=0.0.4",
                        )
                    elif path == "/statusz":
                        self._send(
                            200,
                            json.dumps(server.statusz_fn(), default=str)
                            + "\n",
                            "application/json",
                        )
                    elif path == "/timeseriesz":
                        if server.timeseries_fn is None:
                            self._send(
                                404,
                                "no time-series store (run with "
                                "timeseries enabled)\n",
                                "text/plain",
                            )
                        else:
                            self._send(
                                200,
                                json.dumps(server.timeseries_fn(),
                                           default=str) + "\n",
                                "application/json",
                            )
                    else:
                        self._send(
                            404,
                            "not found — try /healthz, /metrics, "
                            "/statusz, /timeseriesz\n",
                            "text/plain",
                        )
                except BrokenPipeError:  # client went away mid-write
                    pass
                except Exception as e:  # noqa: BLE001 — a bad provider
                    # must answer 500, not kill the handler thread
                    try:
                        self._send(500, f"{type(e).__name__}: {e}\n",
                                   "text/plain")
                    except BrokenPipeError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="statusz", daemon=True
        )
        self._thread.start()

    def url(self, path: str = "/statusz") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        """Stop serving and release the port (idempotent)."""
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        self._httpd = None
